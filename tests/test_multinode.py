"""Two-node cluster over real HTTP transport + janitor lifecycle."""

import http.client
import json
import time

import pytest

from quickwit_tpu.cluster.membership import ClusterChange, ClusterMember
from quickwit_tpu.janitor import apply_retention, run_garbage_collection
from quickwit_tpu.metastore.base import ListSplitsQuery
from quickwit_tpu.models.split_metadata import SplitState
from quickwit_tpu.serve import Node, NodeConfig, RestServer
from quickwit_tpu.serve.http_client import HttpSearchClient, HttpTransportError
from quickwit_tpu.storage import StorageResolver

INDEX_CONFIG = {
    "index_id": "mn-logs",
    "doc_mapping": {
        "field_mappings": [
            {"name": "ts", "type": "datetime", "fast": True,
             "input_formats": ["unix_timestamp"]},
            {"name": "body", "type": "text"},
        ],
        "timestamp_field": "ts",
        "default_search_fields": ["body"],
    },
    "indexing_settings": {"split_num_docs_target": 50},
}


def rest(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    conn.request(method, path, body=data)
    response = conn.getresponse()
    payload = response.read()
    conn.close()
    return response.status, (json.loads(payload) if payload else None)


@pytest.fixture(scope="module")
def two_nodes():
    # shared storage resolver = shared object storage + shared metastore files
    resolver = StorageResolver.for_test()
    nodes, servers = [], []
    for i in range(2):
        node = Node(NodeConfig(node_id=f"mn-{i}", rest_port=0,
                               metastore_uri="ram:///mn/metastore",
                               default_index_root_uri="ram:///mn/indexes"),
                    storage_resolver=resolver)
        server = RestServer(node)
        server.start()
        nodes.append(node)
        servers.append(server)
    # mutual membership via heartbeat (the gossip join)
    for i, node in enumerate(nodes):
        peer = servers[1 - i]
        client = HttpSearchClient(peer.endpoint)
        client.heartbeat({"node_id": node.config.node_id,
                          "roles": list(node.config.roles),
                          "rest_endpoint": servers[i].endpoint})
    yield nodes, servers
    for server in servers:
        server.stop()


def test_cross_node_search(two_nodes):
    nodes, servers = two_nodes
    port0, port1 = servers[0].port, servers[1].port
    status, _ = rest(port0, "POST", "/api/v1/indexes", INDEX_CONFIG)
    assert status == 200
    docs = "\n".join(json.dumps({"ts": 1_600_000_000 + i, "body": f"doc {i} shared"})
                     for i in range(200)).encode()
    status, result = rest(port0, "POST", "/api/v1/mn-logs/ingest", docs)
    assert status == 200 and result["num_ingested_docs"] == 200

    # both nodes know each other
    status, cluster = rest(port0, "GET", "/api/v1/cluster")
    assert {m["node_id"] for m in cluster["members"]} == {"mn-0", "mn-1"}

    # searching via node 1 works even though node 0 ingested; with 2 searcher
    # nodes, the placer fans splits across BOTH (4 splits of 50 docs)
    status, result = rest(port1, "GET", "/api/v1/mn-logs/search?query=shared&max_hits=5")
    assert status == 200
    assert result["num_hits"] == 200

    # node-level caches: both nodes hold readers now; a repeat query hits them
    status, result = rest(port1, "GET", "/api/v1/mn-logs/search?query=shared&max_hits=5")
    assert status == 200 and result["num_hits"] == 200


def test_dead_node_failover(two_nodes):
    nodes, servers = two_nodes
    port0 = servers[0].port
    # kill node 1's server; node 0 should still answer by retrying on itself
    servers[1].stop()
    # mark node 1 dead via heartbeat age
    member = nodes[0].cluster.member("mn-1")
    member.last_heartbeat = time.monotonic() - 1000
    status, result = rest(port0, "GET", "/api/v1/mn-logs/search?query=shared&max_hits=3")
    assert status == 200
    assert result["num_hits"] == 200


def test_http_client_error_surface():
    client = HttpSearchClient("127.0.0.1:1")  # nothing listens
    with pytest.raises(HttpTransportError):
        client.heartbeat({"node_id": "x", "roles": []})


def test_janitor_gc_and_retention(two_nodes):
    nodes, _ = two_nodes
    node = nodes[0]
    metadata = node.metastore.index_metadata("mn-logs")
    uid = metadata.index_uid
    storage = node.storage_resolver.resolve(metadata.index_config.index_uri)

    published = node.metastore.list_splits(
        ListSplitsQuery(index_uids=[uid], states=[SplitState.PUBLISHED]))
    victim = published[0].metadata.split_id
    node.metastore.mark_splits_for_deletion(uid, [victim])
    # too young: grace period protects it
    stats = run_garbage_collection(node.metastore, node.storage_resolver)
    assert stats["gc_deleted_splits"] == 0
    # pretend time passed
    stats = run_garbage_collection(node.metastore, node.storage_resolver,
                                   now=time.time() + 10_000)
    assert stats["gc_deleted_splits"] == 1
    assert not storage.exists(f"{victim}.split")

    # retention: a policy of 1 hour expires everything (docs are from
    # 2020). The policy must be PERSISTED — apply_retention re-reads
    # metastore state (the janitor's forced refresh drops cached objects)
    from quickwit_tpu.models.index_metadata import RetentionPolicy
    node.metastore.update_retention_policy(
        uid, RetentionPolicy(period_seconds=3600))
    stats = apply_retention(node.metastore)
    remaining = node.metastore.list_splits(
        ListSplitsQuery(index_uids=[uid], states=[SplitState.PUBLISHED]))
    assert stats["retention_marked_splits"] > 0
    assert remaining == []


def test_two_node_wal_ingest_no_checkpoint_collision(tmp_path):
    """Both nodes take WAL ingests for the SAME index and drain their own
    local WALs into the shared metastore: node-prefixed shard ids keep the
    source-checkpoint partitions disjoint, so neither drain is rejected as
    a replay and no docs are lost."""
    resolver = StorageResolver.for_test()
    nodes = []
    for i in range(2):
        nodes.append(Node(NodeConfig(node_id=f"walmn-{i}", rest_port=0,
                                     metastore_uri="ram:///walmn/metastore",
                                     default_index_root_uri="ram:///walmn/indexes",
                                     data_dir=str(tmp_path / f"n{i}"),
                                     wal_fsync=False),
                          storage_resolver=resolver))
    nodes[0].index_service.create_index(INDEX_CONFIG)
    nodes[0].ingest_v2("mn-logs", [{"ts": 1_600_000_000 + i,
                                    "body": f"walmn from zero {i}"}
                                   for i in range(30)])
    nodes[1].ingest_v2("mn-logs", [{"ts": 1_600_000_100 + i,
                                    "body": f"walmn from one {i}"}
                                   for i in range(20)])
    assert nodes[0].run_ingest_pass("mn-logs")["num_docs_indexed"] == 30
    # node1's cached metastore state predates node0's publish: the first
    # attempt may fail the optimistic version check (instead of silently
    # erasing node0's splits); the background loop's retry then succeeds
    # off the refreshed state — model that here.
    from quickwit_tpu.metastore import MetastoreError
    try:
        stats = nodes[1].run_ingest_pass("mn-logs")
    except MetastoreError as exc:
        assert exc.kind == "failed_precondition"
        stats = nodes[1].run_ingest_pass("mn-logs")
    assert stats["num_docs_indexed"] == 20

    from quickwit_tpu.query import parse_query_string
    from quickwit_tpu.search.models import SearchRequest
    request = SearchRequest(index_ids=["mn-logs"],
                            query_ast=parse_query_string("walmn", ["body"]),
                            max_hits=5)
    # node1 just wrote, so its metastore cache is current; node0 converges
    # after its polling TTL (covered by test_polling_refresh_sees_other_writers)
    assert nodes[1].root_searcher.search(request).num_hits == 50
    # checkpoint holds one partition per node-prefixed shard (read through
    # node1, whose cache reflects the last write; node0's is TTL-stale)
    uid = nodes[1].metastore.index_metadata("mn-logs").index_uid
    checkpoint = nodes[1].metastore.source_checkpoint(uid, "_ingest-source")
    partitions = set(checkpoint.positions)
    assert any(p.startswith("walmn-0-") for p in partitions)
    assert any(p.startswith("walmn-1-") for p in partitions)


def test_wildcard_bind_address_not_advertised():
    """A node bound to 0.0.0.0 must not poison peers' membership tables
    with an unroutable endpoint: the transport substitutes the address
    the peer was actually reached at."""
    from quickwit_tpu.cluster.membership import substitute_wildcard_host
    assert substitute_wildcard_host("0.0.0.0:7280", "10.0.0.5") == "10.0.0.5:7280"
    assert substitute_wildcard_host(":::7280", "10.0.0.5") == "10.0.0.5:7280"
    assert substitute_wildcard_host("192.168.1.2:7280", "10.0.0.5") \
        == "192.168.1.2:7280"
    assert substitute_wildcard_host("", "10.0.0.5") == ""
    assert substitute_wildcard_host("0.0.0.0:7280", "") == "0.0.0.0:7280"


def test_tls_rest_and_peer_transport(tmp_path):
    """TLS on the REST listener (server cert/key) with the peer client
    verifying against a pinned CA — heartbeat + search over HTTPS."""
    import shutil
    import ssl
    import subprocess
    import urllib.request

    if shutil.which("openssl") is None:
        pytest.skip("openssl unavailable")
    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True)

    from quickwit_tpu.serve import NodeConfig
    resolver = StorageResolver.for_test()
    node = Node(NodeConfig(node_id="tls-node", rest_port=0,
                           metastore_uri="ram:///tls/metastore",
                           default_index_root_uri="ram:///tls/indexes",
                           tls_cert_path=str(cert), tls_key_path=str(key),
                           tls_ca_path=str(cert)),
                storage_resolver=resolver)
    server = RestServer(node)
    server.start()
    try:
        context = ssl.create_default_context(cafile=str(cert))
        with urllib.request.urlopen(
                f"https://127.0.0.1:{server.port}/api/v1/cluster",
                context=context, timeout=10) as response:
            cluster = json.loads(response.read())
        assert cluster["node_id"] == "tls-node"
        # the peer transport speaks HTTPS with the pinned CA
        client = HttpSearchClient(f"127.0.0.1:{server.port}",
                                  **node.config.client_tls_kwargs())
        info = client.heartbeat({"node_id": "probe", "roles": ["searcher"],
                                 "rest_endpoint": "127.0.0.1:9"})
        assert info["node_id"] == "tls-node"
        # a plain-HTTP client is rejected at the TLS layer
        plain = HttpSearchClient(f"127.0.0.1:{server.port}")
        with pytest.raises(HttpTransportError):
            plain.heartbeat({"node_id": "x", "roles": []})
    finally:
        server.stop()


def test_scroll_survives_node_restart_via_replica(two_nodes):
    """Scroll contexts replicate to the best-affinity peer (reference
    put_kv, scroll_context.rs:146): losing the serving node's in-memory
    store no longer kills live scrolls."""
    nodes, servers = two_nodes
    # test_dead_node_failover stopped node 1's server for good: bring a
    # fresh listener up for it, then refresh liveness (the module fixture
    # heartbeats only once at setup)
    replacement = RestServer(nodes[1], host="127.0.0.1", port=0)
    replacement.start()
    servers = [servers[0], replacement]
    for i, node in enumerate(nodes):
        node.cluster.upsert_heartbeat(ClusterMember(
            node_id=f"mn-{1 - i}",
            roles=("searcher", "indexer", "metastore"),
            rest_endpoint=f"127.0.0.1:{servers[1 - i].port}"))
    nodes[0].clients.pop("mn-1", None)  # re-resolve at the new port
    nodes[0]._on_cluster_change(ClusterChange("update", ClusterMember(
        "mn-1", ("searcher", "indexer", "metastore"),
        rest_endpoint=f"127.0.0.1:{replacement.port}")))
    status, _ = rest(servers[0].port, "POST", "/api/v1/indexes", {
        **INDEX_CONFIG, "index_id": "scr-logs"})
    assert status == 200
    docs = "\n".join(json.dumps({"ts": 1_700_000_000 + i,
                                 "body": f"scroll doc {i}"})
                     for i in range(40)).encode()
    status, _ = rest(servers[0].port, "POST",
                     "/api/v1/scr-logs/ingest?commit=force", docs)
    assert status == 200

    status, page1 = rest(servers[0].port, "GET",
                         "/api/v1/scr-logs/search?query=*&max_hits=10"
                         "&scroll=1m")
    assert status == 200 and len(page1["hits"]) == 10
    scroll_id = page1["scroll_id"]

    # simulate the serving node losing its in-memory contexts (restart)
    nodes[0].scroll_store._contexts.clear()

    # the next page recovers from the affinity replica on the peer
    status, page2 = rest(servers[0].port, "GET",
                         f"/api/v1/scroll?scroll_id={scroll_id}")
    assert status == 200, page2
    assert len(page2["hits"]) == 10
    ids1 = {json.dumps(h, sort_keys=True) for h in page1["hits"]}
    ids2 = {json.dumps(h, sort_keys=True) for h in page2["hits"]}
    assert not ids1 & ids2  # disjoint pages: the cursor replicated too


def test_mtls_requires_client_certificate(tmp_path):
    """mTLS (reference quickwit-transport validate_client): the listener
    rejects TLS clients without a CA-signed client certificate; peers
    presenting the node cert connect."""
    import shutil
    import subprocess

    if shutil.which("openssl") is None:
        pytest.skip("openssl unavailable")
    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True)
    node = Node(NodeConfig(node_id="mtls-node", rest_port=0,
                           metastore_uri="ram:///mtls/metastore",
                           default_index_root_uri="ram:///mtls/indexes",
                           tls_cert_path=str(cert), tls_key_path=str(key),
                           tls_ca_path=str(cert), tls_verify_client=True),
                storage_resolver=StorageResolver.for_test())
    server = RestServer(node)
    server.start()
    try:
        # no client cert: the handshake is refused
        bare = HttpSearchClient(f"127.0.0.1:{server.port}", tls=True,
                                ca_path=str(cert))
        with pytest.raises(HttpTransportError):
            bare.heartbeat({"node_id": "x", "roles": []})
        # with the cluster cert as client identity: accepted
        client = HttpSearchClient(f"127.0.0.1:{server.port}",
                                  **node.config.client_tls_kwargs())
        info = client.heartbeat({"node_id": "probe", "roles": ["searcher"],
                                 "rest_endpoint": "127.0.0.1:9"})
        assert info["node_id"] == "mtls-node"
    finally:
        server.stop()
