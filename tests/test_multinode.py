"""Two-node cluster over real HTTP transport + janitor lifecycle."""

import http.client
import json
import time

import pytest

from quickwit_tpu.cluster.membership import ClusterMember
from quickwit_tpu.janitor import apply_retention, run_garbage_collection
from quickwit_tpu.metastore.base import ListSplitsQuery
from quickwit_tpu.models.split_metadata import SplitState
from quickwit_tpu.serve import Node, NodeConfig, RestServer
from quickwit_tpu.serve.http_client import HttpSearchClient, HttpTransportError
from quickwit_tpu.storage import StorageResolver

INDEX_CONFIG = {
    "index_id": "mn-logs",
    "doc_mapping": {
        "field_mappings": [
            {"name": "ts", "type": "datetime", "fast": True,
             "input_formats": ["unix_timestamp"]},
            {"name": "body", "type": "text"},
        ],
        "timestamp_field": "ts",
        "default_search_fields": ["body"],
    },
    "indexing_settings": {"split_num_docs_target": 50},
}


def rest(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    conn.request(method, path, body=data)
    response = conn.getresponse()
    payload = response.read()
    conn.close()
    return response.status, (json.loads(payload) if payload else None)


@pytest.fixture(scope="module")
def two_nodes():
    # shared storage resolver = shared object storage + shared metastore files
    resolver = StorageResolver.for_test()
    nodes, servers = [], []
    for i in range(2):
        node = Node(NodeConfig(node_id=f"mn-{i}", rest_port=0,
                               metastore_uri="ram:///mn/metastore",
                               default_index_root_uri="ram:///mn/indexes"),
                    storage_resolver=resolver)
        server = RestServer(node)
        server.start()
        nodes.append(node)
        servers.append(server)
    # mutual membership via heartbeat (the gossip join)
    for i, node in enumerate(nodes):
        peer = servers[1 - i]
        client = HttpSearchClient(peer.endpoint)
        client.heartbeat({"node_id": node.config.node_id,
                          "roles": list(node.config.roles),
                          "rest_endpoint": servers[i].endpoint})
    yield nodes, servers
    for server in servers:
        server.stop()


def test_cross_node_search(two_nodes):
    nodes, servers = two_nodes
    port0, port1 = servers[0].port, servers[1].port
    status, _ = rest(port0, "POST", "/api/v1/indexes", INDEX_CONFIG)
    assert status == 200
    docs = "\n".join(json.dumps({"ts": 1_600_000_000 + i, "body": f"doc {i} shared"})
                     for i in range(200)).encode()
    status, result = rest(port0, "POST", "/api/v1/mn-logs/ingest", docs)
    assert status == 200 and result["num_ingested_docs"] == 200

    # both nodes know each other
    status, cluster = rest(port0, "GET", "/api/v1/cluster")
    assert {m["node_id"] for m in cluster["members"]} == {"mn-0", "mn-1"}

    # searching via node 1 works even though node 0 ingested; with 2 searcher
    # nodes, the placer fans splits across BOTH (4 splits of 50 docs)
    status, result = rest(port1, "GET", "/api/v1/mn-logs/search?query=shared&max_hits=5")
    assert status == 200
    assert result["num_hits"] == 200

    # node-level caches: both nodes hold readers now; a repeat query hits them
    status, result = rest(port1, "GET", "/api/v1/mn-logs/search?query=shared&max_hits=5")
    assert status == 200 and result["num_hits"] == 200


def test_dead_node_failover(two_nodes):
    nodes, servers = two_nodes
    port0 = servers[0].port
    # kill node 1's server; node 0 should still answer by retrying on itself
    servers[1].stop()
    # mark node 1 dead via heartbeat age
    member = nodes[0].cluster.member("mn-1")
    member.last_heartbeat = time.monotonic() - 1000
    status, result = rest(port0, "GET", "/api/v1/mn-logs/search?query=shared&max_hits=3")
    assert status == 200
    assert result["num_hits"] == 200


def test_http_client_error_surface():
    client = HttpSearchClient("127.0.0.1:1")  # nothing listens
    with pytest.raises(HttpTransportError):
        client.heartbeat({"node_id": "x", "roles": []})


def test_janitor_gc_and_retention(two_nodes):
    nodes, _ = two_nodes
    node = nodes[0]
    metadata = node.metastore.index_metadata("mn-logs")
    uid = metadata.index_uid
    storage = node.storage_resolver.resolve(metadata.index_config.index_uri)

    published = node.metastore.list_splits(
        ListSplitsQuery(index_uids=[uid], states=[SplitState.PUBLISHED]))
    victim = published[0].metadata.split_id
    node.metastore.mark_splits_for_deletion(uid, [victim])
    # too young: grace period protects it
    stats = run_garbage_collection(node.metastore, node.storage_resolver)
    assert stats["gc_deleted_splits"] == 0
    # pretend time passed
    stats = run_garbage_collection(node.metastore, node.storage_resolver,
                                   now=time.time() + 10_000)
    assert stats["gc_deleted_splits"] == 1
    assert not storage.exists(f"{victim}.split")

    # retention: a policy of 1 hour expires everything (docs are from 2020)
    from quickwit_tpu.models.index_metadata import RetentionPolicy
    metadata.index_config.retention = RetentionPolicy(period_seconds=3600)
    stats = apply_retention(node.metastore)
    remaining = node.metastore.list_splits(
        ListSplitsQuery(index_uids=[uid], states=[SplitState.PUBLISHED]))
    assert stats["retention_marked_splits"] > 0
    assert remaining == []
