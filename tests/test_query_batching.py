"""Device-side multi-query batching (ROADMAP item 2): N DISTINCT
shape-compatible queries stack into ONE compiled dispatch along a query
axis, and every lane's results are bit-identical to running that query
solo — across sorts, ties, thresholds, search_after markers, aggs, and
all three split format versions. `QW_DISABLE_QBATCH=1` must restore the
convoy-only seed behavior byte for byte, and a rider shed AFTER group
formation must be masked (validity lane zeroed) without a second launch
or a recompile."""

import os
import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

import jax

from quickwit_tpu.common.deadline import (
    CancellationToken, CancelledQuery, cancel_scope,
)
from quickwit_tpu.common.uri import Uri
from quickwit_tpu.index import SplitReader, SplitWriter
from quickwit_tpu.models import DocMapper, FieldMapping, FieldType
from quickwit_tpu.observability.metrics import (
    QBATCH_GROUPS_TOTAL, QBATCH_INCOMPATIBLE_TOTAL,
    QBATCH_MASKED_RIDERS_TOTAL, QBATCH_QUERIES_PER_DISPATCH,
    QBATCH_SHARED_BYTES_AVOIDED_TOTAL, SEARCH_KERNEL_LAUNCHES_TOTAL,
)
from quickwit_tpu.observability.profile import (
    PHASE_BATCHER_QUEUE, PHASE_QBATCH_GROUP, QueryProfile, profile_scope,
)
from quickwit_tpu.query.ast import MatchAll, Range, RangeBound, Term
from quickwit_tpu.search import SearchRequest, SortField
from quickwit_tpu.search import chunkexec
from quickwit_tpu.search import executor as ex
from quickwit_tpu.search.batcher import (
    QueryBatcher, QueryGroupPlanner, _PriorityLock, qbatch_enabled,
)
from quickwit_tpu.search.leaf import prepare_single_split
from quickwit_tpu.storage import RamStorage

MAPPER = DocMapper(
    field_mappings=[
        FieldMapping("ts", FieldType.DATETIME, fast=True,
                     input_formats=("unix_timestamp",)),
        FieldMapping("sev", FieldType.TEXT, tokenizer="raw", fast=True),
        FieldMapping("tenant", FieldType.U64, fast=True),
        FieldMapping("lat", FieldType.F64, fast=True),
        FieldMapping("body", FieldType.TEXT),
    ],
    timestamp_field="ts", default_search_fields=("body",))

T0 = 1_600_000_000
SEVS = ("INFO", "WARN", "ERROR")


def _docs(n, seed):
    rng = np.random.RandomState(seed)
    for i in range(n):
        yield {
            "ts": T0 + i * 60,
            "sev": SEVS[int(rng.randint(0, 3))],
            "tenant": int(rng.randint(0, 4)),
            # integral latencies: float aggs stay exactly associative, so
            # solo-vs-stacked agg comparisons can demand bit equality
            "lat": float(rng.randint(1, 500)),
            "body": f"m{int(rng.randint(0, 4))}",
        }


@contextmanager
def _writer_env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _build_reader(n_docs, seed, name, env=None):
    with _writer_env(**(env or {})):
        writer = SplitWriter(MAPPER)
        for doc in _docs(n_docs, seed):
            writer.add_json_doc(doc)
        data = writer.finish()
    storage = RamStorage(Uri.parse("ram:///qbatch"))
    storage.put(name, data)
    return SplitReader(storage, name)


@pytest.fixture(scope="module")
def reader():
    return _build_reader(300, 11, "v3.split")


@pytest.fixture(scope="module")
def reader_v2():
    return _build_reader(300, 11, "v2.split", env={"QW_DISABLE_IMPACT": "1"})


@pytest.fixture(scope="module")
def reader_v1():
    return _build_reader(300, 11, "v1.split", env={"QW_DISABLE_PACKED": "1"})


@pytest.fixture(scope="module")
def big_reader():
    # large enough that posting chunking spans multiple chunks at a
    # forced span (the group-chunked equivalence tests); seed chosen so
    # all three severity posting lists pad to the same bucket (the
    # shape-compatibility invariant the planner would otherwise enforce)
    return _build_reader(3000, 7, "big.split")


def _prep(rdr, request, split_id="s"):
    plan, arrs, _ = prepare_single_split(request, MAPPER, rdr, split_id)
    return plan, arrs


def _sev_req(sev, **kw):
    return SearchRequest(index_ids=["t"], query_ast=Term("sev", sev), **kw)


def _window_req(lo_s, hi_s, **kw):
    return SearchRequest(
        index_ids=["t"],
        query_ast=Range("ts", lower=RangeBound(lo_s * 1_000_000, True),
                        upper=RangeBound(hi_s * 1_000_000, False)), **kw)


def _assert_same(got, want):
    """Bit-identity between a stacked lane's result dict and its solo
    twin: counts, hit addresses, both sort keys, scores, and every agg
    leaf."""
    assert got is not None and want is not None
    assert int(got["count"]) == int(want["count"])
    for f in ("doc_ids", "sort_values", "sort_values2", "scores"):
        np.testing.assert_array_equal(np.asarray(got[f]), np.asarray(want[f]),
                                      err_msg=f)
    got_aggs = jax.tree_util.tree_leaves(got["aggs"])
    want_aggs = jax.tree_util.tree_leaves(want["aggs"])
    assert len(got_aggs) == len(want_aggs)
    for a, b in zip(got_aggs, want_aggs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _stack_and_compare(prepped, k, valid=None):
    plans = [p for p, _ in prepped]
    arrays = [a for _, a in prepped]
    solos = [ex.execute_plan(p, k, a) for p, a in prepped]
    stacked = ex.readback_plan_stacked(
        ex.dispatch_plan_stacked(plans, k, arrays, valid=valid))
    assert len(stacked) == len(plans)
    for lane, (got, want) in enumerate(zip(stacked, solos)):
        if valid is not None and not valid[lane]:
            assert got is None
        else:
            _assert_same(got, want)
    return stacked, solos


# --- stacked executor: bit-identity across query shapes ---------------------

def test_stacked_matches_solo_score_sort(reader):
    prepped = [_prep(reader, _sev_req(s, max_hits=10)) for s in SEVS]
    _stack_and_compare(prepped, 10)


def test_stacked_matches_solo_column_sort_asc(reader):
    prepped = [_prep(reader, _window_req(T0 + 600 * i, T0 + 600 * i + 7200,
                                         max_hits=8,
                                         sort_fields=[SortField("ts", "asc")]))
               for i in range(3)]
    _stack_and_compare(prepped, 8)


def test_stacked_matches_solo_column_sort_desc(reader):
    prepped = [_prep(reader, _window_req(T0 + 600 * i, T0 + 600 * i + 7200,
                                         max_hits=8,
                                         sort_fields=[SortField("ts",
                                                                "desc")]))
               for i in range(3)]
    _stack_and_compare(prepped, 8)


def test_stacked_matches_solo_two_key_sort(reader):
    prepped = [_prep(reader, _sev_req(
        s, max_hits=10, sort_fields=[SortField("lat", "desc"),
                                     SortField("ts", "asc")]))
        for s in SEVS]
    _stack_and_compare(prepped, 10)


def test_stacked_tie_breaks_identical_to_solo(reader):
    """tenant has only 4 distinct values over 400 docs — a tenant sort is
    almost all ties, so identical doc_id order proves the stacked top-k's
    tie-breaks are bit-compatible with solo."""
    prepped = [_prep(reader, _window_req(
        T0, T0 + 60 * 400, max_hits=12,
        sort_fields=[SortField("tenant", "desc")]))
        for _ in range(2)] + [_prep(reader, _window_req(
            T0 + 6000, T0 + 60 * 400, max_hits=12,
            sort_fields=[SortField("tenant", "desc")]))]
    _stack_and_compare(prepped, 12)


def test_stacked_matches_solo_search_after(reader):
    """Each lane carries its OWN search_after marker (scalar lane vector):
    pagination cursors stay per-query inside one stacked dispatch."""
    sa = [[(T0 + 60 * (100 + 50 * i)) * 1_000_000, "s", 5 * i]
          for i in range(3)]
    prepped = [_prep(reader, _window_req(
        T0, T0 + 60 * 300, max_hits=6,
        sort_fields=[SortField("ts", "desc")], search_after=sa[i]))
        for i in range(3)]
    _stack_and_compare(prepped, 6)


def test_stacked_matches_solo_aggs(reader):
    aggs = {"per_hour": {
        "date_histogram": {"field": "ts", "fixed_interval": "1h"},
        "aggs": {"lat_avg": {"avg": {"field": "lat"}}}}}
    prepped = [_prep(reader, _sev_req(s, max_hits=5, aggs=aggs))
               for s in SEVS]
    _stack_and_compare(prepped, 5)


def test_stacked_matches_solo_count_only_k0(reader):
    prepped = [_prep(reader, _sev_req(s, max_hits=0,
                                      aggs={"lat_stats": {
                                          "stats": {"field": "lat"}}}))
               for s in SEVS]
    _stack_and_compare(prepped, 0)


def test_stacked_matches_solo_v2_split(reader_v2):
    prepped = [_prep(reader_v2, _sev_req(s, max_hits=10)) for s in SEVS]
    _stack_and_compare(prepped, 10)


def test_stacked_matches_solo_v1_split(reader_v1):
    prepped = [_prep(reader_v1, _sev_req(s, max_hits=10)) for s in SEVS]
    _stack_and_compare(prepped, 10)


# --- stacked executor: masking, bucketing, cache mirror ---------------------

def test_stacked_valid_mask_zeroes_lane_keeps_survivors(reader):
    prepped = [_prep(reader, _sev_req(s, max_hits=10)) for s in SEVS]
    _stack_and_compare(prepped, 10, valid=[True, False, True])


def test_stacked_lane_count_pads_to_bucket(reader):
    prepped = [_prep(reader, _sev_req(s, max_hits=5)) for s in SEVS]
    plans = [p for p, _ in prepped]
    stacked, _ = _stack_and_compare(prepped, 5)
    assert len(stacked) == 3          # surplus pad lanes never surface
    key = ex.stacked_program_cache_key(plans, 5)
    assert key[1] == 4                # 3 lanes bucket to the next pow2
    assert key in ex._STACKED_CACHE


def test_stacked_cache_key_mirror_in_lockstep(reader):
    """`stacked_program_cache_key` is the R1 closure mirror: after a
    dispatch, exactly that key must be present in the live cache."""
    prepped = [_prep(reader, _window_req(T0, T0 + 7200, max_hits=4)),
               _prep(reader, _window_req(T0 + 900, T0 + 9000, max_hits=4))]
    plans = [p for p, _ in prepped]
    ex.readback_plan_stacked(ex.dispatch_plan_stacked(
        plans, 4, [a for _, a in prepped]))
    assert ex.stacked_program_cache_key(plans, 4) in ex._STACKED_CACHE


def test_stacked_slot_split_shares_columns_stacks_postings(reader):
    """sev-term lanes read the same fast columns (shared slots, one
    broadcast buffer) but different posting lists (stacked slots)."""
    plans = [_prep(reader, _sev_req(s, max_hits=5))[0] for s in SEVS]
    shared, stacked = ex.stacked_slot_split(plans)
    assert shared and stacked
    assert sorted(shared + stacked) == list(range(len(plans[0].arrays)))
    keys0 = plans[0].array_keys
    for s in shared:
        assert all(p.array_keys[s] == keys0[s] for p in plans)
    for s in stacked:
        assert any(p.array_keys[s] != keys0[s] for p in plans)


def test_stacked_program_reused_across_groups(reader):
    """A second same-shape group is one launch, zero new compile-cache
    entries — the stacked program is keyed on structure + bucket, never on
    the queries riding it."""
    first = [_prep(reader, _sev_req(s, max_hits=7)) for s in SEVS]
    _stack_and_compare(first, 7)
    cache_size = len(ex._STACKED_CACHE)
    again = [_prep(reader, _sev_req(s, max_hits=7))
             for s in ("ERROR", "INFO", "WARN")]
    launches0 = SEARCH_KERNEL_LAUNCHES_TOTAL.get()
    stacked = ex.readback_plan_stacked(ex.dispatch_plan_stacked(
        [p for p, _ in again], 7, [a for _, a in again]))
    assert SEARCH_KERNEL_LAUNCHES_TOTAL.get() - launches0 == 1
    assert len(ex._STACKED_CACHE) == cache_size
    assert all(r is not None for r in stacked)


# --- grouping rules (QueryGroupPlanner) -------------------------------------

def test_group_key_stacks_distinct_terms_separates_structures(reader):
    plans = [_prep(reader, _sev_req(s, max_hits=5))[0] for s in SEVS]
    keys = {QueryGroupPlanner.key_for(p, 5, "s", True) for p in plans}
    assert len(keys) == 1             # distinct terms, one group
    other = _prep(reader, _window_req(T0, T0 + 7200, max_hits=5))[0]
    assert QueryGroupPlanner.key_for(other, 5, "s", True) not in keys
    # a different split never groups
    assert QueryGroupPlanner.key_for(plans[0], 5, "s2", True) not in keys


def test_group_key_kill_switch_restores_convoy_key(reader):
    """Under QW_DISABLE_QBATCH the key carries the array cache keys again:
    ERROR and INFO (different posting arrays) must NOT share."""
    plans = [_prep(reader, _sev_req(s, max_hits=5))[0]
             for s in ("ERROR", "INFO")]
    k_on = {QueryGroupPlanner.key_for(p, 5, "s", True) for p in plans}
    k_off = {QueryGroupPlanner.key_for(p, 5, "s", False) for p in plans}
    assert len(k_on) == 1 and len(k_off) == 2
    assert k_off == {(p.signature(5), tuple(p.array_keys), "s")
                     for p in plans}


def test_group_key_falls_back_without_structure_digest():
    class BarePlan:
        array_keys = ("x",)
        scalars = ()

        def signature(self, k):
            return ("bare", k)

    key = QueryGroupPlanner.key_for(BarePlan(), 3, "s", True)
    assert key == (("bare", 3), ("x",), "s")


def test_incompatible_metric_reasons(reader):
    plan = _prep(reader, _sev_req("ERROR", max_hits=5))[0]
    key = QueryGroupPlanner.key_for(plan, 5, "s", True)
    other = _prep(reader, _window_req(T0, T0 + 7200, max_hits=5))[0]
    other_key = QueryGroupPlanner.key_for(other, 5, "s", True)
    full0 = QBATCH_INCOMPATIBLE_TOTAL.get(reason="group_full")
    shape0 = QBATCH_INCOMPATIBLE_TOTAL.get(reason="plan_shape")
    # leading a fresh queue while the same key's queue is full
    QueryGroupPlanner.note_reject({key: [object()]}, key, True)
    assert QBATCH_INCOMPATIBLE_TOTAL.get(reason="group_full") == full0 + 1
    # leading a fresh queue while a different-shape group is open on the
    # same split
    QueryGroupPlanner.note_reject({other_key: [object()]}, key, True)
    assert QBATCH_INCOMPATIBLE_TOTAL.get(reason="plan_shape") == shape0 + 1
    # kill switch: no attribution at all
    QueryGroupPlanner.note_reject({key: [object()]}, key, False)
    assert QBATCH_INCOMPATIBLE_TOTAL.get(reason="group_full") == full0 + 1


def test_shared_staging_accounting(reader):
    from quickwit_tpu.search.residency import note_group_shared_staging
    plans = [_prep(reader, _sev_req(s, max_hits=5))[0] for s in SEVS]
    before = QBATCH_SHARED_BYTES_AVOIDED_TOTAL.get()
    saved = note_group_shared_staging(plans, 3)
    shared, _stacked = ex.stacked_slot_split(plans)
    expect = sum(plans[0].arrays[s].nbytes for s in shared) * 2
    assert saved == expect > 0
    assert QBATCH_SHARED_BYTES_AVOIDED_TOTAL.get() - before == expect
    # a lone lane shares with nobody
    assert note_group_shared_staging(plans, 1) == 0


# --- batcher integration: group formation, masking, kill switch -------------

def _run_group_through_batcher(batcher, prepped, k, cancel_idx=None,
                               profiles=None):
    """Deterministic group formation: hold the dispatch lock so riders
    pile into one queue, optionally cancel one AFTER it joined, then
    release and let the leader dispatch."""
    plans = [p for p, _ in prepped]
    key = batcher.planner.key_for(plans[0], k, "s", qbatch_enabled())
    assert all(batcher.planner.key_for(p, k, "s", qbatch_enabled()) == key
               for p in plans)
    entry = batcher._dispatch_locks.setdefault(key, [_PriorityLock(), 1])
    entry[0].acquire()
    results = [None] * len(prepped)
    tokens = [CancellationToken() for _ in prepped]

    def rider(i):
        plan, arrs = prepped[i]
        try:
            with cancel_scope(tokens[i]):
                if profiles is not None:
                    with profile_scope(profiles[i]):
                        results[i] = batcher.execute(plan, k, arrs,
                                                     split_key="s")
                else:
                    results[i] = batcher.execute(plan, k, arrs,
                                                 split_key="s")
        except Exception as exc:  # noqa: BLE001 - recorded for asserts
            results[i] = exc

    threads = [threading.Thread(target=rider, args=(i,), daemon=True)
               for i in range(len(prepped))]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 10.0
    while (len(batcher._queues.get(key, ())) < len(prepped)
           and time.monotonic() < deadline):
        time.sleep(0.005)
    assert len(batcher._queues.get(key, ())) == len(prepped)
    if cancel_idx is not None:
        tokens[cancel_idx].cancel("shed after group formation")
    entry[0].release()
    for t in threads:
        t.join(timeout=30.0)
    with batcher._lock:
        entry[1] -= 1
        if entry[1] <= 0:
            batcher._dispatch_locks.pop(key, None)
    return results


def test_batcher_groups_distinct_queries_into_one_launch(reader):
    prepped = [_prep(reader, _window_req(T0 + 600 * i, T0 + 600 * i + 9000,
                                         max_hits=6,
                                         sort_fields=[SortField("ts",
                                                                "desc")]))
               for i in range(3)]
    solos = [ex.execute_plan(p, 6, a) for p, a in prepped]
    batcher = QueryBatcher()
    groups0 = QBATCH_GROUPS_TOTAL.get()
    launches0 = SEARCH_KERNEL_LAUNCHES_TOTAL.get()
    results = _run_group_through_batcher(batcher, prepped, 6)
    assert SEARCH_KERNEL_LAUNCHES_TOTAL.get() - launches0 == 1
    assert QBATCH_GROUPS_TOTAL.get() - groups0 == 1
    for got, want in zip(results, solos):
        assert not isinstance(got, Exception)
        _assert_same(got, want)
    assert batcher.num_dispatches == 1 and batcher.num_queries == 3
    assert not batcher._dispatch_locks


def test_masked_rider_keeps_single_launch_and_survivors_exact(reader):
    """THE satellite regression: a rider cancelled after group formation
    but before launch is masked out (validity lane), not rebuilt around —
    launch count stays 1, no new compiled program, survivors bit-identical
    to solo, and the doomed rider gets a typed CancelledQuery."""
    prepped = [_prep(reader, _window_req(T0 + 600 * i, T0 + 600 * i + 9000,
                                         max_hits=6,
                                         sort_fields=[SortField("ts",
                                                                "desc")]))
               for i in range(3)]
    solos = [ex.execute_plan(p, 6, a) for p, a in prepped]
    # warm the stacked program for this exact shape+bucket so a recompile
    # (cache growth) below would be visible
    ex.readback_plan_stacked(ex.dispatch_plan_stacked(
        [p for p, _ in prepped], 6, [a for _, a in prepped]))
    cache_size = len(ex._STACKED_CACHE)
    batcher = QueryBatcher()
    launches0 = SEARCH_KERNEL_LAUNCHES_TOTAL.get()
    masked0 = QBATCH_MASKED_RIDERS_TOTAL.get()
    results = _run_group_through_batcher(batcher, prepped, 6, cancel_idx=1)
    assert SEARCH_KERNEL_LAUNCHES_TOTAL.get() - launches0 == 1
    assert len(ex._STACKED_CACHE) == cache_size
    assert QBATCH_MASKED_RIDERS_TOTAL.get() - masked0 == 1
    assert isinstance(results[1], CancelledQuery)
    _assert_same(results[0], solos[0])
    _assert_same(results[2], solos[2])
    assert batcher.num_dispatches == 1


def test_group_riders_get_group_wait_phase(reader):
    """Grouped riders' profiles attribute the formation wait to
    `qbatch_group_wait` (not the convoy's `batcher_queue`), so dashboards
    can separate stacking wait from convoy wait."""
    prepped = [_prep(reader, _sev_req(s, max_hits=5)) for s in SEVS]
    profiles = [QueryProfile(f"q{i}") for i in range(3)]
    batcher = QueryBatcher()
    results = _run_group_through_batcher(batcher, prepped, 5,
                                         profiles=profiles)
    assert not any(isinstance(r, Exception) for r in results)
    for prof in profiles:
        names = [p["name"] for p in prof.phases()]
        assert PHASE_QBATCH_GROUP in names
        assert PHASE_BATCHER_QUEUE not in names
        group = next(p for p in prof.phases()
                     if p["name"] == PHASE_QBATCH_GROUP)
        assert group["riders"] == 3


def test_queries_per_dispatch_histogram_observes_live_lanes(reader):
    prepped = [_prep(reader, _sev_req(s, max_hits=5)) for s in SEVS]
    before = QBATCH_QUERIES_PER_DISPATCH._totals.get((), 0)
    batcher = QueryBatcher()
    _run_group_through_batcher(batcher, prepped, 5)
    assert QBATCH_QUERIES_PER_DISPATCH._totals.get((), 0) == before + 1
    # the 3-lane group lands in the le=4 bucket
    assert QBATCH_QUERIES_PER_DISPATCH.percentile(0.5) <= 4.0


def test_kill_switch_restores_convoy_behavior(reader, monkeypatch):
    """QW_DISABLE_QBATCH: distinct-term queries lead separate queues
    (per-array keys), each dispatches alone, qbatch metrics stay silent,
    and results equal the stacking-on results bit for bit."""
    stacked_results = []
    batcher_on = QueryBatcher()
    for s in SEVS:
        plan, arrs = _prep(reader, _sev_req(s, max_hits=10))
        stacked_results.append(batcher_on.execute(plan, 10, arrs,
                                                  split_key="s"))
    monkeypatch.setenv("QW_DISABLE_QBATCH", "1")
    assert not qbatch_enabled()
    groups0 = QBATCH_GROUPS_TOTAL.get()
    batcher = QueryBatcher()
    for s, want in zip(SEVS, stacked_results):
        plan, arrs = _prep(reader, _sev_req(s, max_hits=10))
        got = batcher.execute(plan, 10, arrs, split_key="s")
        _assert_same(got, want)
    assert batcher.num_dispatches == batcher.num_queries == 3
    assert QBATCH_GROUPS_TOTAL.get() == groups0


def test_solo_rider_result_identical_on_and_off(reader, monkeypatch):
    """A lone query must be byte-identical with stacking on, with it off,
    and with no batcher at all — the kill switch changes routing, never
    results."""
    plan, arrs = _prep(reader, _sev_req("ERROR", max_hits=10))
    base = ex.execute_plan(plan, 10, arrs)
    on = QueryBatcher().execute(plan, 10, arrs, split_key="s")
    monkeypatch.setenv("QW_DISABLE_QBATCH", "1")
    off = QueryBatcher().execute(plan, 10, arrs, split_key="s")
    _assert_same(on, base)
    _assert_same(off, base)


# --- chunked group composition ----------------------------------------------

def test_group_chunked_matches_solo(big_reader):
    """The chunked stacked scan (carried state with a query dim, one
    stacked dispatch per chunk) returns the same results as each query's
    solo run."""
    prepped = [_prep(big_reader, _sev_req(s, max_hits=10)) for s in SEVS]
    plans = [p for p, _ in prepped]
    assert len({p.structure_digest(10) for p in plans}) == 1
    assert chunkexec.chunk_mode(plans[0]) is not None
    solos = [ex.execute_plan(p, 10, a) for p, a in prepped]
    results = chunkexec.execute_group_chunked(
        plans, 10, [a for _, a in prepped], span=256)
    assert results is not None
    for got, want in zip(results, solos):
        _assert_same(got, want)


def test_group_chunked_masks_and_cancels_lanes(big_reader):
    prepped = [_prep(big_reader, _sev_req(s, max_hits=10)) for s in SEVS]
    plans = [p for p, _ in prepped]
    solos = [ex.execute_plan(p, 10, a) for p, a in prepped]
    doomed = CancellationToken()
    doomed.cancel("lane cancelled before the scan")
    results = chunkexec.execute_group_chunked(
        plans, 10, [a for _, a in prepped],
        valid=[True, False, True],
        cancels=[doomed, None, None], span=256)
    assert results is not None
    assert results[1] is None                       # masked on entry
    lane0 = results[0]
    assert isinstance(lane0, CancelledQuery) or (
        isinstance(lane0, dict) and lane0.get("partial"))
    _assert_same(results[2], solos[2])


# --- fanout: the query axis over the splits x docs mesh ---------------------

def _batches(readers_keys, request_list, k):
    from quickwit_tpu.parallel import fanout
    rds, ids = readers_keys
    return [fanout.build_batch(req, MAPPER, rds, list(ids))
            for req in request_list], k


@pytest.fixture(scope="module")
def two_splits():
    return ([_build_reader(220, 3, "m1.split"),
             _build_reader(220, 7, "m2.split")], ["m1", "m2"])


def _response_key(resp):
    return (resp.num_hits,
            [(h.split_id, h.doc_id, h.sort_value, h.sort_value2)
             for h in resp.partial_hits],
            repr(sorted(resp.intermediate_aggs.items())))


def test_query_group_no_mesh_matches_solo_batches(two_splits):
    from quickwit_tpu.parallel import fanout
    reqs = [SearchRequest(index_ids=["t"], query_ast=Term("sev", s),
                          max_hits=8) for s in SEVS]
    batches, k = _batches(two_splits, reqs, 8)
    solos = [fanout.execute_batch(b, r) for b, r in zip(batches, reqs)]
    group = fanout.execute_query_group(batches, reqs[0])
    assert len(group) == 3
    for got, want in zip(group, solos):
        assert _response_key(got) == _response_key(want)


def test_query_group_mesh_matches_solo(two_splits):
    from quickwit_tpu.parallel import fanout
    mesh = fanout.make_mesh(2, 2)
    aggs = {"lat_stats": {"stats": {"field": "lat"}},
            "sevs": {"terms": {"field": "sev"}}}
    reqs = [SearchRequest(index_ids=["t"], query_ast=Term("sev", s),
                          max_hits=8, aggs=aggs,
                          sort_fields=[SortField("ts", "desc")])
            for s in SEVS]
    batches, k = _batches(two_splits, reqs, 8)
    solos = [fanout.execute_batch(b, r) for b, r in zip(batches, reqs)]
    group = fanout.execute_query_group(batches, reqs[0], mesh=mesh)
    for got, want in zip(group, solos):
        assert _response_key(got) == _response_key(want)
    key = fanout.group_cache_key(batches, 8, mesh=mesh)
    assert key in fanout._GROUP_JIT_CACHE


def test_query_group_mesh_masks_lanes(two_splits):
    from quickwit_tpu.parallel import fanout
    mesh = fanout.make_mesh(2, 1)
    reqs = [SearchRequest(index_ids=["t"], query_ast=Term("sev", s),
                          max_hits=8) for s in SEVS]
    batches, k = _batches(two_splits, reqs, 8)
    masked0 = QBATCH_MASKED_RIDERS_TOTAL.get()
    group = fanout.execute_query_group(batches, reqs[0], mesh=mesh,
                                       valid=[True, False, True])
    assert group[1] is None
    assert QBATCH_MASKED_RIDERS_TOTAL.get() - masked0 == 1
    solos = [fanout.execute_batch(b, r) for b, r in zip(batches, reqs)]
    assert _response_key(group[0]) == _response_key(solos[0])
    assert _response_key(group[2]) == _response_key(solos[2])


def test_query_group_mesh_validity_is_operand_not_key(two_splits):
    """Masking a lane must reuse the already-compiled group program — the
    validity mask is an operand, never part of the compile-cache key."""
    from quickwit_tpu.parallel import fanout
    mesh = fanout.make_mesh(2, 1)
    reqs = [SearchRequest(index_ids=["t"], query_ast=Term("sev", s),
                          max_hits=6) for s in SEVS]
    batches, k = _batches(two_splits, reqs, 6)
    fanout.execute_query_group(batches, reqs[0], mesh=mesh)
    cache_size = len(fanout._GROUP_JIT_CACHE)
    fanout.execute_query_group(batches, reqs[0], mesh=mesh,
                               valid=[False, True, True])
    assert len(fanout._GROUP_JIT_CACHE) == cache_size
