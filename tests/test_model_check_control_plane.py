"""Bounded model check of the control-plane convergence protocol.

Role of the reference's stateright models (`quickwit-dst/src/models/`):
exhaustive BFS over failure interleavings, driving the REAL
implementation — `Node.run_control_plane_pass` (leader election, plan,
concurrent poll, per-node diff apply), `apply_indexing_plan`,
`indexing_tasks_report`, `source_assignment_allows`, and the real
`IndexingScheduler` — with only the transport faked (direct method
calls that raise when the model cuts a link). Reference behavior
modeled: the singleton scheduler's apply/drift loop
(`control_plane/src/indexing_scheduler/mod.rs:111,360` +
`indexing_service.rs:1152`).

Actions: leader pass, leader death + revival, indexer process restart
(in-memory plan loss), network partition + heal. From EVERY reachable
state the protocol must re-converge once the network is quiet:
repeated passes reach drift=False, and then each external source has
EXACTLY ONE consumer among alive nodes (the single-consumer rule the
plan gating exists to enforce), with gating live on every alive node.
"""

import time

import pytest

from quickwit_tpu.cluster.membership import ClusterMember
from quickwit_tpu.models.index_metadata import SourceConfig
from quickwit_tpu.serve import Node, NodeConfig
from quickwit_tpu.storage import StorageResolver

NODE_IDS = ("m0", "m1", "m2")
SOURCES = ("file-0", "file-1")
MAX_DEPTH = 6
CONVERGE_PASSES = 4


class FakeClient:
    """The wire, minus the wire: routes the two control-plane RPCs
    straight to the peer object; raises when the model partitioned or
    killed the peer (exactly what a socket would do)."""

    def __init__(self, world, peer_id):
        self.world = world
        self.peer_id = peer_id

    def _post(self, path, body):
        if self.peer_id in self.world.dead or \
                self.peer_id in self.world.cut:
            raise ConnectionError(f"{self.peer_id} unreachable")
        peer = self.world.nodes[self.peer_id]
        if path == "/internal/indexing_tasks":
            return peer.indexing_tasks_report()
        if path == "/internal/apply_indexing_plan":
            return peer.apply_indexing_plan(body.get("tasks", []))
        raise AssertionError(f"unexpected RPC {path}")


class World:
    """One materialization: three all-role nodes sharing a metastore,
    one index with two external sources."""

    def __init__(self):
        self.resolver = StorageResolver.for_test()
        self.nodes = {}
        self.dead: set[str] = set()
        self.cut: set[str] = set()
        for node_id in NODE_IDS:
            self.nodes[node_id] = Node(
                NodeConfig(node_id=node_id, rest_port=0,
                           metastore_uri="ram:///mc/ms",
                           default_index_root_uri="ram:///mc/idx"),
                storage_resolver=self.resolver)
        for node in self.nodes.values():
            for peer_id, peer in self.nodes.items():
                if peer_id != node.config.node_id:
                    node.cluster.upsert_heartbeat(ClusterMember(
                        peer_id, tuple(peer.config.roles)))
                    node.clients[peer_id] = FakeClient(self, peer_id)
        first = self.nodes["m0"]
        first.index_service.create_index({
            "index_id": "mc", "doc_mapping": {"field_mappings": [
                {"name": "body", "type": "text"}]}})
        self.uid = first.metastore.index_metadata("mc").index_uid
        for source_id in SOURCES:
            first.metastore.add_source(self.uid, SourceConfig(
                source_id, "file", params={"filepath": "/dev/null"}))

    # --- model actions ----------------------------------------------------
    def alive(self):
        return [n for n in NODE_IDS if n not in self.dead]

    def leader_id(self):
        return min(self.alive())

    def set_liveness(self, node_id, alive):
        stamp = time.monotonic() - (0 if alive else 10_000)
        for node in self.nodes.values():
            member = node.cluster.member(node_id)
            if member is not None:
                member.last_heartbeat = stamp
                member.intervals.clear()

    def apply(self, action):
        if action == "pass":
            self.nodes[self.leader_id()].run_control_plane_pass()
        elif action == "kill-0":
            self.dead.add("m0")
            self.set_liveness("m0", False)
        elif action == "revive-0":
            self.dead.discard("m0")
            self.set_liveness("m0", True)
        elif action == "restart-1":
            # process restart: the in-memory plan is gone
            node = self.nodes["m1"]
            node._applied_indexing_tasks = None
            node._assigned_sources = set()
        elif action == "cut-1":
            self.cut.add("m1")
        elif action == "heal-1":
            self.cut.discard("m1")
        else:
            raise AssertionError(action)

    def enabled(self, action):
        if action == "pass":
            return True
        if action == "kill-0":
            return "m0" not in self.dead
        if action == "revive-0":
            return "m0" in self.dead
        if action == "restart-1":
            return "m1" not in self.dead
        if action == "cut-1":
            return "m1" not in self.cut and "m1" not in self.dead
        if action == "heal-1":
            return "m1" in self.cut
        raise AssertionError(action)

    # --- observations -----------------------------------------------------
    def fingerprint(self):
        per_node = []
        for node_id in NODE_IDS:
            node = self.nodes[node_id]
            applied = node._applied_indexing_tasks
            per_node.append((applied is None, tuple(sorted(
                (t["index_uid"], t["source_id"])
                for t in (applied or [])))))
        return (frozenset(self.dead), frozenset(self.cut),
                tuple(per_node))

    def consumers(self, source_id):
        """Alive nodes whose REAL ingest gate would run this source —
        source_assignment_allows with the production owns_index
        rendezvous fallback for never-applied nodes (the same pair of
        calls ingest_tick makes)."""
        out = []
        for node_id in self.alive():
            node = self.nodes[node_id]
            allowed = node.source_assignment_allows(self.uid, source_id)
            if allowed is None:
                allowed = node.owns_index(self.uid)
            if allowed:
                out.append(node_id)
        return out


def materialize(seq):
    world = World()
    for action in seq:
        world.apply(action)
    return world


def check_convergence(world, trace):
    """Quiet the network (heal cuts, keep deaths) and require the REAL
    pass loop to converge, then enforce single-consumer + liveness."""
    world.cut.clear()
    leader = world.nodes[world.leader_id()]
    out = None
    for _ in range(CONVERGE_PASSES):
        out = leader.run_control_plane_pass()
        if out["drift"] is False:
            break
    assert out is not None and out["drift"] is False, \
        f"no convergence after {CONVERGE_PASSES} passes; trace={trace}"
    for source_id in SOURCES:
        owners = world.consumers(source_id)
        assert len(owners) == 1, \
            (f"source {source_id} has consumers {owners} "
             f"(want exactly 1); trace={trace}")
    # every alive node is ON the plan (no node left behind on the
    # legacy election after convergence)
    for node_id in world.alive():
        report = world.nodes[node_id].indexing_tasks_report()
        assert report["applied"] is True, \
            f"{node_id} never got a plan; trace={trace}"


ACTIONS = ("pass", "kill-0", "revive-0", "restart-1", "cut-1", "heal-1")


def test_model_check_convergence():
    """BFS over failure interleavings; every reachable state must
    re-converge to exactly-one-consumer-per-source."""
    seen = set()
    frontier = [()]
    world = materialize(())
    seen.add(world.fingerprint())
    states = transitions = 0
    while frontier:
        next_frontier = []
        for seq in frontier:
            if len(seq) >= MAX_DEPTH:
                continue
            for action in ACTIONS:
                world = materialize(seq)
                if not world.enabled(action):
                    continue
                world.apply(action)
                transitions += 1
                fp = world.fingerprint()
                if fp in seen:
                    continue
                seen.add(fp)
                next_frontier.append(seq + (action,))
                check_convergence(world, seq + (action,))
                states += 1
        frontier = next_frontier
    # pin the explored-space size: silent shrinkage = lost coverage
    # (1,876 states / 1,885 transitions at depth 6 when written)
    assert states >= 1_500, states
    assert transitions >= 1_500, transitions


def test_leader_failover_reassigns():
    """Directed scenario: the LEADER dies; the next controller takes
    over and re-plans the dead node's sources onto survivors."""
    world = materialize(("pass",))
    before = {s: world.consumers(s) for s in SOURCES}
    assert all(len(v) == 1 for v in before.values())
    world.apply("kill-0")
    assert world.leader_id() == "m1"
    out = world.nodes["m1"].run_control_plane_pass()
    assert out["drift"] is True
    for source_id in SOURCES:
        [owner] = world.consumers(source_id)
        assert owner != "m0"


def test_restarted_node_rejoins_plan():
    """Directed scenario: an indexer restart (plan loss) re-converges
    onto the plan instead of double-consuming via the election."""
    world = materialize(("pass", "restart-1"))
    report = world.nodes["m1"].indexing_tasks_report()
    assert report["applied"] is False
    check_convergence(world, ("pass", "restart-1"))


def test_partitioned_node_keeps_old_slice_until_heal():
    """A partitioned indexer keeps running its last applied slice (it
    can't learn otherwise); after heal the next pass restores exact
    single-ownership."""
    world = materialize(("pass", "cut-1"))
    old = {t["source_id"]
           for t in world.nodes["m1"].indexing_tasks()}
    world.nodes[world.leader_id()].run_control_plane_pass()
    assert {t["source_id"]
            for t in world.nodes["m1"].indexing_tasks()} == old
    check_convergence(world, ("pass", "cut-1"))
