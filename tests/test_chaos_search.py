"""Seeded chaos suite for the search path.

Drives the root→leaf→storage stack through injected latency spikes, typed
errors, hangs, and node loss (quickwit_tpu.common.faults) and asserts the
robustness invariants the deadline machinery promises:

- no query ever exceeds its deadline + a fixed slack (no hangs);
- failures always surface as typed partial results (`failed_splits` /
  `timed_out`), never as silently-dropped splits;
- identical seeds reproduce identical failure schedules.

Everything here is deterministic and fast (marked `chaos`, runs in tier-1);
long randomized soak variants belong in `slow`-marked tests."""

import time

import pytest

from quickwit_tpu.common.deadline import (
    Deadline, DeadlineExceeded, QueryBudget, deadline_scope,
)
from quickwit_tpu.common.faults import (
    FaultInjector, FaultRule, FaultyClient, FaultyMetastore,
    FaultyStorageResolver, InjectedFault,
)
from quickwit_tpu.indexing import IndexingPipeline, PipelineParams, VecSource
from quickwit_tpu.metastore import FileBackedMetastore
from quickwit_tpu.models import DocMapper, FieldMapping, FieldType
from quickwit_tpu.models.index_metadata import (
    IndexConfig, IndexMetadata, SourceConfig,
)
from quickwit_tpu.query import parse_query_string
from quickwit_tpu.search.models import (
    LeafSearchRequest, SearchRequest, SortField, SplitIdAndFooter,
)
from quickwit_tpu.search.root import RootSearcher
from quickwit_tpu.search.service import (
    LocalSearchClient, SearcherContext, SearchService,
)
from quickwit_tpu.storage import StorageResolver

pytestmark = pytest.mark.chaos

MAPPER = DocMapper(
    field_mappings=[
        FieldMapping("ts", FieldType.DATETIME, fast=True,
                     input_formats=("unix_timestamp",)),
        FieldMapping("body", FieldType.TEXT),
        FieldMapping("severity", FieldType.TEXT, tokenizer="raw", fast=True),
    ],
    timestamp_field="ts",
    default_search_fields=("body",),
)

NUM_DOCS = 600          # 6 splits of 100
ERROR_DOCS = NUM_DOCS // 2
# Fixed slack on top of a request deadline: thread joins, partial-response
# assembly, and CPU-jax dispatch jitter — generous for CI, far below the
# injected hang durations it must cut off.
DEADLINE_SLACK_SECS = 1.6


@pytest.fixture(scope="module")
def corpus():
    """Splits + metastore built ONCE on a clean resolver; each test wraps
    the read path in its own injector so fault occurrences start from a
    fresh, reproducible sequence."""
    resolver = StorageResolver.for_test()
    metastore = FileBackedMetastore(resolver.resolve("ram:///chaos/ms"))
    split_uri = "ram:///chaos/splits"
    config = IndexConfig(index_id="chaos", index_uri=split_uri,
                         doc_mapper=MAPPER, split_num_docs_target=100)
    metastore.create_index(IndexMetadata(
        index_uid="chaos:01", index_config=config,
        sources={"src": SourceConfig("src", "vec")}))
    docs = [{"ts": 1_700_000_000 + i,
             "body": f"event {i} common",
             "severity": ["INFO", "ERROR"][i % 2]} for i in range(NUM_DOCS)]
    pipeline = IndexingPipeline(
        PipelineParams(index_uid="chaos:01", source_id="src",
                       split_num_docs_target=100, batch_num_docs=50),
        MAPPER, VecSource(docs), metastore, resolver.resolve(split_uri))
    pipeline.run_to_completion()
    return resolver, metastore


def build_root(corpus, num_nodes=3, storage_injector=None,
               client_injector=None, batcher_injector=None,
               prefetch=False, batch_size=1):
    """Fresh services/clients per call: no cache state crosses tests or
    determinism runs."""
    resolver, metastore = corpus
    storage_resolver = (FaultyStorageResolver(resolver, storage_injector)
                        if storage_injector is not None else resolver)
    clients = {}
    for i in range(num_nodes):
        node_id = f"node-{i}"
        context = SearcherContext(storage_resolver=storage_resolver,
                                  prefetch=prefetch, batch_size=batch_size)
        if batcher_injector is not None:
            context.query_batcher.fault_injector = batcher_injector
        client = LocalSearchClient(SearchService(context, node_id=node_id))
        if client_injector is not None:
            client = FaultyClient(client, client_injector, node_id)
        clients[node_id] = client
    return RootSearcher(metastore, clients)


def term_request(**kwargs):
    return SearchRequest(
        index_ids=["chaos"], query_ast=parse_query_string("severity:ERROR"),
        sort_fields=(SortField("ts", "desc"),), **kwargs)


# --- invariant: failures surface typed, queries still answer ---------------


def test_storage_errors_surface_as_typed_partial_results(corpus):
    # two storage reads error (each killing the split that issued them);
    # with a single node there is no retry target, so those splits MUST
    # fail — and every one of them must appear in failed_splits (nothing
    # silently dropped)
    injector = FaultInjector(seed=11, rules=[
        FaultRule("storage.get_slice", "error", every=3, max_fires=2),
        FaultRule("storage.get_slice", "latency", every=7,
                  latency_secs=0.01),
    ])
    root = build_root(corpus, num_nodes=1, storage_injector=injector)
    t0 = time.monotonic()
    response = root.search(term_request(max_hits=5, timeout_millis=20_000))
    elapsed = time.monotonic() - t0
    assert elapsed < 20.0 + DEADLINE_SLACK_SECS
    assert response.failed_splits, "injected storage errors vanished"
    for failure in response.failed_splits:
        assert "injected fault" in failure.error
    # accounting: every split is either successful or reported failed
    failed_ids = {e.split_id for e in response.failed_splits}
    assert len(failed_ids) == 2  # one split per fired fault, no more
    assert response.num_successful_splits + len(failed_ids) == 6
    # hits from surviving splits only (50 ERROR docs per split)
    assert response.num_hits == 50 * response.num_successful_splits


def test_node_failure_recovered_by_budgeted_retry(corpus):
    # node-0 drops every leaf request; rendezvous retry lands its splits on
    # a healthy peer, so the final response is complete and clean
    injector = FaultInjector(seed=5, rules=[
        FaultRule("client.leaf_search@node-0", "error"),
    ])
    root = build_root(corpus, num_nodes=3, client_injector=injector)
    response = root.search(term_request(max_hits=10))
    assert response.num_hits == ERROR_DOCS
    assert not response.failed_splits
    assert not response.timed_out
    assert len(response.hits) == 10


def test_all_nodes_down_is_a_typed_error_not_a_hang(corpus):
    injector = FaultInjector(seed=5, rules=[
        FaultRule("client.leaf_search@*", "error"),
    ])
    root = build_root(corpus, num_nodes=2, client_injector=injector)
    t0 = time.monotonic()
    with pytest.raises(ValueError, match="injected fault|failed"):
        root.search(term_request(max_hits=0, timeout_millis=20_000))
    assert time.monotonic() - t0 < 20.0 + DEADLINE_SLACK_SECS


def test_batcher_fault_fans_typed_errors_no_hang(corpus):
    # the convoy batcher's dispatch blows up on every 2nd dispatch: affected
    # riders get typed errors (surfacing as failed splits), others succeed
    injector = FaultInjector(seed=3, rules=[
        FaultRule("batcher.dispatch", "error", every=2),
    ])
    root = build_root(corpus, num_nodes=1, batcher_injector=injector)
    t0 = time.monotonic()
    response = root.search(term_request(max_hits=3, timeout_millis=20_000))
    assert time.monotonic() - t0 < 20.0 + DEADLINE_SLACK_SECS
    assert len(response.failed_splits) == 3  # dispatches 2, 4, 6 of 6
    for failure in response.failed_splits:
        assert "injected fault" in failure.error
    assert response.num_hits == 50 * 3


# --- invariant: deadline + slack, never a hang -----------------------------


def test_leaf_hang_cut_off_at_deadline(corpus):
    # every leaf RPC stalls 3s; the query budget is 0.4s — the root must
    # answer within deadline + slack with a timed_out partial response
    injector = FaultInjector(seed=21, rules=[
        FaultRule("client.leaf_search@*", "hang", hang_secs=3.0),
    ])
    root = build_root(corpus, num_nodes=3, client_injector=injector)
    t0 = time.monotonic()
    response = root.search(term_request(max_hits=5, timeout_millis=400))
    elapsed = time.monotonic() - t0
    assert elapsed < 0.4 + DEADLINE_SLACK_SECS
    assert response.timed_out
    assert response.failed_splits
    for failure in response.failed_splits:
        assert "deadline exceeded" in failure.error
    # the ES/native wire shape carries the verdict
    assert response.to_dict()["timed_out"] is True


def test_expired_budget_sheds_instead_of_searching(corpus):
    # a budget that expires before the fan-out even starts: every split is
    # shed with a typed deadline error, fast
    root = build_root(corpus, num_nodes=2)
    t0 = time.monotonic()
    response = root.search(term_request(max_hits=5, timeout_millis=1))
    elapsed = time.monotonic() - t0
    assert elapsed < DEADLINE_SLACK_SECS
    assert response.timed_out
    assert response.num_hits == 0
    assert len({e.split_id for e in response.failed_splits}) == 6
    for failure in response.failed_splits:
        assert "deadline exceeded" in failure.error


def test_storage_hang_cut_off_at_deadline(corpus):
    # slow storage (0.5s per read) against a 0.3s budget: reads are cut off
    # by the ambient deadline inside the leaf, the root answers on time
    injector = FaultInjector(seed=8, rules=[
        FaultRule("storage.get_slice", "hang", hang_secs=0.5),
    ])
    root = build_root(corpus, num_nodes=1, storage_injector=injector)
    t0 = time.monotonic()
    response = root.search(term_request(max_hits=5, timeout_millis=300))
    elapsed = time.monotonic() - t0
    assert elapsed < 0.3 + DEADLINE_SLACK_SECS
    assert response.timed_out
    assert response.failed_splits


def test_slow_metastore_yields_typed_partial_not_extra_work(corpus):
    # list_splits stalls 1s against a 0.4s budget: the stall itself is a
    # synchronous lower bound on latency, but once the deadline is gone the
    # root must SHED the whole fan-out (typed deadline failures, timed_out)
    # instead of piling leaf work on top of the blown budget
    injector = FaultInjector(seed=13, rules=[
        FaultRule("metastore.list_splits", "hang", hang_secs=1.0),
    ])
    root = build_root(corpus, num_nodes=2)
    root.metastore = FaultyMetastore(root.metastore, injector)
    t0 = time.monotonic()
    response = root.search(term_request(max_hits=5, timeout_millis=400))
    elapsed = time.monotonic() - t0
    assert elapsed < 1.0 + DEADLINE_SLACK_SECS  # stall + slack, nothing more
    assert response.timed_out
    assert response.num_hits == 0
    assert len({e.split_id for e in response.failed_splits}) == 6
    for failure in response.failed_splits:
        assert "deadline exceeded" in failure.error
    assert injector.occurrences("metastore.list_splits") == 1


def test_metastore_error_surfaces_typed_not_a_hang(corpus):
    from quickwit_tpu.metastore import MetastoreError
    injector = FaultInjector(seed=13, rules=[
        FaultRule("metastore.list_splits", "error"),
    ])
    root = build_root(corpus, num_nodes=1)
    root.metastore = FaultyMetastore(root.metastore, injector)
    t0 = time.monotonic()
    with pytest.raises(MetastoreError, match="injected fault"):
        root.search(term_request(max_hits=5, timeout_millis=20_000))
    assert time.monotonic() - t0 < DEADLINE_SLACK_SECS


# --- invariant: same seed, same schedule -----------------------------------


def test_same_seed_reproduces_schedule_and_failures(corpus):
    rules = [
        FaultRule("storage.get_slice", "error", probability=0.2),
        FaultRule("storage.get_slice", "latency", probability=0.3,
                  latency_secs=0.002),
    ]

    def outcome(root, request):
        try:
            r = root.search(request)
            return (r.num_hits, sorted(e.split_id for e in r.failed_splits))
        except ValueError as exc:  # all splits failed — also reproducible
            return ("all-failed", str(exc))

    def run():
        injector = FaultInjector(seed=1234, rules=rules)
        root = build_root(corpus, num_nodes=1, storage_injector=injector)
        outcomes = [
            outcome(root, term_request(max_hits=5, timeout_millis=30_000)),
            outcome(root, SearchRequest(
                index_ids=["chaos"],
                query_ast=parse_query_string("common", ["body"]),
                max_hits=0, timeout_millis=30_000,
                aggs={"sev": {"terms": {"field": "severity"}}})),
        ]
        return injector.schedule(), outcomes

    schedule_a, outcomes_a = run()
    schedule_b, outcomes_b = run()
    assert schedule_a == schedule_b
    assert outcomes_a == outcomes_b
    assert schedule_a, "seeded rules never fired — the run tested nothing"


def test_decisions_immune_to_cross_operation_interleaving():
    # the same per-operation call sequences must see the same decisions no
    # matter how calls to DIFFERENT operations interleave (thread timing)
    rules = [FaultRule("op.*", "error", probability=0.5)]

    def decisions(order):
        injector = FaultInjector(seed=99, rules=rules)
        for op in order:
            try:
                injector.perturb(op)
            except InjectedFault:
                pass
        return injector.schedule()

    interleaved = decisions(["op.a", "op.b", "op.a", "op.b", "op.a", "op.b"])
    grouped = decisions(["op.a", "op.a", "op.a", "op.b", "op.b", "op.b"])
    assert interleaved == grouped


# --- satellite regression: no silently-dropped split failures --------------


def _leaf_request_for(splits):
    return LeafSearchRequest(
        search_request=term_request(max_hits=3),
        index_uid="chaos:01", doc_mapping=MAPPER.to_dict(),
        splits=[SplitIdAndFooter(split_id=s, storage_uri="ram:///chaos/splits")
                for s in splits])


class _DeadClient:
    def leaf_search(self, request):
        raise RuntimeError("node unreachable")


def test_no_retry_node_still_reports_failed_splits(corpus):
    # single node, node dead, nowhere to retry: the response MUST carry a
    # SplitSearchError per split (this used to return failed_splits=[])
    _, metastore = corpus
    root = RootSearcher(metastore, {"node-0": _DeadClient()})
    leaf_request = _leaf_request_for(["s1", "s2", "s3"])
    response = root._leaf_search_with_retry(leaf_request, "node-0",
                                            ["node-0"])
    assert sorted(e.split_id for e in response.failed_splits) == \
        ["s1", "s2", "s3"]
    assert response.num_attempted_splits == 3
    for failure in response.failed_splits:
        assert "node unreachable" in failure.error


def test_failed_retry_still_reports_failed_splits(corpus):
    # both the primary and the retry node throw: failures must surface with
    # the retry error (this used to return an EMPTY LeafSearchResponse)
    _, metastore = corpus
    root = RootSearcher(metastore, {"node-0": _DeadClient(),
                                    "node-1": _DeadClient()})
    leaf_request = _leaf_request_for(["s1", "s2"])
    response = root._leaf_search_with_retry(leaf_request, "node-0",
                                            ["node-0", "node-1"])
    assert sorted(e.split_id for e in response.failed_splits) == ["s1", "s2"]
    assert response.num_attempted_splits == 2
    for failure in response.failed_splits:
        assert "retry on node-1 failed" in failure.error


# --- fetch-docs phase: one replica retry, never a replica walk -------------


class _FlakyFetchClient:
    """Counts fetch_docs per node and fails on the nodes in `fail` (a
    shared mutable set so tests can pick victims AFTER split ids exist)."""

    def __init__(self, inner, node_id, fail, calls):
        self._inner = inner
        self.node_id = node_id
        self._fail = fail
        self._calls = calls

    def fetch_docs(self, request):
        self._calls[self.node_id] = self._calls.get(self.node_id, 0) + 1
        if self.node_id in self._fail:
            raise RuntimeError("injected fetch_docs failure")
        return self._inner.fetch_docs(request)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _fetch_retry_root(corpus, fail, calls, num_nodes=3):
    resolver, metastore = corpus
    clients = {}
    for i in range(num_nodes):
        node_id = f"node-{i}"
        context = SearcherContext(storage_resolver=resolver)
        clients[node_id] = _FlakyFetchClient(
            LocalSearchClient(SearchService(context, node_id=node_id)),
            node_id, fail, calls)
    return RootSearcher(metastore, clients)


def test_fetch_docs_failure_recovered_on_next_replica(corpus):
    # the preferred replica of every split drops phase-2 doc fetches; the
    # single budgeted retry on the next replica must still fill the page
    from quickwit_tpu.search.placer import nodes_for_split
    from quickwit_tpu.observability.metrics import (
        SEARCH_FETCH_DOCS_RETRIES_TOTAL,
    )
    fail: set[str] = set()
    calls: dict[str, int] = {}
    root = _fetch_retry_root(corpus, fail, calls)
    nodes = sorted(root.clients)
    _, metastore = corpus
    from quickwit_tpu.metastore.base import ListSplitsQuery
    splits = metastore.list_splits(ListSplitsQuery())
    # newest split holds the ts-desc top page; fail ONLY its preferred
    # replica so the retry target stays healthy
    top_split = max(splits, key=lambda s: s.metadata.time_range_end or 0)
    preference = nodes_for_split(top_split.metadata.split_id, nodes)
    fail.add(preference[0])
    before = SEARCH_FETCH_DOCS_RETRIES_TOTAL.get()
    response = root.search(term_request(max_hits=5))
    assert len(response.hits) == 5, \
        "page incomplete: fetch_docs retry never recovered the docs"
    assert not response.failed_splits
    assert SEARCH_FETCH_DOCS_RETRIES_TOTAL.get() - before == 1
    assert calls[preference[0]] == 1   # first attempt failed
    assert calls[preference[1]] == 1   # exactly one retry, on replica #2


def test_fetch_docs_retries_once_not_a_replica_walk(corpus):
    # every replica is down for phase 2: the phase must attempt the
    # preferred node plus ONE retry — not walk all replicas — and still
    # return the phase-1 counts with the unfetchable docs dropped
    from quickwit_tpu.observability.metrics import (
        SEARCH_FETCH_DOCS_RETRIES_TOTAL,
    )
    fail: set[str] = set()
    calls: dict[str, int] = {}
    root = _fetch_retry_root(corpus, fail, calls)
    fail.update(root.clients)
    before = SEARCH_FETCH_DOCS_RETRIES_TOTAL.get()
    response = root.search(term_request(max_hits=5))
    assert response.hits == []          # docs unfetchable everywhere
    assert response.num_hits == ERROR_DOCS  # phase-1 result preserved
    assert SEARCH_FETCH_DOCS_RETRIES_TOTAL.get() - before == 1
    assert sum(calls.values()) == 2, \
        f"expected first attempt + one retry, saw {calls}"


# --- residency eviction faults ---------------------------------------------


def _resident_leaf_setup(corpus, budget_factor):
    """A SearchService whose HBM budget fits `budget_factor` splits'
    resident columns — admission of later splits must evict earlier ones
    mid-request. Returns (service, context, offsets)."""
    from quickwit_tpu.metastore.base import ListSplitsQuery
    from quickwit_tpu.search.admission import HbmBudget
    resolver, metastore = corpus
    splits = metastore.list_splits(ListSplitsQuery())
    offsets = [SplitIdAndFooter(split_id=s.metadata.split_id,
                                storage_uri="ram:///chaos/splits")
               for s in sorted(splits, key=lambda s: s.metadata.split_id)]
    # probe one split's resident footprint with an unconstrained context
    probe = SearcherContext(storage_resolver=resolver, batch_size=1,
                            prefetch=False)
    SearchService(probe).leaf_search(LeafSearchRequest(
        search_request=term_request(max_hits=3), index_uid="chaos:01",
        doc_mapping=MAPPER.to_dict(), splits=offsets[:1]))
    per_split = probe.hbm_budget.stats()["resident"]
    assert per_split > 0
    context = SearcherContext(storage_resolver=resolver, batch_size=1,
                              prefetch=False)
    context.hbm_budget = HbmBudget(
        budget_bytes=int(per_split * budget_factor))
    return SearchService(context), context, offsets


def test_residency_evict_fault_absorbed_query_succeeds(corpus):
    # every eviction notification raises an injected error INSIDE the
    # admission lock of whichever query triggered the LRU; the fault must
    # be absorbed: all queries complete with full, correct results, and
    # the evictions are still counted
    from quickwit_tpu.search.residency import RESIDENT_EVICTIONS
    service, context, offsets = _resident_leaf_setup(corpus,
                                                     budget_factor=2.5)
    injector = FaultInjector(seed=7, rules=[
        FaultRule("residency.evict", "error"),
    ])
    context.resident_store.fault_injector = injector
    before = RESIDENT_EVICTIONS.get()
    for max_hits in (5, 4):  # distinct pages: second pass re-warms evicted
        response = service.leaf_search(LeafSearchRequest(
            search_request=term_request(max_hits=max_hits),
            index_uid="chaos:01", doc_mapping=MAPPER.to_dict(),
            splits=list(offsets)))
        assert response.num_hits == ERROR_DOCS
        assert not response.failed_splits
        assert len(response.partial_hits) == max_hits
    assert injector.occurrences("residency.evict") >= 1
    assert RESIDENT_EVICTIONS.get() - before >= 1
    # store accounting survived the faulted evictions
    assert context.resident_store.stats()["bytes"] >= 0
    assert context.hbm_budget.stats()["pinned"] == 0


def test_residency_evict_results_match_fault_free_run(corpus):
    # same seed corpus, same pressured budget: a run with eviction faults
    # injected is bit-identical to a fault-free run (the cache layer may
    # lose residency, never correctness)
    faulted, faulted_ctx, offsets = _resident_leaf_setup(corpus,
                                                         budget_factor=1.5)
    faulted_ctx.resident_store.fault_injector = FaultInjector(
        seed=29, rules=[FaultRule("residency.evict", "error", every=2)])
    clean, _, _ = _resident_leaf_setup(corpus, budget_factor=1.5)
    request = term_request(max_hits=7)

    def run(service):
        r = service.leaf_search(LeafSearchRequest(
            search_request=request, index_uid="chaos:01",
            doc_mapping=MAPPER.to_dict(), splits=list(offsets)))
        assert not r.failed_splits
        return (r.num_hits,
                [(h.split_id, h.doc_id, h.sort_value)
                 for h in r.partial_hits])

    assert run(faulted) == run(clean)
    assert faulted_ctx.resident_store.fault_injector.occurrences(
        "residency.evict") >= 1


# --- budget mechanics ------------------------------------------------------


def test_query_budget_retry_pool_and_backoff():
    budget = QueryBudget(Deadline.after(60.0), max_retries=2)
    assert budget.try_acquire_retry() == 0
    assert budget.try_acquire_retry() == 1
    assert budget.try_acquire_retry() is None  # pool drained
    assert budget.backoff_secs(0) == 0.0       # first retry is immediate
    assert budget.backoff_secs(1) == pytest.approx(0.05)
    assert budget.backoff_secs(2) == pytest.approx(0.10)
    assert budget.backoff_secs(100) == QueryBudget.BACKOFF_CAP_SECS
    # backoff never exceeds the remaining deadline
    tight = QueryBudget(Deadline.after(0.01))
    assert tight.backoff_secs(100) <= 0.01
    # an expired deadline grants no retries at all
    expired = QueryBudget(Deadline.after(0.0))
    assert expired.try_acquire_retry() is None


def test_deadline_scope_propagates_and_clamps():
    with deadline_scope(Deadline.after(5.0)) as deadline:
        assert deadline.clamp(60.0) <= 5.0
        assert deadline.clamp(1.0) == 1.0
        assert deadline.timeout_millis() <= 5_000
    unbounded = Deadline.never()
    assert unbounded.clamp(None) is None
    assert unbounded.timeout_millis() is None
    assert not unbounded.expired
    with pytest.raises(DeadlineExceeded, match="deadline exceeded"):
        Deadline.after(0.0).check("unit")
