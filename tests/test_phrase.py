"""Unit tests for host-side phrase matching (ops/phrase.py) — tantivy
PhraseScorer semantics, including the repeated-term rule: duplicate
phrase terms must occupy DISTINCT document positions."""

import numpy as np

from quickwit_tpu.ops.phrase import phrase_match


def _term(doc_positions: dict[int, list[int]]):
    """Build (postings, positions, df) for one term from doc->positions."""
    ids = np.array(sorted(doc_positions), dtype=np.int32)
    tfs = np.array([len(doc_positions[d]) for d in sorted(doc_positions)],
                   dtype=np.int32)
    offsets = np.zeros(len(ids) + 1, dtype=np.int32)
    data = []
    for i, d in enumerate(sorted(doc_positions)):
        data.extend(doc_positions[d])
        offsets[i + 1] = len(data)
    return (ids, tfs), (offsets, np.array(data, dtype=np.int32)), len(ids)


def _match(terms, slop=0, keys=None):
    posts, poss, dfs = zip(*terms)
    return phrase_match(list(posts), list(poss), list(dfs), slop,
                        term_keys=keys)


def test_exact_phrase():
    # doc 0: "quick brown fox"; doc 1: "brown quick"
    quick = _term({0: [0], 1: [1]})
    brown = _term({0: [1], 1: [0]})
    ids, freqs = _match([quick, brown], slop=0, keys=["quick", "brown"])
    assert ids.tolist() == [0] and freqs.tolist() == [1]


def test_sloppy_transposition():
    quick = _term({0: [0], 1: [1]})
    brown = _term({0: [1], 1: [0]})
    ids, _ = _match([quick, brown], slop=2, keys=["quick", "brown"])
    assert ids.tolist() == [0, 1]


def test_repeated_term_needs_two_occurrences():
    # phrase "a a" with slop=1 must NOT match a doc holding a single "a"
    a = _term({0: [0], 1: [0, 1], 2: [0, 5]})
    ids, freqs = _match([a, a], slop=1, keys=["a", "a"])
    assert ids.tolist() == [1]
    assert freqs.tolist() == [1]
    # wider slop reaches the spread-out occurrences in doc 2
    ids, _ = _match([a, a], slop=5, keys=["a", "a"])
    assert ids.tolist() == [1, 2]


def test_repeated_term_exact_unaffected():
    # slop=0 path already required distinct positions; stays correct
    a = _term({0: [0], 1: [0, 1]})
    ids, freqs = _match([a, a], slop=0, keys=["a", "a"])
    assert ids.tolist() == [1] and freqs.tolist() == [1]
