"""Unit tests for host-side phrase matching (ops/phrase.py) — tantivy
PhraseScorer semantics, including the repeated-term rule: duplicate
phrase terms must occupy DISTINCT document positions."""

import numpy as np

from quickwit_tpu.ops.phrase import phrase_match


def _term(doc_positions: dict[int, list[int]]):
    """Build (postings, positions, df) for one term from doc->positions."""
    ids = np.array(sorted(doc_positions), dtype=np.int32)
    tfs = np.array([len(doc_positions[d]) for d in sorted(doc_positions)],
                   dtype=np.int32)
    offsets = np.zeros(len(ids) + 1, dtype=np.int32)
    data = []
    for i, d in enumerate(sorted(doc_positions)):
        data.extend(doc_positions[d])
        offsets[i + 1] = len(data)
    return (ids, tfs), (offsets, np.array(data, dtype=np.int32)), len(ids)


def _match(terms, slop=0, keys=None):
    posts, poss, dfs = zip(*terms)
    return phrase_match(list(posts), list(poss), list(dfs), slop,
                        term_keys=keys)


def test_exact_phrase():
    # doc 0: "quick brown fox"; doc 1: "brown quick"
    quick = _term({0: [0], 1: [1]})
    brown = _term({0: [1], 1: [0]})
    ids, freqs = _match([quick, brown], slop=0, keys=["quick", "brown"])
    assert ids.tolist() == [0] and freqs.tolist() == [1]


def test_sloppy_transposition():
    quick = _term({0: [0], 1: [1]})
    brown = _term({0: [1], 1: [0]})
    ids, _ = _match([quick, brown], slop=2, keys=["quick", "brown"])
    assert ids.tolist() == [0, 1]


def test_repeated_term_needs_two_occurrences():
    # phrase "a a" with slop=1 must NOT match a doc holding a single "a"
    a = _term({0: [0], 1: [0, 1], 2: [0, 5]})
    ids, freqs = _match([a, a], slop=1, keys=["a", "a"])
    assert ids.tolist() == [1]
    assert freqs.tolist() == [1]
    # wider slop reaches the spread-out occurrences in doc 2
    ids, _ = _match([a, a], slop=5, keys=["a", "a"])
    assert ids.tolist() == [1, 2]


def test_repeated_term_exact_unaffected():
    # slop=0 path already required distinct positions; stays correct
    a = _term({0: [0], 1: [0, 1]})
    ids, freqs = _match([a, a], slop=0, keys=["a", "a"])
    assert ids.tolist() == [1] and freqs.tolist() == [1]


def test_exact_vectorized_parity_random():
    """The vectorized slop=0 path agrees with a brute-force per-doc
    oracle on random 3-term corpora."""
    rng = np.random.RandomState(5)
    for trial in range(20):
        num_docs, length, vocab = 40, 10, 6
        toks = rng.randint(0, vocab, size=(num_docs, length))
        phrase = [rng.randint(0, 3), rng.randint(0, 3), rng.randint(0, 3)]
        expected = {}
        for d in range(num_docs):
            freq = sum(
                1 for p in range(length - 2)
                if toks[d, p] == phrase[0] and toks[d, p + 1] == phrase[1]
                and toks[d, p + 2] == phrase[2])
            if freq:
                expected[d] = freq
        terms = []
        for t in phrase:
            doc_positions = {
                d: list(np.nonzero(toks[d] == t)[0])
                for d in range(num_docs) if (toks[d] == t).any()}
            terms.append(_term(doc_positions))
        ids, freqs = _match(terms, slop=0, keys=[str(t) for t in phrase])
        assert dict(zip(ids.tolist(), freqs.tolist())) == expected, \
            (trial, phrase)
