"""Concurrent mixed-workload soak: searches, SQL, aggregations, and
ingest hammering one node from many threads at once.

Role of the reference's integration stress coverage: the serving path
(convoy batcher, executor compile cache, WAL, metastore cache) must
stay correct and error-free under REAL concurrency — every response a
200, every search's num_hits monotone in the (growing) corpus, no
deadlocks (bounded wall-clock), no dropped ingest."""

import http.client
import json
import threading

import pytest

from quickwit_tpu.serve import Node, NodeConfig, RestServer
from quickwit_tpu.storage import StorageResolver

THREADS = 8
ROUNDS = 12


@pytest.fixture()
def api():
    node = Node(NodeConfig(node_id="soak", rest_port=0,
                           metastore_uri="ram:///soak/ms",
                           default_index_root_uri="ram:///soak/idx"),
                storage_resolver=StorageResolver.for_test())
    server = RestServer(node, host="127.0.0.1", port=0)
    server.start()
    conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                      timeout=30)
    conn.request("POST", "/api/v1/indexes", json.dumps({
        "index_id": "soak",
        "doc_mapping": {"field_mappings": [
            {"name": "ts", "type": "datetime", "fast": True,
             "input_formats": ["unix_timestamp"]},
            {"name": "sev", "type": "text", "tokenizer": "raw",
             "fast": True},
            {"name": "num", "type": "f64", "fast": True},
            {"name": "body", "type": "text"}],
            "timestamp_field": "ts",
            "default_search_fields": ["body"]}}).encode())
    assert conn.getresponse().status == 200
    conn.close()
    # seed corpus so every query shape compiles BEFORE the storm
    node.ingest("soak", [
        {"ts": 1000 + i, "sev": ["a", "b"][i % 2], "num": float(i),
         "body": f"seed{i} common"} for i in range(50)], commit="force")
    yield server.port
    server.stop()


def _call(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request(method, path, body)
    response = conn.getresponse()
    data = response.read()
    conn.close()
    return response.status, data


def test_concurrent_mixed_workload(api):
    port = api
    errors: list[str] = []
    ingested = [0] * THREADS
    barrier = threading.Barrier(THREADS)

    def worker(worker_id: int) -> None:
        try:
            barrier.wait(timeout=30)
            for round_no in range(ROUNDS):
                kind = (worker_id + round_no) % 4
                if kind == 0:      # plain search
                    status, data = _call(
                        port, "GET",
                        "/api/v1/soak/search?query=common&max_hits=5")
                    assert status == 200, data[:200]
                    assert json.loads(data)["num_hits"] >= 50
                elif kind == 1:    # aggregation (same-shape: convoy)
                    status, data = _call(
                        port, "POST", "/api/v1/_elastic/soak/_search",
                        json.dumps({
                            "query": {"match": {"body": "common"}},
                            "size": 0,
                            "aggs": {"per_sev": {"terms":
                                                 {"field": "sev"}}},
                        }).encode())
                    assert status == 200, data[:200]
                    buckets = json.loads(data)["aggregations"][
                        "per_sev"]["buckets"]
                    assert sum(b["doc_count"] for b in buckets) >= 50
                elif kind == 2:    # SQL
                    status, data = _call(
                        port, "POST", "/api/v1/_sql", json.dumps({
                            "query": "SELECT sev, COUNT(*) AS n "
                                     "FROM soak GROUP BY sev"}).encode())
                    assert status == 200, data[:200]
                else:              # ingest more docs
                    docs = "\n".join(json.dumps(
                        {"ts": 2000 + worker_id * 1000 + round_no,
                         "sev": "c", "num": 1.0,
                         "body": f"w{worker_id}r{round_no} common"})
                        for _ in range(2))
                    status, data = _call(
                        port, "POST",
                        "/api/v1/soak/ingest?commit=force",
                        docs.encode())
                    assert status == 200, data[:200]
                    ingested[worker_id] += 2
        except Exception as exc:  # noqa: BLE001 - collected for report
            errors.append(f"worker {worker_id}: {exc!r}")

    workers = [threading.Thread(target=worker, args=(i,))
               for i in range(THREADS)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(timeout=120)
    assert not any(w.is_alive() for w in workers), "soak deadlocked"
    assert not errors, errors

    # every ingested doc is searchable afterwards (nothing dropped)
    status, data = _call(
        port, "GET", "/api/v1/soak/search?query=common&max_hits=0")
    assert status == 200
    assert json.loads(data)["num_hits"] == 50 + sum(ingested)
