"""Concurrent mixed-workload soak: searches, SQL, aggregations, and
ingest hammering one node from many threads at once.

Role of the reference's integration stress coverage: the serving path
(convoy batcher, executor compile cache, WAL, metastore cache) must
stay correct and error-free under REAL concurrency — every response a
200, every search's num_hits monotone in the (growing) corpus, no
deadlocks (bounded wall-clock), no dropped ingest."""

import http.client
import json
import threading
import time

import pytest

from quickwit_tpu.observability.metrics import (
    SEARCH_BATCHER_DISPATCHES_TOTAL, SEARCH_BATCHER_QUERIES_TOTAL,
    SEARCH_BATCHER_QUEUE_WAIT, SEARCH_BATCHER_RATIO,
)
from quickwit_tpu.serve import Node, NodeConfig, RestServer
from quickwit_tpu.storage import StorageResolver

THREADS = 8
ROUNDS = 12


def _percentile(sorted_values, q):
    assert sorted_values
    idx = min(len(sorted_values) - 1, int(q * (len(sorted_values) - 1)))
    return sorted_values[idx]


@pytest.fixture()
def api():
    node = Node(NodeConfig(node_id="soak", rest_port=0,
                           metastore_uri="ram:///soak/ms",
                           default_index_root_uri="ram:///soak/idx"),
                storage_resolver=StorageResolver.for_test())
    server = RestServer(node, host="127.0.0.1", port=0)
    server.start()
    conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                      timeout=30)
    conn.request("POST", "/api/v1/indexes", json.dumps({
        "index_id": "soak",
        "doc_mapping": {"field_mappings": [
            {"name": "ts", "type": "datetime", "fast": True,
             "input_formats": ["unix_timestamp"]},
            {"name": "sev", "type": "text", "tokenizer": "raw",
             "fast": True},
            {"name": "num", "type": "f64", "fast": True},
            {"name": "body", "type": "text"}],
            "timestamp_field": "ts",
            "default_search_fields": ["body"]}}).encode())
    assert conn.getresponse().status == 200
    conn.close()
    # seed corpus so every query shape compiles BEFORE the storm
    node.ingest("soak", [
        {"ts": 1000 + i, "sev": ["a", "b"][i % 2], "num": float(i),
         "body": f"seed{i} common"} for i in range(50)], commit="force")
    yield server.port, node
    server.stop()


def _call(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request(method, path, body)
    response = conn.getresponse()
    data = response.read()
    conn.close()
    return response.status, data


def test_concurrent_mixed_workload(api):
    port, node = api
    batcher = node.searcher_context.query_batcher
    queries_before = batcher.num_queries
    dispatches_before = batcher.num_dispatches
    errors: list[str] = []
    ingested = [0] * THREADS
    latencies: list[float] = []  # list.append is GIL-atomic
    barrier = threading.Barrier(THREADS)

    # arm the slow-query log over REST for the whole storm: threshold 0
    # captures every search, so the dump below is a per-query waterfall
    # census of the soak — exactly what the endpoint is for in production
    status, data = _call(port, "POST", "/api/v1/developer/slowlog",
                         json.dumps({"threshold_ms": 0.0}).encode())
    assert status == 200, data[:200]
    assert json.loads(data)["armed"]

    def timed_call(method, path, body=None):
        t0 = time.monotonic()
        result = _call(port, method, path, body)
        latencies.append(time.monotonic() - t0)
        return result

    def worker(worker_id: int) -> None:
        try:
            barrier.wait(timeout=30)
            for round_no in range(ROUNDS):
                kind = (worker_id + round_no) % 4
                if kind == 0:      # plain search
                    status, data = timed_call(
                        "GET",
                        "/api/v1/soak/search?query=common&max_hits=5")
                    assert status == 200, data[:200]
                    assert json.loads(data)["num_hits"] >= 50
                elif kind == 1:    # aggregation (same-shape: convoy)
                    status, data = timed_call(
                        "POST", "/api/v1/_elastic/soak/_search",
                        json.dumps({
                            "query": {"match": {"body": "common"}},
                            "size": 0,
                            "aggs": {"per_sev": {"terms":
                                                 {"field": "sev"}}},
                        }).encode())
                    assert status == 200, data[:200]
                    buckets = json.loads(data)["aggregations"][
                        "per_sev"]["buckets"]
                    assert sum(b["doc_count"] for b in buckets) >= 50
                elif kind == 2:    # SQL
                    status, data = timed_call(
                        "POST", "/api/v1/_sql", json.dumps({
                            "query": "SELECT sev, COUNT(*) AS n "
                                     "FROM soak GROUP BY sev"}).encode())
                    assert status == 200, data[:200]
                else:              # ingest more docs
                    docs = "\n".join(json.dumps(
                        {"ts": 2000 + worker_id * 1000 + round_no,
                         "sev": "c", "num": 1.0,
                         "body": f"w{worker_id}r{round_no} common"})
                        for _ in range(2))
                    status, data = timed_call(
                        "POST",
                        "/api/v1/soak/ingest?commit=force",
                        docs.encode())
                    assert status == 200, data[:200]
                    ingested[worker_id] += 2
        except Exception as exc:  # noqa: BLE001 - collected for report
            errors.append(f"worker {worker_id}: {exc!r}")

    workers = [threading.Thread(target=worker, args=(i,))
               for i in range(THREADS)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(timeout=120)
    assert not any(w.is_alive() for w in workers), "soak deadlocked"
    assert not errors, errors

    # latency tail: every request bounded, no hidden per-request hang
    ordered = sorted(latencies)
    p50, p99 = _percentile(ordered, 0.50), _percentile(ordered, 0.99)
    print(f"\nsoak latency over {len(ordered)} requests: "
          f"p50={p50 * 1000:.1f}ms p99={p99 * 1000:.1f}ms")
    assert p99 < 30.0, f"p99 latency {p99:.1f}s — a request nearly hung"

    # convoy accounting stays sane under the storm (strict coalescing is
    # asserted by the dedicated burst test below)
    query_delta = batcher.num_queries - queries_before
    dispatch_delta = batcher.num_dispatches - dispatches_before
    print(f"convoy batcher: {query_delta} queries -> "
          f"{dispatch_delta} dispatches")
    assert dispatch_delta <= query_delta

    # every ingested doc is searchable afterwards (nothing dropped)
    status, data = _call(
        port, "GET", "/api/v1/soak/search?query=common&max_hits=0")
    assert status == 200
    assert json.loads(data)["num_hits"] == 50 + sum(ingested)

    # slow-query dump: the armed ring buffer captured real waterfalls for
    # the storm's searches — phase names, not zeros — and disarming stops
    # further capture
    try:
        status, data = _call(port, "GET", "/api/v1/developer/slowlog")
        assert status == 200, data[:200]
        dump = json.loads(data)
        assert dump["armed"]
        entries = dump["entries"]
        assert entries, "armed slowlog captured nothing during the soak"
        for entry in entries:
            assert entry["elapsed_ms"] >= 0
            assert entry["profile"]["phases"], \
                f"slowlog entry {entry['query_id']} has an empty waterfall"
        slowest = sorted(entries, key=lambda e: e["elapsed_ms"])[-3:]
        print("slowlog dump (slowest of "
              f"{len(entries)} captured):")
        for entry in reversed(slowest):
            phases = {p["name"]: round(p["duration_ms"], 2)
                      for p in entry["profile"]["phases"]}
            print(f"  {entry['query_id']} {entry['elapsed_ms']:.1f}ms "
                  f"{phases}")
    finally:
        status, data = _call(port, "POST", "/api/v1/developer/slowlog",
                             json.dumps({"threshold_ms": None}).encode())
        assert status == 200
        assert not json.loads(data)["armed"]


def test_convoy_batcher_coalesces_concurrent_burst(api):
    """Same-shape queries arriving together must share device dispatches.

    32 range queries differ ONLY in their (traced-scalar) lower bound, so
    they share one compiled plan but miss the leaf cache individually; with
    the corpus still a single split, each rides the convoy batcher — the
    burst must finish in strictly fewer dispatches than queries."""
    port, node = api
    batcher = node.searcher_context.query_batcher
    queries_before = batcher.num_queries
    dispatches_before = batcher.num_dispatches
    errors: list[str] = []
    barrier = threading.Barrier(THREADS)
    per_thread = 4

    def worker(worker_id: int) -> None:
        try:
            barrier.wait(timeout=30)
            for i in range(per_thread):
                lo = worker_id * per_thread + i  # 0..31, all distinct
                status, data = _call(
                    port, "POST", "/api/v1/_elastic/soak/_search",
                    json.dumps({
                        "query": {"range": {"num": {"gte": lo,
                                                    "lte": 49.0}}},
                        "size": 1}).encode())
                assert status == 200, data[:200]
                assert json.loads(data)["hits"]["total"]["value"] == 50 - lo
        except Exception as exc:  # noqa: BLE001 - collected for report
            errors.append(f"worker {worker_id}: {exc!r}")

    workers = [threading.Thread(target=worker, args=(i,))
               for i in range(THREADS)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(timeout=120)
    assert not any(w.is_alive() for w in workers), "burst deadlocked"
    assert not errors, errors

    query_delta = batcher.num_queries - queries_before
    dispatch_delta = batcher.num_dispatches - dispatches_before
    print(f"\nburst: {query_delta} batcher queries -> "
          f"{dispatch_delta} dispatches")
    assert query_delta == THREADS * per_thread, \
        "burst queries bypassed the batcher (cache hit or fast path?)"
    assert dispatch_delta < query_delta, \
        "concurrent same-shape queries never coalesced into a batch"

    # the exported metrics must tell the same story as the instance
    # counters: operators read qw_search_batcher_* — not internals
    assert SEARCH_BATCHER_QUERIES_TOTAL.get() >= batcher.num_queries
    assert SEARCH_BATCHER_DISPATCHES_TOTAL.get() >= batcher.num_dispatches
    assert SEARCH_BATCHER_RATIO.get() > 1.0, \
        "batching ratio gauge never saw a coalesced dispatch"

    # queue-wait histogram: one observation per dispatched rider, finite
    # tail (the convoy window is bounded by real dispatch latency)
    wait_p50 = SEARCH_BATCHER_QUEUE_WAIT.percentile(0.50)
    wait_p99 = SEARCH_BATCHER_QUEUE_WAIT.percentile(0.99)
    assert wait_p50 is not None and wait_p99 is not None, \
        "no queue-wait observations recorded by the batcher"
    print(f"batcher queue wait: p50<={wait_p50 * 1000:.1f}ms "
          f"p99<={wait_p99 * 1000:.1f}ms "
          f"ratio={SEARCH_BATCHER_RATIO.get():.2f}")
    assert wait_p99 <= 10.0, \
        f"queue-wait p99 bucket {wait_p99}s — riders starved in the convoy"
