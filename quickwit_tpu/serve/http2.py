"""Minimal HTTP/2 (h2c prior-knowledge) server on stdlib sockets — the
transport under the gRPC surface (`grpc_server.py`).

Role of the reference's tonic/hyper HTTP/2 stack (`quickwit-serve/src/
grpc.rs:1`): this build has no HTTP/2 or gRPC library, so the protocol
subset a gRPC server needs is implemented here:

- connection preface + SETTINGS exchange, PING replies, GOAWAY
- HEADERS/CONTINUATION with full HPACK decoding (static + dynamic
  tables, integer prefix coding, Huffman-coded string literals via the
  RFC 7541 Appendix B table in `hpack_huffman.py`) — stock gRPC clients
  (grpc-core Huffman-encodes headers by default) interoperate; see the
  grpcio-client tests
- DATA with flow control (generous WINDOW_UPDATEs keep senders moving)
- response HEADERS + DATA + trailers (gRPC's status trailers), encoded
  as literal-without-indexing raw strings (always-valid HPACK)
"""

from __future__ import annotations

import socket
import ssl
import struct
import threading
from typing import Callable, Optional

FRAME_DATA = 0x0
FRAME_HEADERS = 0x1
FRAME_PRIORITY = 0x2
FRAME_RST_STREAM = 0x3
FRAME_SETTINGS = 0x4
FRAME_PING = 0x6
FRAME_GOAWAY = 0x7
FRAME_WINDOW_UPDATE = 0x8
FRAME_CONTINUATION = 0x9

FLAG_END_STREAM = 0x1
FLAG_END_HEADERS = 0x4
FLAG_ACK = 0x1
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

# RFC 7541 Appendix A static table (1-based)
HPACK_STATIC = [
    (":authority", ""), (":method", "GET"), (":method", "POST"),
    (":path", "/"), (":path", "/index.html"), (":scheme", "http"),
    (":scheme", "https"), (":status", "200"), (":status", "204"),
    (":status", "206"), (":status", "304"), (":status", "400"),
    (":status", "404"), (":status", "500"), ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"), ("accept-language", ""),
    ("accept-ranges", ""), ("accept", ""), ("access-control-allow-origin", ""),
    ("age", ""), ("allow", ""), ("authorization", ""), ("cache-control", ""),
    ("content-disposition", ""), ("content-encoding", ""),
    ("content-language", ""), ("content-length", ""), ("content-location", ""),
    ("content-range", ""), ("content-type", ""), ("cookie", ""), ("date", ""),
    ("etag", ""), ("expect", ""), ("expires", ""), ("from", ""), ("host", ""),
    ("if-match", ""), ("if-modified-since", ""), ("if-none-match", ""),
    ("if-range", ""), ("if-unmodified-since", ""), ("last-modified", ""),
    ("link", ""), ("location", ""), ("max-forwards", ""),
    ("proxy-authenticate", ""), ("proxy-authorization", ""), ("range", ""),
    ("referer", ""), ("refresh", ""), ("retry-after", ""), ("server", ""),
    ("set-cookie", ""), ("strict-transport-security", ""),
    ("transfer-encoding", ""), ("user-agent", ""), ("vary", ""), ("via", ""),
    ("www-authenticate", ""),
]


class Http2Error(RuntimeError):
    pass


class HpackDecoder:
    """RFC 7541 decoder: dynamic table + Huffman string literals."""

    def __init__(self, max_table_size: int = 4096):
        self.dynamic: list[tuple[str, str]] = []
        self.max_size = max_table_size
        self.size = 0

    def _entry(self, index: int) -> tuple[str, str]:
        if index <= 0:
            raise Http2Error("hpack index 0")
        if index <= len(HPACK_STATIC):
            return HPACK_STATIC[index - 1]
        dyn = index - len(HPACK_STATIC) - 1
        if dyn >= len(self.dynamic):
            raise Http2Error(f"hpack index {index} out of table")
        return self.dynamic[dyn]

    def _add(self, name: str, value: str) -> None:
        self.dynamic.insert(0, (name, value))
        self.size += len(name) + len(value) + 32
        while self.size > self.max_size and self.dynamic:
            n, v = self.dynamic.pop()
            self.size -= len(n) + len(v) + 32

    @staticmethod
    def _int(data: bytes, pos: int, prefix_bits: int) -> tuple[int, int]:
        mask = (1 << prefix_bits) - 1
        value = data[pos] & mask
        pos += 1
        if value < mask:
            return value, pos
        shift = 0
        while True:
            b = data[pos]
            pos += 1
            value += (b & 0x7F) << shift
            if not b & 0x80:
                return value, pos
            shift += 7

    def _string(self, data: bytes, pos: int) -> tuple[str, int]:
        huffman = bool(data[pos] & 0x80)
        length, pos = self._int(data, pos, 7)
        raw = data[pos: pos + length]
        pos += length
        if huffman:
            from .hpack_huffman import HuffmanError, huffman_decode
            try:
                raw = huffman_decode(bytes(raw))
            except HuffmanError as exc:
                raise Http2Error(f"bad huffman header literal: {exc}")
        return raw.decode("utf-8", "replace"), pos

    def decode(self, data: bytes) -> list[tuple[str, str]]:
        out: list[tuple[str, str]] = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:                       # indexed
                index, pos = self._int(data, pos, 7)
                out.append(self._entry(index))
            elif b & 0x40:                     # literal, incremental index
                index, pos = self._int(data, pos, 6)
                name = (self._entry(index)[0] if index
                        else None)
                if name is None:
                    name, pos = self._string(data, pos)
                value, pos = self._string(data, pos)
                self._add(name, value)
                out.append((name, value))
            elif b & 0x20:                     # dynamic table size update
                self.max_size, pos = self._int(data, pos, 5)
                while self.size > self.max_size and self.dynamic:
                    n, v = self.dynamic.pop()
                    self.size -= len(n) + len(v) + 32
            else:                              # literal, no/never index
                index, pos = self._int(data, pos, 4)
                name = self._entry(index)[0] if index else None
                if name is None:
                    name, pos = self._string(data, pos)
                value, pos = self._string(data, pos)
                out.append((name, value))
        return out


def hpack_encode_raw(headers: list[tuple[str, str]]) -> bytes:
    """Literal-without-indexing, raw strings — minimal always-valid
    HPACK (what the server emits and the in-repo client sends)."""
    out = bytearray()
    for name, value in headers:
        out.append(0x00)
        n = name.encode()
        v = value.encode()
        out += _hpack_int(len(n), 7) + n
        out += _hpack_int(len(v), 7) + v
    return bytes(out)


def _hpack_int(value: int, prefix_bits: int) -> bytes:
    mask = (1 << prefix_bits) - 1
    if value < mask:
        return bytes([value])
    out = bytearray([mask])
    value -= mask
    while value >= 0x80:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def read_exact_from(sock: socket.socket, n: int) -> bytes:
    """recv() until exactly n bytes (shared by server and client)."""
    chunks = bytearray()
    while len(chunks) < n:
        chunk = sock.recv(n - len(chunks))
        if not chunk:
            raise Http2Error("connection closed")
        chunks += chunk
    return bytes(chunks)


def read_frame(read_exact) -> tuple[int, int, int, bytes]:
    header = read_exact(9)
    length = int.from_bytes(header[:3], "big")
    frame_type = header[3]
    flags = header[4]
    stream_id = int.from_bytes(header[5:9], "big") & 0x7FFFFFFF
    payload = read_exact(length) if length else b""
    return frame_type, flags, stream_id, payload


def frame(frame_type: int, flags: int, stream_id: int, payload: bytes) -> bytes:
    return (len(payload).to_bytes(3, "big") + bytes([frame_type, flags])
            + stream_id.to_bytes(4, "big") + payload)


class _Stream:
    def __init__(self, stream_id: int):
        self.stream_id = stream_id
        self.header_block = bytearray()
        self.headers: Optional[list[tuple[str, str]]] = None
        self.data = bytearray()
        self.headers_done = False
        self.ended = False


def _tls_duplex_bridge(tls_sock) -> socket.socket:
    """Bridge a server-side SSLSocket to a plaintext socketpair pumped by
    a single owner thread, and return the plaintext end.

    Why: the h2 connection logic is full-duplex — one thread blocks in
    the frame-read loop while dispatch threads send response frames — and
    OpenSSL does not allow SSL_read and SSL_write to run concurrently on
    one SSL object (a TLS1.3 KeyUpdate processed inside SSL_read while
    another thread is mid SSL_write corrupts the cipher state). Every SSL
    call below happens on the pump thread alone; the h2 code sees an
    ordinary full-duplex socket."""
    import select as select_mod

    plain, inner = socket.socketpair()
    tls_sock.settimeout(0)   # non-blocking: the pump multiplexes
    inner.settimeout(0)
    chunk = 1 << 16
    high_water = 1 << 20     # stop draining a side whose peer is slow

    def pump() -> None:
        # bytearrays: `del buf[:sent]` keeps partial drains O(n) (bytes
        # slicing would re-copy the tail on every partial send)
        to_tls = bytearray()    # from the h2 side, awaiting SSL_write
        to_inner = bytearray()  # decrypted, awaiting delivery to h2
        tls_eof = inner_eof = False
        # non-blocking SSL: a recv can demand socket WRITABILITY and a
        # send can demand READABILITY (key updates / renegotiation)
        recv_wants_write = send_wants_read = False
        try:
            while not (tls_eof and not to_inner) \
                    and not (inner_eof and not to_tls):
                rlist, wlist = [], []
                read_tls = (not tls_eof and len(to_inner) < high_water
                            and not recv_wants_write)
                if read_tls or send_wants_read:
                    rlist.append(tls_sock)
                if to_tls or recv_wants_write:
                    wlist.append(tls_sock)
                if not inner_eof and len(to_tls) < high_water:
                    rlist.append(inner)
                if to_inner:
                    wlist.append(inner)
                readable, writable, _ = select_mod.select(
                    rlist, wlist, [], 30.0)
                if not readable and not writable:
                    continue  # idle heartbeat tick
                tls_ready_r = tls_sock in readable
                tls_ready_w = tls_sock in writable
                if (not tls_eof and (tls_ready_r or
                                     (recv_wants_write and tls_ready_w))):
                    recv_wants_write = False
                    try:
                        while True:  # drain the SSL-internal buffer too
                            data = tls_sock.recv(chunk)
                            if not data:
                                tls_eof = True
                                break
                            to_inner += data
                            if not tls_sock.pending():
                                break
                    except ssl.SSLWantReadError:
                        pass
                    except ssl.SSLWantWriteError:
                        recv_wants_write = True
                if to_tls and (tls_ready_w or
                               (send_wants_read and tls_ready_r)):
                    send_wants_read = False
                    try:
                        sent = tls_sock.send(bytes(to_tls))
                        del to_tls[:sent]
                    except ssl.SSLWantWriteError:
                        pass
                    except ssl.SSLWantReadError:
                        send_wants_read = True
                if inner in readable:
                    data = inner.recv(chunk)
                    if not data:
                        inner_eof = True
                    else:
                        to_tls += data
                if to_inner and inner in writable:
                    sent = inner.send(to_inner)
                    del to_inner[:sent]
        except (OSError, ssl.SSLError):
            pass
        finally:
            for sock in (tls_sock, inner):
                try:
                    sock.close()
                except OSError:
                    pass

    # qwlint: disable-next-line=QW003 - byte-pump between the TLS and
    # plaintext halves of one socket; carries frames, not queries
    # qwlint: disable-next-line=QW008 - serve-layer transport infrastructure
    # (sockets, real IO) outside the DST-raced path; gating it would block the
    # token on real IO
    threading.Thread(target=pump, daemon=True,
                     name="h2-tls-pump").start()
    return plain


class Http2Server:
    """Threaded h2c server: one thread per connection, streams dispatched
    to `handler(headers, body) -> (response_headers, body_chunks,
    trailers)` as they END_STREAM."""

    def __init__(self, handler: Callable, host: str = "127.0.0.1",
                 port: int = 0, ssl_context=None):
        self.handler = handler
        # with an ssl_context the listener speaks HTTP/2 over TLS instead
        # of h2c — the TLS-cluster binary plane (ALPN h2 is baked into
        # the context by its builder, server_ssl_context(alpn=["h2"]))
        self._ssl_context = ssl_context
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(16)
        self.host, self.port = self._server.getsockname()
        self._running = True
        # qwlint: disable-next-line=QW003 - listener accept loop: query
        # context is established per-request from the payload downstream
        # (deadline_millis -> deadline_scope), never inherited from here
        # qwlint: disable-next-line=QW008 - serve-layer transport
        # infrastructure (sockets, real IO) outside the DST-raced path; gating
        # it would block the token on real IO
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass

    def _serve(self) -> None:
        while self._running:
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return
            # qwlint: disable-next-line=QW003 - connection thread; see
            # listener note above (context comes from each request)
            # qwlint: disable-next-line=QW008 - serve-layer transport
            # infrastructure (sockets, real IO) outside the DST-raced path;
            # gating it would block the token on real IO
            threading.Thread(target=self._connection, args=(conn,),
                             daemon=True).start()

    def _connection(self, conn: socket.socket) -> None:
        if self._ssl_context is not None:
            # handshake on the connection thread, BOUNDED: a silent or
            # stalled client must neither wedge the accept loop nor pin
            # this thread/fd forever (same 10s bound as the REST plane)
            try:
                conn.settimeout(10.0)
                conn = self._ssl_context.wrap_socket(conn, server_side=True)
                conn.settimeout(None)  # long-lived h2 connection
            except (OSError, ValueError):
                try:
                    conn.close()
                except OSError:
                    pass
                return
            # h2 is full-duplex (this reader thread + dispatch threads
            # writing responses), but OpenSSL forbids concurrent
            # SSL_read/SSL_write on one SSL object — bridge the TLS
            # socket to a plaintext socketpair owned by ONE pump thread
            conn = _tls_duplex_bridge(conn)
        state = _ConnState(conn)

        def read_exact(n: int) -> bytes:
            return read_exact_from(conn, n)

        send = state.send_raw
        try:
            if read_exact(len(PREFACE)) != PREFACE:
                return
            send(frame(FRAME_SETTINGS, 0, 0, b""))
            decoder = HpackDecoder()
            streams: dict[int, _Stream] = {}
            while True:
                frame_type, flags, stream_id, payload = read_frame(read_exact)
                if frame_type == FRAME_SETTINGS:
                    if not flags & FLAG_ACK:
                        state.apply_settings(payload)
                        send(frame(FRAME_SETTINGS, FLAG_ACK, 0, b""))
                    continue
                if frame_type == FRAME_PING:
                    if not flags & FLAG_ACK:
                        send(frame(FRAME_PING, FLAG_ACK, 0, payload))
                    continue
                if frame_type == FRAME_GOAWAY:
                    return
                if frame_type == FRAME_WINDOW_UPDATE:
                    increment = struct.unpack(">I", payload)[0] & 0x7FFFFFFF
                    state.add_window(stream_id, increment)
                    continue
                if frame_type in (FRAME_PRIORITY, FRAME_RST_STREAM):
                    continue
                if frame_type in (FRAME_HEADERS, FRAME_CONTINUATION):
                    stream = streams.setdefault(stream_id,
                                                _Stream(stream_id))
                    block = payload
                    if frame_type == FRAME_HEADERS:
                        if flags & FLAG_PADDED:
                            pad = block[0]
                            block = block[1: len(block) - pad]
                        if flags & FLAG_PRIORITY:
                            block = block[5:]
                    stream.header_block += block
                    if flags & FLAG_END_HEADERS:
                        stream.headers = decoder.decode(
                            bytes(stream.header_block))
                        stream.headers_done = True
                    if flags & FLAG_END_STREAM:
                        stream.ended = True
                elif frame_type == FRAME_DATA:
                    stream = streams.setdefault(stream_id,
                                                _Stream(stream_id))
                    block = payload
                    if flags & FLAG_PADDED:
                        pad = block[0]
                        block = block[1: len(block) - pad]
                    stream.data += block
                    # generous flow control: replenish both windows
                    if block:
                        increment = struct.pack(">I", len(block))
                        send(frame(FRAME_WINDOW_UPDATE, 0, 0, increment)
                             + frame(FRAME_WINDOW_UPDATE, 0, stream_id,
                                     increment))
                    if flags & FLAG_END_STREAM:
                        stream.ended = True
                if stream_id and stream_id in streams:
                    stream = streams[stream_id]
                    if stream.ended and stream.headers_done:
                        del streams[stream_id]
                        # qwlint: disable-next-line=QW003 - per-stream
                        # dispatch; the handler binds context from the
                        # decoded request, not from the reader thread
                        # qwlint: disable-next-line=QW008 - serve-layer
                        # transport infrastructure (sockets, real IO) outside
                        # the DST-raced path; gating it would block the token
                        # on real IO
                        threading.Thread(
                            target=self._dispatch,
                            args=(state, stream), daemon=True).start()
        except (Http2Error, OSError, IndexError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, state: "_ConnState", stream: _Stream) -> None:
        try:
            response_headers, body_chunks, trailers = self.handler(
                stream.headers or [], bytes(stream.data))
        # qwlint: disable-next-line=QW004 - transport's last-resort 500:
        # typed exceptions are mapped to statuses by the gRPC/REST layers
        # above; anything reaching here is a handler bug, and raising
        # would kill the shared connection for unrelated streams
        except Exception:  # noqa: BLE001 - connection must survive
            response_headers = [(":status", "500")]
            body_chunks = []
            trailers = []
        header_flags = FLAG_END_HEADERS
        if not body_chunks and not trailers:
            header_flags |= FLAG_END_STREAM
        state.send_raw(frame(FRAME_HEADERS, header_flags, stream.stream_id,
                             hpack_encode_raw(response_headers)))
        for chunk in body_chunks:
            state.send_data(stream.stream_id, chunk)
        if trailers:
            state.send_raw(
                frame(FRAME_HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM,
                      stream.stream_id, hpack_encode_raw(trailers)))
        elif body_chunks:
            state.send_raw(frame(FRAME_DATA, FLAG_END_STREAM,
                                 stream.stream_id, b""))


class _ConnState:
    """Per-connection write side: serialized writes, the peer's
    SETTINGS_MAX_FRAME_SIZE, and flow-control send windows (connection +
    per stream, RFC 7540 §5.2/§6.9) — DATA is split to the frame-size
    limit and blocks until window is available."""

    INITIAL_WINDOW = 65535

    def __init__(self, conn: socket.socket):
        self._conn = conn
        # qwlint: disable-next-line=QW008 - serve-layer transport
        # infrastructure (sockets, real IO) outside the DST-raced path; gating
        # it would block the token on real IO
        self._lock = threading.Lock()
        # qwlint: disable-next-line=QW008 - serve-layer transport
        # infrastructure (sockets, real IO) outside the DST-raced path; gating
        # it would block the token on real IO
        self._window_cv = threading.Condition(self._lock)
        self.max_frame_size = 16384
        self._initial_stream_window = self.INITIAL_WINDOW
        self._conn_window = self.INITIAL_WINDOW
        self._stream_windows: dict[int, int] = {}

    def send_raw(self, data: bytes) -> None:
        with self._lock:
            self._conn.sendall(data)

    def apply_settings(self, payload: bytes) -> None:
        with self._window_cv:
            for i in range(0, len(payload) - 5, 6):
                ident = int.from_bytes(payload[i: i + 2], "big")
                value = int.from_bytes(payload[i + 2: i + 6], "big")
                if ident == 0x5:
                    self.max_frame_size = max(16384,
                                              min(value, (1 << 24) - 1))
                elif ident == 0x4:
                    delta = value - self._initial_stream_window
                    self._initial_stream_window = value
                    for sid in self._stream_windows:
                        self._stream_windows[sid] += delta
            self._window_cv.notify_all()

    def add_window(self, stream_id: int, increment: int) -> None:
        with self._window_cv:
            if stream_id == 0:
                self._conn_window += increment
            else:
                self._stream_windows[stream_id] = self._stream_windows.get(
                    stream_id, self._initial_stream_window) + increment
            self._window_cv.notify_all()

    def send_data(self, stream_id: int, data: bytes,
                  timeout: float = 30.0) -> None:
        offset = 0
        while offset < len(data):
            with self._window_cv:
                self._stream_windows.setdefault(
                    stream_id, self._initial_stream_window)
                budget = min(self._conn_window,
                             self._stream_windows[stream_id],
                             self.max_frame_size)
                if budget <= 0:
                    if not self._window_cv.wait(timeout=timeout):
                        raise Http2Error(
                            "flow-control window exhausted (peer sent no "
                            "WINDOW_UPDATE)")
                    continue
                chunk = data[offset: offset + budget]
                offset += len(chunk)
                self._conn_window -= len(chunk)
                self._stream_windows[stream_id] -= len(chunk)
                self._conn.sendall(frame(FRAME_DATA, 0, stream_id, chunk))
