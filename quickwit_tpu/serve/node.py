"""Node bootstrap + service wiring.

Role of the reference's `serve_quickwit` (`quickwit-serve/src/lib.rs:557`):
instantiate the services a node's roles require — searcher, indexer,
metastore, janitor — over a shared storage resolver and cluster membership,
and wire remote clients (HTTP) for peers. A node runs any subset of roles
(`lib.rs:566-700`); single-process all-roles is the default.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from ..common.clock import get_clock, monotonic as _clock_monotonic
from ..cluster.membership import Cluster, ClusterChange, ClusterMember
from ..indexing.merge import MergeExecutor, merge_policy_from_config
from ..indexing.pipeline import IndexingPipeline, PipelineParams
from ..indexing.sources import VecSource, make_source
from ..ingest.router import INGEST_API_SOURCE_ID
from ..metastore.base import ListSplitsQuery, Metastore
from ..metastore.file_backed import FileBackedMetastore
from ..models.doc_mapper import DocMapper
from ..models.index_metadata import IndexConfig, IndexMetadata, SourceConfig
from ..models.split_metadata import SplitState
from ..query import ast as Q
from ..search.root import RootSearcher
from ..search.service import LocalSearchClient, SearcherContext, SearchService
from ..storage.base import StorageResolver

logger = logging.getLogger(__name__)

ALL_SERVICES = ("searcher", "indexer", "metastore", "janitor", "control_plane")


@dataclass
class NodeConfig:
    node_id: str = "node-0"
    cluster_id: str = "quickwit-tpu"
    roles: tuple[str, ...] = ALL_SERVICES
    metastore_uri: str = "ram:///qw/metastore"
    default_index_root_uri: str = "ram:///qw/indexes"
    rest_host: str = "127.0.0.1"
    rest_port: int = 7280
    peers: tuple[str, ...] = ()  # "host:port" seeds
    data_dir: Optional[str] = None  # WAL + scratch; tmp dir when unset
    wal_fsync: bool = True
    # TLS (role of quickwit-transport's rustls config): server cert/key
    # enable HTTPS on the REST listener; clusters are homogeneous, so a
    # TLS-enabled node speaks HTTPS to its peers too. `tls_ca_path`
    # verifies peer certs (self-signed deployments); `tls_skip_verify`
    # disables verification (tests only).
    tls_cert_path: Optional[str] = None
    tls_key_path: Optional[str] = None
    tls_ca_path: Optional[str] = None
    tls_skip_verify: bool = False
    # mTLS (reference quickwit-transport `validate_client`): the REST
    # listener REQUIRES peer client certificates signed by tls_ca_path,
    # and peer clients present the node cert/key as their identity
    tls_verify_client: bool = False
    # UDP scuttlebutt gossip (role of chitchat): when enabled, membership
    # disseminates over UDP on the REST port number and the REST heartbeat
    # loop is not started. peer_seeds serve as gossip seeds unchanged.
    gossip_enabled: bool = False
    # ingest v2 chained replication (reference replication_factor): 2 =
    # every persisted batch is synchronously replicated to one follower
    # before the ack; follower replicas promote when the leader dies
    replication_factor: int = 1
    # per-shard ingestion throughput target (MiB/s) driving the shard
    # autoscaling arbiter (reference: DEFAULT_SHARD_THROUGHPUT_LIMIT)
    max_shard_throughput_mib: float = 5.0
    # self-tracing (reference: quickwit-telemetry-exporters, opt-in via
    # QW_ENABLE_OPENTELEMETRY_OTLP_EXPORTER there): export the node's own
    # request spans into its own otel-traces index
    self_tracing: bool = False
    # cooperative indexing (reference cooperative_indexing.rs): WAL-drain
    # pipelines take phase-spread turns over each index's commit window,
    # at most max_concurrent_pipelines building splits at once. Off by
    # default: every tick drains every index immediately.
    cooperative_indexing: bool = False
    max_concurrent_pipelines: int = 3
    # serverless offload (reference: quickwit-lambda leaf offload): cold
    # splits beyond offload_max_local_splits per leaf request fan out over
    # an elastic worker pool (quickwit_tpu/offload/) — any servers
    # speaking the internal leaf-search protocol (peer nodes, FaaS
    # workers). `offload` is the pool config dict (keys: endpoints,
    # max_local_splits, task_splits, hedging/health/autoscale knobs);
    # offload_endpoint is the legacy single-endpoint form, normalized to
    # a pool of one. None/None = all-local.
    offload: Optional[dict] = None
    offload_endpoint: Optional[str] = None
    offload_max_local_splits: int = 16
    # disk-resident split cache (reference split_cache/mod.rs): None
    # disables; the dir is created on startup and scanned for leftovers
    split_cache_dir: Optional[str] = None
    split_cache_max_bytes: int = 10 << 30
    split_cache_max_splits: int = 10_000
    # gRPC listener (reference: the tonic server in grpc.rs — OTLP
    # collector services + Jaeger SpanReaderPlugin over stdlib HTTP/2).
    # None = disabled; 0 = ephemeral port.
    grpc_port: Optional[int] = None
    # standalone compactor role: bounded concurrent merge executions
    # (reference compactor_supervisor.rs slots)
    max_concurrent_merges: int = 2
    # multi-tenant workload isolation (tenancy/): per-tenant classes,
    # weights, rate limits and the overload controller. None/absent =
    # tenancy disabled, the tenant-blind neutral path.
    tenancy: Optional[dict] = None

    @property
    def tls_enabled(self) -> bool:
        return self.tls_cert_path is not None and self.tls_key_path is not None

    def server_ssl_context(self, alpn=None):
        """Server-side TLS context (role of quickwit-transport's rustls
        server config), shared by the REST listener and the gRPC plane
        (the latter passes alpn=["h2"])."""
        if not self.tls_enabled:
            return None
        import ssl
        context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        context.load_cert_chain(self.tls_cert_path, self.tls_key_path)
        if self.tls_verify_client:
            if not self.tls_ca_path:
                raise ValueError(
                    "rest.tls.verify_client requires rest.tls.ca_path "
                    "(the CA that signs peer client certificates)")
            # mTLS: only peers holding a CA-signed client cert connect
            context.verify_mode = ssl.CERT_REQUIRED
            context.load_verify_locations(cafile=self.tls_ca_path)
        if alpn:
            try:
                context.set_alpn_protocols(alpn)
            except NotImplementedError:
                pass
        return context

    def client_tls_kwargs(self) -> dict:
        """kwargs for HttpSearchClient toward peers of this cluster."""
        if not self.tls_enabled:
            return {}
        kwargs = {"tls": True, "ca_path": self.tls_ca_path,
                  "skip_verify": self.tls_skip_verify}
        if self.tls_verify_client:
            kwargs["client_cert_path"] = self.tls_cert_path
            kwargs["client_key_path"] = self.tls_key_path
        return kwargs


def _validate_doc_mapping(doc_mapper: DocMapper) -> None:
    """Create-time schema validation (reference: doc-mapper build errors,
    `tag_pruning.rs` allowed tag types + default-search-field checks).
    Raises ValueError → HTTP 400."""
    from ..models.doc_mapper import FieldType
    for tag in doc_mapper.tag_fields:
        fm = doc_mapper.field(tag)
        if fm is None:
            raise ValueError(f"tag field {tag!r} is not a mapped field")
        allowed = (fm.type in (FieldType.U64, FieldType.I64)
                   or (fm.type is FieldType.TEXT and fm.tokenizer == "raw"))
        if not allowed:
            raise ValueError(
                f"tag field {tag!r} must be a raw-tokenized text, u64, or "
                f"i64 field (got {fm.type.value}"
                f"{'/' + fm.tokenizer if fm.type is FieldType.TEXT else ''})")
    if doc_mapper.partition_key:
        # malformed expressions already raised RoutingExprError (a
        # ValueError → 400) in DocMapper.__post_init__; here we only
        # catch typos that can never resolve. Routing evaluates on the
        # RAW doc, so lenient/dynamic modes and subpaths of mapped JSON
        # fields resolve at runtime — only strict mode pins the schema.
        if doc_mapper.mode == "strict":
            for field in doc_mapper._routing_expr.field_names():
                if doc_mapper.field(field) is not None:
                    continue
                parts = field.split(".")
                # subpaths of a mapped JSON field hold arbitrary keys
                # even under strict mode; a PARENT path of concretely
                # mapped fields ("resource" over "resource.service")
                # also resolves at runtime (routing hashes the object)
                json_ancestor = any(
                    (fm := doc_mapper.field(".".join(parts[:i])))
                    is not None and fm.type is FieldType.JSON
                    for i in range(1, len(parts)))
                mapped_descendant = any(
                    fm.name.startswith(field + ".")
                    for fm in doc_mapper.field_mappings)
                if not json_ancestor and not mapped_descendant:
                    raise ValueError(
                        f"partition_key references unknown field `{field}`")
    for field in doc_mapper.default_search_fields:
        fm = doc_mapper.field(field)
        if fm is None:
            if (doc_mapper.mode == "dynamic"
                    and not doc_mapper.shadows_concrete_field(field)):
                # resolvable dynamically — but only if dynamic fields are
                # indexed (reference: dynamic default-field validation)
                fm = doc_mapper.dynamic_field(field)
            else:
                raise ValueError(
                    f"unknown default search field `{field}`")
        if not fm.indexed:
            raise ValueError(
                f"default search field `{field}` is not indexed")


def _require_string_list(name: str, value) -> tuple:
    if not isinstance(value, list) \
            or not all(isinstance(v, str) for v in value):
        raise ValueError(f"{name} must be a list of strings")
    return tuple(value)


class IndexService:
    """Index management operations (role of `quickwit-index-management`)."""

    def __init__(self, metastore: Metastore, storage_resolver: StorageResolver,
                 default_index_root_uri: str):
        self.metastore = metastore
        self.storage_resolver = storage_resolver
        self.default_index_root_uri = default_index_root_uri

    def create_index(self, index_config_json: dict[str, Any]) -> IndexMetadata:
        if not isinstance(index_config_json, dict):
            raise ValueError("index config must be a JSON object")
        index_id = index_config_json.get("index_id")
        if not isinstance(index_id, str) or not index_id \
                or not index_id.replace("-", "").replace("_", "").isalnum():
            raise ValueError(f"invalid index id {index_id!r}")
        for key in ("search_settings", "indexing_settings", "retention"):
            value = index_config_json.get(key)
            if value is not None and not isinstance(value, dict):
                raise ValueError(f"{key} must be a JSON object")
        doc_mapping = index_config_json.get("doc_mapping", {})
        doc_mapper = DocMapper.from_dict(doc_mapping)
        # search_settings.default_search_fields (reference config shape)
        # overrides/augments the doc_mapping-level list
        search_settings = index_config_json.get("search_settings") or {}
        fields = search_settings.get("default_search_fields")
        if fields:
            doc_mapper.default_search_fields = _require_string_list(
                "default_search_fields", fields)
        _validate_doc_mapping(doc_mapper)
        index_uri = index_config_json.get(
            "index_uri", f"{self.default_index_root_uri}/{index_id}")
        commit_timeout = index_config_json.get(
            "indexing_settings", {}).get("commit_timeout_secs", 60)
        if not isinstance(commit_timeout, (int, float)) \
                or commit_timeout <= 0:
            # cooperative indexing divides by this; a zero would halt the
            # node's whole WAL-drain loop
            raise ValueError(
                f"commit_timeout_secs must be positive, got {commit_timeout!r}")
        config = IndexConfig(
            index_id=index_id, index_uri=index_uri, doc_mapper=doc_mapper,
            commit_timeout_secs=commit_timeout,
            split_num_docs_target=index_config_json.get(
                "indexing_settings", {}).get("split_num_docs_target", 10_000_000),
            merge_policy=index_config_json.get(
                "indexing_settings", {}).get("merge_policy", {"type": "stable_log"}),
        )
        retention = index_config_json.get("retention")
        if retention:
            if not isinstance(retention.get("period"), str):
                raise ValueError(
                    'retention requires {"period": "<n> days", ...}')
            from ..models.index_metadata import RetentionPolicy
            config.retention = RetentionPolicy(
                period_seconds=_parse_period(retention["period"]),
                schedule=retention.get("schedule", "hourly"))
        metadata = IndexMetadata(
            # ULID-style unique incarnation (reference uses a ULID suffix):
            # wall-clock-derived values collide on delete+recreate within
            # the same second, defeating uid-based conflict detection.
            index_uid=f"{index_id}:{uuid.uuid4().hex[:13]}",
            index_config=config,
            sources={INGEST_API_SOURCE_ID: SourceConfig(INGEST_API_SOURCE_ID, "vec")},
        )
        self.metastore.create_index(metadata)
        return metadata

    def update_index(self, index_id: str,
                     update_json: dict[str, Any]) -> IndexMetadata:
        """Live index-config update (reference `update_index`,
        `index_api/rest_handler.rs` PUT route): search settings,
        retention, indexing settings, and APPEND-ONLY doc-mapping
        changes — existing fields must stay byte-identical (old splits
        were built with them); new fields only apply to future splits,
        which is exactly the reference's compatibility rule."""
        metadata = self.metastore.index_metadata(index_id)
        current = metadata.index_config
        for key in ("search_settings", "indexing_settings"):
            if update_json.get(key) is not None \
                    and not isinstance(update_json[key], dict):
                raise ValueError(f"{key} must be a JSON object")
        # round-trip copy: index_metadata() returns the metastore's LIVE
        # cached object — mutating it before validation would corrupt
        # the running config on a rejected request
        doc_mapper = DocMapper.from_dict(current.doc_mapper.to_dict())
        if "doc_mapping" in update_json:
            new_mapper = DocMapper.from_dict(update_json["doc_mapping"])
            old_fields = {f.name: f.to_dict()
                          for f in current.doc_mapper.field_mappings}
            new_fields = {f.name: f.to_dict()
                          for f in new_mapper.field_mappings}
            for name, old in old_fields.items():
                if name not in new_fields:
                    raise ValueError(
                        f"doc_mapping update cannot REMOVE field "
                        f"{name!r} (existing splits were built with it)")
                if new_fields[name] != old:
                    raise ValueError(
                        f"doc_mapping update cannot CHANGE field "
                        f"{name!r} (existing splits were built with it); "
                        "only new fields may be appended")
            if new_mapper.timestamp_field != \
                    current.doc_mapper.timestamp_field:
                raise ValueError("timestamp_field is immutable")
            if not new_mapper.default_search_fields:
                new_mapper.default_search_fields = \
                    current.doc_mapper.default_search_fields
            doc_mapper = new_mapper
        search_settings = update_json.get("search_settings") or {}
        if "default_search_fields" in search_settings:
            doc_mapper.default_search_fields = _require_string_list(
                "default_search_fields",
                search_settings["default_search_fields"])
        _validate_doc_mapping(doc_mapper)
        indexing = update_json.get("indexing_settings") or {}
        commit_timeout = indexing.get(
            "commit_timeout_secs", current.commit_timeout_secs)
        if not isinstance(commit_timeout, (int, float)) \
                or commit_timeout <= 0:
            raise ValueError(
                f"commit_timeout_secs must be positive, got "
                f"{commit_timeout!r}")
        merge_policy = indexing.get("merge_policy", current.merge_policy)
        if not isinstance(merge_policy, dict):
            raise ValueError("merge_policy must be a JSON object")
        # reject now, not on every future merge pass
        merge_policy_from_config(merge_policy)
        config = IndexConfig(
            index_id=current.index_id,          # immutable
            index_uri=current.index_uri,        # immutable
            doc_mapper=doc_mapper,
            commit_timeout_secs=commit_timeout,
            split_num_docs_target=indexing.get(
                "split_num_docs_target", current.split_num_docs_target),
            merge_policy=merge_policy,
            retention=current.retention,
        )
        if "retention" in update_json:
            retention = update_json["retention"]
            if retention is None:
                config.retention = None
            elif not isinstance(retention, dict) \
                    or not isinstance(retention.get("period"), str):
                raise ValueError(
                    'retention must be null or {"period": "<n> days", '
                    '"schedule"?: ...}')
            else:
                from ..models.index_metadata import RetentionPolicy
                config.retention = RetentionPolicy(
                    period_seconds=_parse_period(retention["period"]),
                    schedule=retention.get("schedule", "hourly"))
        self.metastore.update_index_config(metadata.index_uid, config)
        return self.metastore.index_metadata(index_id)

    def delete_index(self, index_id: str) -> list[str]:
        metadata = self.metastore.index_metadata(index_id)
        splits = self.metastore.list_splits(
            ListSplitsQuery(index_uids=[metadata.index_uid]))
        storage = self.storage_resolver.resolve(metadata.index_config.index_uri)
        removed = []
        for split in splits:
            try:
                storage.delete(f"{split.metadata.split_id}.split")
                removed.append(split.metadata.split_id)
            except Exception:  # noqa: BLE001 - missing files are fine
                pass
        self.metastore.delete_index(metadata.index_uid)
        return removed


def _parse_period(period: str) -> int:
    period = period.strip()
    units = {"seconds": 1, "minutes": 60, "hours": 3600, "days": 86400,
             "weeks": 7 * 86400}
    parts = period.split()
    if len(parts) == 2 and parts[1] in units:
        return int(parts[0]) * units[parts[1]]
    raise ValueError(f"cannot parse retention period {period!r}")


class Node:
    """A running node: metastore + searcher + indexer + janitor services
    according to roles, plus the client pool for distributed search."""

    def __init__(self, config: NodeConfig,
                 storage_resolver: Optional[StorageResolver] = None):
        from ..utils.compile_cache import enable_persistent_compile_cache
        enable_persistent_compile_cache()
        self.config = config
        if config.tenancy is not None:
            # arm the process-global registry from the node config's
            # `tenancy` section (absent config leaves whatever state the
            # registry already has — embedded/test nodes stay neutral)
            from ..tenancy import configure_tenancy
            configure_tenancy(config.tenancy)
        self.storage_resolver = storage_resolver or StorageResolver.default()
        if config.metastore_uri.startswith("sqlite://"):
            # SQL backend (reference: PostgresqlMetastore): transactional
            # publish on a database instead of object-store CAS
            from ..metastore.sql import SqlMetastore
            self.metastore: Metastore = SqlMetastore(
                config.metastore_uri[len("sqlite://"):])
        else:
            self.metastore = FileBackedMetastore(
                self.storage_resolver.resolve(config.metastore_uri))
        self.cluster = Cluster(
            config.node_id, config.roles,
            rest_endpoint=f"{config.rest_host}:{config.rest_port}")
        self.split_cache = None
        if config.split_cache_dir:
            from ..storage.split_cache import DiskSplitCache
            self.split_cache = DiskSplitCache(
                config.split_cache_dir, self.storage_resolver,
                max_bytes=config.split_cache_max_bytes,
                max_splits=config.split_cache_max_splits)
            self.split_cache.start()
        self.searcher_context = SearcherContext(
            self.storage_resolver,
            offload=config.offload,
            offload_endpoint=config.offload_endpoint,
            offload_max_local_splits=config.offload_max_local_splits,
            split_cache=self.split_cache)
        self.search_service = SearchService(self.searcher_context, config.node_id)
        self.index_service = IndexService(self.metastore, self.storage_resolver,
                                          config.default_index_root_uri)
        self.clients: dict[str, Any] = {
            config.node_id: LocalSearchClient(self.search_service)}
        # node_id -> (grpc_endpoint, rest_endpoint) the client was built
        # for, so role-only membership updates don't churn live sockets
        self._client_endpoints: dict[str, tuple] = {}
        self._transform_cache: dict[tuple, Any] = {}
        # cached external-source clients (kafka connections survive passes)
        self._external_sources: dict[tuple, Any] = {}
        # one pass at a time per (index_uid, source_id): a REST-triggered
        # pass and the background tick must not drain the same cached
        # source concurrently (the per-source pipeline-actor guarantee)
        self._source_pass_locks: dict[tuple, threading.Lock] = {}
        self.root_searcher = RootSearcher(
            self.metastore, self.clients,
            nodes_provider=lambda: self.cluster.nodes_with_role("searcher"))
        self.cluster.subscribe(self._on_cluster_change)
        # qwlint: disable-next-line=QW008 - serve-layer transport
        # infrastructure (sockets, real IO) outside the DST-raced path; gating
        # it would block the token on real IO
        self._lock = threading.Lock()
        # ingest v2: WAL-backed write path (router -> ingester shards)
        import os
        import tempfile
        from ..ingest.ingester import Ingester
        from ..ingest.router import IngestRouter
        data_dir = config.data_dir or tempfile.mkdtemp(prefix="qwt-data-")
        self.data_dir = data_dir
        self.ingester = Ingester(
            os.path.join(data_dir, "wal"), fsync=config.wal_fsync,
            replicate_to=(self._replicate_batch
                          if config.replication_factor > 1 else None))
        if config.replication_factor > 1:
            self.ingester.on_truncate = self._replica_truncate
        self.ingest_router = IngestRouter(
            self.ingester, shard_prefix=config.node_id,
            get_or_create_shards=self._live_open_shards)
        from ..control_plane.scheduler import IndexingScheduler
        self.indexing_scheduler = IndexingScheduler()
        # None until a control-plane plan is first applied (legacy
        # rendezvous election gates external sources until then).
        self._applied_indexing_tasks: Optional[list[dict]] = None
        self._assigned_sources: set[tuple[str, str]] = set()
        from ..control_plane.arbiter import (ScalingArbiter, ScalingPermits,
                                             ShardRateTracker)
        self.scaling_arbiter = ScalingArbiter(
            max_shard_throughput_mib=config.max_shard_throughput_mib)
        self.scaling_permits = ScalingPermits()
        self.shard_rate_tracker = ShardRateTracker()
        from ..search.scroll import ScrollStore
        self.scroll_store = ScrollStore()
        from .otel import OtelService
        self.otel = OtelService(self)
        self.grpc_server = None
        if config.grpc_port is not None:
            from .grpc_server import GrpcServer
            self.grpc_server = GrpcServer(
                self, host=config.rest_host, port=config.grpc_port,
                ssl_context=config.server_ssl_context(alpn=["h2"]))
        # standalone compactor role (reference quickwit-compaction):
        # planner + bounded supervisor; when any alive compactor exists,
        # indexers stop running merges themselves
        self.compactor = None
        self.compaction_planner = None
        if "compactor" in config.roles:
            from ..compaction import CompactionPlanner, CompactorSupervisor
            self.compactor = CompactorSupervisor(
                self.metastore, self.storage_resolver,
                node_id=config.node_id,
                max_concurrent_merges=config.max_concurrent_merges)
            self.compaction_planner = CompactionPlanner(self.metastore)
        # cooperative indexing state (shared across every index pipeline)
        # qwlint: disable-next-line=QW008 - serve-layer transport
        # infrastructure (sockets, real IO) outside the DST-raced path; gating
        # it would block the token on real IO
        self._coop_permits = threading.Semaphore(
            max(1, config.max_concurrent_pipelines))
        self._coop_cycles: dict[str, Any] = {}
        self._coop_next_wake: dict[str, float] = {}
        self._coop_clock = _clock_monotonic  # process clock; tests/DST swap in a virtual one
        self.pipeline_metrics: dict[str, Any] = {}
        self.span_exporter = None
        self._ensure_span_exporter()

    def _ensure_span_exporter(self) -> None:
        """Create + register the self-tracing exporter if configured.

        Called from __init__ AND start_background_services: stop tears the
        exporter down, so a stop/start cycle must recreate it or the node
        would keep serving with `self_tracing: true` while silently
        exporting nothing."""
        if not self.config.self_tracing or self.span_exporter is not None:
            return
        from ..observability.tracing import TRACER, BatchSpanExporter
        self.span_exporter = BatchSpanExporter(
            self.otel.ingest_traces, service_name="quickwit-tpu",
            node_id=self.config.node_id, scope=self.config.node_id)
        TRACER.add_processor(self.span_exporter)

    def _live_open_shards(self, index_uid: str,
                          source_id: str) -> list[str]:
        """Routing-table resolver: the LIVE open leader shards for the
        source (autoscaling changes the set); falls back to the router's
        static default for the very first batch."""
        from ..ingest.ingester import ShardState
        live = sorted(
            s.shard_id for s in self.ingester.list_shards(index_uid)
            if s.source_id == source_id and s.role == "leader"
            and s.state is ShardState.OPEN)
        return live or self.ingest_router._default_shards(index_uid,
                                                          source_id)

    # ------------------------------------------------------------------
    def _grpc_advertise(self) -> str:
        """This node's gRPC endpoint for peers ("" when disabled). A TLS
        cluster advertises too — the gRPC plane runs h2-over-TLS with the
        same cert/CA/mTLS settings as the REST listener."""
        if self.grpc_server is None:
            return ""
        return f"{self.config.rest_host}:{self.grpc_server.port}"

    def _make_peer_client(self, member: ClusterMember):
        """Search client for one peer: the gRPC plane (binary payloads on a
        persistent HTTP/2 connection — the reference's codegen'd tonic
        client role) when the peer advertises it, JSON/HTTP otherwise.
        Under TLS both planes carry the cluster's TLS settings."""
        if member.grpc_endpoint:
            from .grpc_server import GrpcSearchClient
            return GrpcSearchClient(member.grpc_endpoint,
                                    member.rest_endpoint,
                                    **self.config.client_tls_kwargs())
        from .http_client import HttpSearchClient
        return HttpSearchClient(member.rest_endpoint,
                                **self.config.client_tls_kwargs())

    def _on_cluster_change(self, change: ClusterChange) -> None:
        member = change.member
        if change.kind == "remove":
            if member.node_id != self.config.node_id:
                self._close_client(self.clients.pop(member.node_id, None))
                self._client_endpoints.pop(member.node_id, None)
            return
        if member.node_id == self.config.node_id:
            return
        if "searcher" in member.roles and member.rest_endpoint:
            # replace the client only when the peer's endpoints changed (a
            # rejoin under new ports): closing a live client mid-flight
            # fails in-flight RPCs and trips the circuit breaker, so
            # role-only updates must keep the existing connection
            endpoints = (member.grpc_endpoint, member.rest_endpoint)
            if self._client_endpoints.get(member.node_id) == endpoints \
                    and member.node_id in self.clients:
                return
            # publish the replacement BEFORE closing the old reference: a
            # concurrent search thread that already fetched the old client
            # may still fail, but no thread can fetch an already-closed
            # client from the map
            old = self.clients.get(member.node_id)
            self._client_endpoints[member.node_id] = endpoints
            self.clients[member.node_id] = self._make_peer_client(member)
            self._close_client(old)

    @staticmethod
    def _close_client(client) -> None:
        close = getattr(client, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 - best-effort cleanup
                pass

    # ------------------------------------------------------------------
    # ingest (v1-style: REST batch → immediate split, commit semantics
    # per-request; the WAL-based v2 path lives in quickwit_tpu.ingest)
    def ingest(self, index_id: str, docs: list[dict],
               commit: str = "auto") -> dict[str, Any]:
        metadata = self._metadata_or_template(index_id)
        if not self._source_enabled(metadata, INGEST_API_SOURCE_ID):
            from ..metastore.base import MetastoreError
            raise MetastoreError(
                f"ingest source for index {index_id!r} is disabled",
                kind="failed_precondition")
        doc_mapper = metadata.index_config.doc_mapper
        storage = self.storage_resolver.resolve(metadata.index_config.index_uri)
        params = PipelineParams(
            index_uid=metadata.index_uid,
            source_id=INGEST_API_SOURCE_ID,
            node_id=self.config.node_id,
            split_num_docs_target=metadata.index_config.split_num_docs_target,
        )
        source = VecSource(docs, partition_id=f"ingest-{get_clock().time_ns()}")
        pipeline = IndexingPipeline(
            params, doc_mapper, source, self.metastore, storage,
            transform=self._transform_for(metadata, INGEST_API_SOURCE_ID))
        counters = pipeline.run_to_completion()
        return {"num_docs_for_processing": len(docs),
                "num_ingested_docs": counters.num_docs_processed,
                "num_invalid_docs": counters.num_docs_invalid}

    # source types with their own drive paths (REST ingest / WAL drain)
    _INTERNAL_SOURCE_TYPES = ("vec", "void", "ingest_api", "ingest_v2")

    def run_source_pass(self, index_id: str, source_id: str):
        """Drain one configured EXTERNAL source (file/kafka) through an
        indexing pipeline pass — the role of the reference's per-(index,
        source) pipeline actors under IndexingService
        (`indexing_service.rs:1152`). Checkpoints make each pass resume
        exactly where the last one stopped; source clients are cached so
        broker connections persist across passes."""
        with self._lock:
            pass_lock = self._source_pass_locks.setdefault(
                # qwlint: disable-next-line=QW008 - serve-layer transport
                # infrastructure (sockets, real IO) outside the DST-raced path;
                # gating it would block the token on real IO
                (index_id, source_id), threading.Lock())
        with pass_lock:
            # metadata is read INSIDE the lock: a pass queued behind a
            # running one must see config changes (source deleted /
            # re-pointed) made while it waited
            metadata = self.metastore.index_metadata(index_id)
            return self._run_source_pass_locked(metadata, source_id)

    def _run_source_pass_locked(self, metadata, source_id: str):
        source_config = metadata.sources.get(source_id)
        if (source_config is None or not source_config.enabled
                or source_config.source_type in self._INTERNAL_SOURCE_TYPES):
            # a deleted/disabled source releases its cached client (and
            # its broker sockets) immediately, not at index deletion
            stale = self._external_sources.pop(
                (metadata.index_uid, source_id), None)
            if stale is not None:
                self._close_source(stale[1])
            return None
        # config fingerprint in the key: delete + re-add with the same
        # source_id but a new topic/brokers must not keep consuming the
        # old config through a stale cached client
        fingerprint = json.dumps(
            [source_config.source_type, source_config.params],
            sort_keys=True)
        key = (metadata.index_uid, source_id)
        cached = self._external_sources.get(key)
        if cached is not None and cached[0] != fingerprint:
            self._close_source(cached[1])
            cached = None
        if cached is None:
            cached = (fingerprint,
                      make_source(source_config.source_type,
                                  source_config.params,
                                  resolver=self.storage_resolver))
            self._external_sources[key] = cached
        source = cached[1]
        storage = self.storage_resolver.resolve(
            metadata.index_config.index_uri)
        pipeline = IndexingPipeline(
            PipelineParams(
                index_uid=metadata.index_uid, source_id=source_id,
                node_id=self.config.node_id,
                split_num_docs_target=metadata.index_config
                .split_num_docs_target),
            metadata.index_config.doc_mapper, source, self.metastore,
            storage, transform=self._transform_for(metadata, source_id))
        try:
            return pipeline.run_to_completion()
        except Exception:
            # a broken source connection must not wedge future passes on
            # a stale cached client
            self._external_sources.pop(key, None)
            self._close_source(source)
            raise

    @staticmethod
    def _close_source(source) -> None:
        close = getattr(source, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 - best-effort socket cleanup
                logger.debug("source close failed", exc_info=True)

    def _transform_for(self, metadata: IndexMetadata, source_id: str):
        """Compiled doc transform from the source config's
        `transform: {script: ...}` params, if any (the reference's VRL
        source transforms, doc_processor.rs:94). Compiled once per
        (index, source, script) — the reference compiles VRL at pipeline
        spawn, not per batch."""
        from ..indexing.transform import Transform, transform_script_of
        source = metadata.sources.get(source_id)
        if source is None:
            return None
        script = transform_script_of(source.params)
        if script is None:
            return None
        key = (metadata.index_uid, source_id, script)
        if key not in self._transform_cache:
            self._transform_cache[key] = Transform(script)
        return self._transform_cache[key]

    def _source_enabled(self, metadata: IndexMetadata, source_id: str) -> bool:
        source = metadata.sources.get(source_id)
        return source is None or source.enabled

    def _metadata_or_template(self, index_id: str) -> IndexMetadata:
        """Existing index, or auto-created from a matching index template
        (reference: template matching by index-id patterns)."""
        from ..metastore.base import MetastoreError
        try:
            return self.metastore.index_metadata(index_id)
        except MetastoreError as exc:
            if exc.kind != "not_found":
                raise
            template = getattr(self.metastore, "find_index_template",
                               lambda _i: None)(index_id)
            if template is None:
                raise
            config = dict(template.get("index_config", {}))
            config["index_id"] = index_id
            logger.info("auto-creating index %s from template %s",
                        index_id, template["template_id"])
            try:
                return self.index_service.create_index(config)
            except MetastoreError as create_exc:
                if create_exc.kind != "already_exists":
                    raise
                # lost a concurrent auto-create race: the index exists now
                return self.metastore.index_metadata(index_id)

    # ------------------------------------------------------------------
    def _replicate_batch(self, index_uid: str, source_id: str,
                         shard_id: str, first_position: int,
                         payloads: list[bytes]) -> None:
        """Leader side of chained replication: pick the follower by
        rendezvous on the shard's queue id among OTHER live indexer nodes
        and replicate synchronously; the persist ack implies follower
        durability (reference: replication.rs + persist semantics)."""
        import base64

        from ..common.rendezvous import sort_by_rendezvous_hash
        from ..ingest.ingester import shard_queue_id
        peers = [m for m in self.cluster.members()
                 if m.node_id != self.config.node_id
                 and "indexer" in m.roles and m.rest_endpoint]
        if not peers:
            raise IOError(
                "replication_factor > 1 but no live follower is available")
        queue_id = shard_queue_id(index_uid, source_id, shard_id)
        ordered = sort_by_rendezvous_hash(queue_id,
                                          [m.node_id for m in peers])
        follower = next(m for m in peers if m.node_id == ordered[0])
        recorded = getattr(self, "_recorded_chains", None)
        if recorded is None:
            recorded = self._recorded_chains = {}
        chain = (self.config.node_id, follower.node_id)
        if recorded.get(queue_id) != chain:
            # durable chain registration BEFORE the first batch reaches a
            # new follower: failover promotes only the REGISTERED follower
            # (a rejoined copy with a stale WAL is not eligible), so the
            # record must exist before this follower can hold acked data.
            # A registry write failure fails the persist — acking a batch
            # on an unregistered chain would void the promotion-safety
            # argument (tools/qwmc replication model).
            self.metastore.record_shard_chain(
                index_uid, source_id, shard_id,
                leader=self.config.node_id, follower=follower.node_id)
            recorded[queue_id] = chain
        client = self.clients.get(follower.node_id)
        if client is None:
            # same construction _on_cluster_change would use (gRPC plane
            # when advertised) — a plain HTTP client cached here would
            # otherwise pin this peer to JSON/HTTP forever once the
            # endpoints are recorded. Cache: per-batch client construction
            # would defeat the circuit breaker and pay a TCP/TLS handshake
            # per persist. Recording the endpoints keeps the next no-op
            # gossip update from closing a client mid-replication
            # (_on_cluster_change keeps clients whose endpoints are
            # unchanged).
            client = self._make_peer_client(follower)
            self.clients[follower.node_id] = client
            self._client_endpoints[follower.node_id] = (
                follower.grpc_endpoint, follower.rest_endpoint)

        def send(first: int, batch: list[bytes], reset: bool = False):
            return client.replicate({
                "index_uid": index_uid, "source_id": source_id,
                "shard_id": shard_id, "first_position": first,
                "payloads": [base64.b64encode(p).decode() for p in batch],
                **({"reset": True} if reset else {}),
            })

        from .http_client import HttpStatusError
        try:
            send(first_position, payloads)
            return
        except HttpStatusError as exc:
            if exc.status != 409:
                raise
            gap_body = exc.body
        # gap: a fresh follower (rendezvous re-pick after membership change)
        # is missing earlier records — backfill from the local WAL. When our
        # retained WAL starts past the follower's position (truncated behind
        # the published checkpoint), the follower resets to what we hold:
        # the metastore checkpoint already covers the records below.
        shard = self.ingester.shard(index_uid, source_id, shard_id)
        replica_pos = json.loads(gap_body or b"{}").get(
            "replica_position", 0)
        records = shard.log.read_from(int(replica_pos), max_records=1 << 20)
        if not records:
            raise IOError(f"cannot backfill follower for {shard_id!r}: "
                          "no retained records")
        start = records[0][0]
        send(start, [p for _, p in records], reset=(start > replica_pos))

    def _replica_truncate(self, index_uid: str, source_id: str,
                          shard_id: str, position: int) -> None:
        """Best-effort truncation propagation to the follower (replica
        WALs must not grow without bound while the leader reclaims)."""
        from ..common.rendezvous import sort_by_rendezvous_hash
        from ..ingest.ingester import shard_queue_id
        peers = [m for m in self.cluster.members()
                 if m.node_id != self.config.node_id
                 and "indexer" in m.roles and m.rest_endpoint]
        if not peers:
            return
        queue_id = shard_queue_id(index_uid, source_id, shard_id)
        ordered = sort_by_rendezvous_hash(queue_id,
                                          [m.node_id for m in peers])
        follower = next(m for m in peers if m.node_id == ordered[0])
        client = self.clients.get(follower.node_id)
        if client is None:
            return
        client._post("/internal/replica_truncate", {
            "index_uid": index_uid, "source_id": source_id,
            "shard_id": shard_id, "position": position})

    def _shard_chain(self, shard) -> Optional[dict]:
        """Registered replication chain for the shard, or None when it
        never formed one (or the index is gone)."""
        from ..metastore.base import MetastoreError
        try:
            return self.metastore.shard_chain(shard.index_uid,
                                              shard.source_id,
                                              shard.shard_id)
        except MetastoreError:
            return None

    def _published_floor(self, shard) -> int:
        """Published checkpoint for the shard (exclusive end): everything
        below it is already in published splits."""
        from ..metastore.base import MetastoreError
        from ..metastore.checkpoint import BEGINNING
        try:
            checkpoint = self.metastore.source_checkpoint(shard.index_uid,
                                                          shard.source_id)
        except MetastoreError:
            return 0
        position = checkpoint.position_for(shard.shard_id)
        return 0 if position == BEGINNING else int(position)

    def promote_orphaned_replicas(self, grace_secs: float = 30.0) -> list[str]:
        """Replica shards whose leader node is no longer a live cluster
        member get promoted and drained from here (the reference's
        AdviseResetShards / shard re-open on ingester death). The durable
        chain registry (metastore.shard_chain) names the current leader —
        shard-id prefixes ("{node_id}-shard-NN") only seed it for shards
        that never replicated — and gates the takeover: only the
        REGISTERED follower is eligible, because a copy that merely looks
        healthy may have crashed out of the chain and be missing acked
        batches (qwmc's stale-replica-promotion counterexample). A
        promoted log behind the published checkpoint forward-resets to it,
        or fresh appends would land on already-consumed positions.

        Promotion is irreversible (the old leader's persists are refused
        after it), so it only fires after the leader has been CONTINUOUSLY
        absent for `grace_secs` — a heartbeat blip, GC pause, or this
        node's own fresh restart (empty membership view) must not
        split-brain the shard."""
        alive = {m.node_id for m in self.cluster.members()}
        dead_since = getattr(self, "_leader_dead_since", None)
        if dead_since is None:
            dead_since = self._leader_dead_since = {}
        now = _clock_monotonic()
        promoted = []
        refreshed = False
        for queue_id, shard in self.ingester.replica_shards():
            chain = self._shard_chain(shard)
            if chain is not None and chain.get("leader") == self.config.node_id:
                # a crash between the registry write and the role flip left
                # the record already naming this node: finish the promotion
                if self.ingester.promote_replica(
                        queue_id, min_position=self._published_floor(shard)):
                    promoted.append(shard.shard_id)
                continue
            leader_node = (chain["leader"] if chain is not None
                           else shard.shard_id.rsplit("-shard-", 1)[0])
            if leader_node in alive:
                dead_since.pop(leader_node, None)
                continue
            first_seen_dead = dead_since.setdefault(leader_node, now)
            if now - first_seen_dead < grace_secs:
                continue
            if not refreshed:
                # the takeover decision must read the registry and the
                # checkpoint fresh, not from the polling cache
                self.metastore.refresh()
                refreshed = True
                chain = self._shard_chain(shard)
            if chain is not None and chain.get("follower") != self.config.node_id:
                continue  # not the registered follower: not eligible
            # registry BEFORE the role flip: a crash in between leaves the
            # record naming this node, and the next tick finishes the flip
            # (branch above) instead of another copy taking over
            from ..metastore.base import MetastoreError
            try:
                self.metastore.record_shard_chain(
                    shard.index_uid, shard.source_id, shard.shard_id,
                    leader=self.config.node_id, follower=None)
            except MetastoreError:
                continue  # retry next tick; the old record still gates
            if self.ingester.promote_replica(
                    queue_id, min_position=self._published_floor(shard)):
                promoted.append(shard.shard_id)
                logger.warning(
                    "promoted replica shard %s (leader %s dead for %.0fs)",
                    shard.shard_id, leader_node, now - first_seen_dead)
        return promoted

    def reconcile_stale_leaders(self) -> list[str]:
        """Demote local leader-role shards whose REGISTERED leader is
        another node: this node crashed, its replica was promoted
        elsewhere, and WAL recovery restored the stale leader role — the
        split-brain that qwmc's stale-leader-rejoin counterexample turns
        into an acked-record loss (re-used published positions). The WAL
        resets at the published checkpoint; the registered chain holds
        every acked record, so the stale copy is redundant."""
        from ..ingest.ingester import shard_queue_id
        demoted = []
        for shard in self.ingester.list_shards(include_replicas=False):
            chain = self._shard_chain(shard)
            if chain is None or chain.get("leader") == self.config.node_id:
                continue
            queue_id = shard_queue_id(shard.index_uid, shard.source_id,
                                      shard.shard_id)
            if self.ingester.demote_to_replica(queue_id,
                                               self._published_floor(shard)):
                demoted.append(shard.shard_id)
                logger.warning(
                    "demoted stale leader shard %s (registry names %s)",
                    shard.shard_id, chain["leader"])
        return demoted

    def ingest_v2(self, index_id: str, docs: list[dict]) -> dict[str, Any]:
        """Durable WAL ingest (v2 path): docs are fsync'd into shard queues
        and become searchable after the next ingest pipeline pass."""
        metadata = self._metadata_or_template(index_id)
        return self.ingest_router.ingest(metadata.index_uid, docs)

    def run_ingest_pass(self, index_id: str) -> dict[str, Any]:
        """Drain WAL shards into splits, publish, truncate behind the
        published checkpoint (the decoupled indexer side of ingest v2)."""
        from ..indexing.sources import IngestSource
        from ..ingest.router import INGEST_V2_SOURCE_ID
        metadata = self.metastore.index_metadata(index_id)
        uid = metadata.index_uid
        if not self._source_enabled(metadata, INGEST_V2_SOURCE_ID):
            return {"num_docs_indexed": 0, "num_splits_published": 0,
                    "source_disabled": True}
        if INGEST_V2_SOURCE_ID not in metadata.sources:
            self.metastore.add_source(
                uid, SourceConfig(INGEST_V2_SOURCE_ID, "ingest"))
        source = IngestSource(self.ingester, uid, INGEST_V2_SOURCE_ID)
        params = PipelineParams(
            index_uid=uid, source_id=INGEST_V2_SOURCE_ID,
            node_id=self.config.node_id,
            split_num_docs_target=metadata.index_config.split_num_docs_target)
        pipeline = IndexingPipeline(
            params, metadata.index_config.doc_mapper, source, self.metastore,
            self.storage_resolver.resolve(metadata.index_config.index_uri),
            transform=self._transform_for(metadata, INGEST_V2_SOURCE_ID))
        counters = pipeline.run_to_completion()
        # truncate WAL behind the (now durable) published checkpoint
        checkpoint = self.metastore.source_checkpoint(uid, INGEST_V2_SOURCE_ID)
        from ..metastore.checkpoint import BEGINNING
        for shard in self.ingester.list_shards(uid):
            position = checkpoint.position_for(shard.shard_id)
            if position != BEGINNING:
                self.ingester.truncate(uid, INGEST_V2_SOURCE_ID,
                                       shard.shard_id, int(position))
        return {"num_docs_indexed": counters.num_docs_processed,
                "num_splits_published": counters.num_splits_published,
                "uncompressed_bytes": counters.num_published_bytes}

    def _cooperative_drain(self, metadata: IndexMetadata) -> None:
        """One cooperative-indexing turn for an index's WAL pipeline
        (reference cooperative_indexing.rs): drain only at this
        pipeline's phase of the commit window, under the node-wide
        concurrency permit; the post-work sleep re-phases the cycle."""
        from ..indexing.cooperative import CooperativeIndexingCycle
        uid = metadata.index_uid
        now = self._coop_clock()
        cycle = self._coop_cycles.get(uid)
        if cycle is None:
            cycle = CooperativeIndexingCycle(
                uid, metadata.index_config.commit_timeout_secs,
                self._coop_permits, clock=self._coop_clock)
            self._coop_cycles[uid] = cycle
            self._coop_next_wake[uid] = now + cycle.initial_sleep_duration()
        if now < self._coop_next_wake[uid]:
            return
        # never block the shared tick loop on the semaphore: a full house
        # means another pipeline is indexing — retry next tick
        period = cycle.begin_period(timeout=0.001)
        if period is None:
            return
        published_bytes = 0
        try:
            result = self.run_ingest_pass(metadata.index_id)
            published_bytes = int(result.get("uncompressed_bytes", 0))
        finally:
            sleep_secs, metrics = period.end_of_work(published_bytes)
            self._coop_next_wake[uid] = self._coop_clock() + sleep_secs
            self.pipeline_metrics[uid] = metrics

    # -- control-plane convergence (§3.4) -------------------------------
    def apply_indexing_plan(self, tasks: list[dict]) -> dict[str, Any]:
        """This node's slice of the physical indexing plan (the role of
        the reference's per-indexer ApplyIndexingPlanRequest,
        `indexing_service.rs:1152`): external-source passes run only for
        assigned (index, source) pairs once a plan is applied. With no
        plan ever applied, the legacy per-index rendezvous election
        gates instead, so single-node/CLI deployments need no control
        plane."""
        applied = [
            {"index_uid": t["index_uid"], "source_id": t["source_id"],
             "shard_id": t.get("shard_id")}
            for t in tasks]
        # The ingest actor thread reads both fields; the gate checks
        # _applied_indexing_tasks last, so publish the source set FIRST
        # to avoid one tick seeing new tasks with the stale set.
        self._assigned_sources = {
            (t["index_uid"], t["source_id"]) for t in applied}
        self._applied_indexing_tasks = applied
        return {"applied": len(applied)}

    def owns_index(self, index_uid: str) -> bool:
        """Deterministic single-worker election per index: every node
        computes the same owner from the same alive set (rendezvous
        hash, stateless — unlike the scheduler's affinity memory), so
        concurrent cli-run indexer nodes sharing one file-backed
        metastore don't race merge writes on the same index. The legacy
        source gate when no indexing plan was ever applied."""
        from ..common.rendezvous import sort_by_rendezvous_hash
        indexers = self.cluster.nodes_with_role("indexer")
        if not indexers:
            return False
        return sort_by_rendezvous_hash(index_uid, indexers)[0] \
            == self.config.node_id

    def indexing_tasks(self) -> list[dict]:
        """What this node believes it is running (drift-check input)."""
        return list(self._applied_indexing_tasks or [])

    def indexing_tasks_report(self) -> dict[str, Any]:
        """Drift-check wire report. `applied` distinguishes an EMPTY plan
        slice from NO plan ever applied: a never-applied node still gates
        sources by the legacy election, so the leader must push even an
        empty slice to converge it onto the plan."""
        return {"applied": self._applied_indexing_tasks is not None,
                "tasks": self.indexing_tasks()}

    def source_assignment_allows(self, index_uid: str,
                                 source_id: str) -> "Optional[bool]":
        """True/False per the applied plan; None when no plan was ever
        applied OR no control-plane node is alive (caller falls back to
        the rendezvous election, so decommissioning every control-plane
        node cannot strand newly added sources behind a stale plan)."""
        if self._applied_indexing_tasks is None:
            return None
        if not self.cluster.nodes_with_role("control_plane"):
            return None
        return (index_uid, source_id) in self._assigned_sources

    def run_control_plane_pass(self) -> dict[str, Any]:
        """One scheduler convergence pass: plan, drift-check against what
        indexers report running, re-apply on drift (the reference's
        periodic re-check, §3.4). Runs on the elected control-plane node
        (lowest alive node id with the role); others no-op."""
        controllers = self.cluster.nodes_with_role("control_plane")
        if controllers and min(controllers) != self.config.node_id:
            return {"role": "follower"}
        # One membership read for both the plan and the poll/apply loops:
        # a node joining between two reads would otherwise receive an
        # empty slice (gating all its sources off for a full tick) or
        # have its planned tasks run nowhere this pass.
        indexers = self.cluster.nodes_with_role("indexer")
        plan = self.schedule_indexing(indexers)
        # Poll indexers concurrently: a few blackholed-but-member nodes
        # must not stretch one pass by N x the client timeout.
        running: dict[str, dict] = {
            n: {"applied": False, "tasks": []} for n in indexers}

        def poll_one(node_id: str) -> None:
            client = self.clients.get(node_id)
            if client is None:
                return
            try:
                report = client._post("/internal/indexing_tasks", {})
                if report:
                    running[node_id] = report
            except Exception:  # noqa: BLE001 - dead node: drift
                pass

        workers = []
        for node_id in indexers:
            if node_id == self.config.node_id:
                running[node_id] = self.indexing_tasks_report()
            else:
                # qwlint: disable-next-line=QW003 - control-plane poll of
                # peer nodes; admin path with its own 10s join budget
                # qwlint: disable-next-line=QW008 - serve-layer transport
                # infrastructure (sockets, real IO) outside the DST-raced path;
                # gating it would block the token on real IO
                worker = threading.Thread(target=poll_one, args=(node_id,),
                                          daemon=True)
                worker.start()
                workers.append(worker)
        deadline = time.monotonic() + 10.0
        for worker in workers:
            worker.join(timeout=max(0.0, deadline - time.monotonic()))

        def task_key(t: dict) -> tuple:
            return (t["index_uid"], t["source_id"], t.get("shard_id"))

        want = {node_id: [{"index_uid": t.index_uid,
                           "source_id": t.source_id,
                           "shard_id": t.shard_id}
                          for t in plan.assignments.get(node_id, [])]
                for node_id in indexers}
        # Re-apply ONLY to nodes whose reported state differs from the
        # plan: one unreachable indexer (permanent drift) must not spam
        # already-converged nodes with apply POSTs every tick. A node
        # that never applied ANY plan is always drifted — even with an
        # empty slice — because until a plan lands it consumes sources
        # via the legacy election, racing the planned consumer.
        changed = [node_id for node_id in indexers
                   if not running[node_id].get("applied")
                   or {task_key(t) for t in want[node_id]}
                   != {task_key(t) for t in running[node_id].get("tasks", [])}]
        applied = 0
        for node_id in changed:
            if node_id == self.config.node_id:
                self.apply_indexing_plan(want[node_id])
                applied += 1
                continue
            client = self.clients.get(node_id)
            if client is None:
                continue
            try:
                client._post("/internal/apply_indexing_plan",
                             {"tasks": want[node_id]})
                applied += 1
            except Exception as exc:  # noqa: BLE001 - next tick
                logger.warning("apply plan to %s failed: %s",
                               node_id, exc)
        return {"role": "leader", "drift": bool(changed),
                "nodes_applied": applied,
                "planned_tasks": sum(len(t) for t in want.values())}

    def schedule_indexing(
            self, indexers: Optional[list[str]] = None) -> "Any":
        """Control-plane convergence pass: logical tasks from metastore
        sources/shards → physical plan over live indexer nodes (§3.4)."""
        from ..control_plane.scheduler import IndexingTask
        tasks = []
        for metadata in self.metastore.list_indexes():
            for source_id, source in metadata.sources.items():
                if not source.enabled or source.source_type == "void":
                    continue
                shards = [s for s in self.ingester.list_shards(metadata.index_uid)
                          if s.source_id == source_id]
                if shards:
                    tasks.extend(IndexingTask(metadata.index_uid, source_id,
                                              shard_id=s.shard_id)
                                 for s in shards)
                else:
                    tasks.append(IndexingTask(metadata.index_uid, source_id))
        if indexers is None:
            indexers = self.cluster.nodes_with_role("indexer")
        return self.indexing_scheduler.schedule(tasks, indexers)

    def autoscale_shards(self) -> list[tuple[str, str, str]]:
        """One shard-scaling convergence pass (role of the reference's
        IngestController scale decisions, `ingest_controller.rs:424`):
        sample per-shard ingestion rates, consult the arbiter per source,
        and open/close local leader shards under permit rate limits.
        Returns the actions taken as (kind, index_uid, shard_id)."""
        from ..control_plane.arbiter import (ScaleUp,
                                             find_scale_down_candidate)
        from ..ingest.ingester import ShardState, shard_queue_id
        groups: dict[tuple[str, str], list[str]] = {}
        live_queue_ids: list[str] = []
        for s in self.ingester.list_shards():
            if s.state is ShardState.OPEN and s.role == "leader":
                groups.setdefault((s.index_uid, s.source_id),
                                  []).append(s.shard_id)
                queue_id = shard_queue_id(s.index_uid, s.source_id,
                                          s.shard_id)
                live_queue_ids.append(queue_id)
                self.shard_rate_tracker.observe(queue_id, s.bytes_written)
        # shards closed/deleted by ANY path leave the tracker (bounded)
        self.shard_rate_tracker.retain(live_queue_ids)
        actions: list[tuple[str, str, str]] = []
        for (index_uid, source_id), shard_ids in sorted(groups.items()):
            stats = self.shard_rate_tracker.source_stats(
                [shard_queue_id(index_uid, source_id, sid)
                 for sid in shard_ids])
            decision = self.scaling_arbiter.should_scale(stats)
            if decision is None:
                continue
            key = f"{index_uid}/{source_id}"
            granted = self.scaling_permits.acquire(key, decision)
            if granted == 0:
                continue
            try:
                if isinstance(decision, ScaleUp):
                    # a large scale-up may be granted partially (burst
                    # cap); the rest re-requests on later ticks as
                    # permits refill
                    ords = [int(sid.rsplit("-", 1)[-1]) for sid in shard_ids
                            if sid.rsplit("-", 1)[-1].isdigit()]
                    base = max(ords, default=-1)
                    for k in range(granted):
                        sid = (f"{self.config.node_id}-shard-"
                               f"{base + 1 + k:02d}")
                        self.ingester.open_shard(index_uid, source_id, sid)
                        actions.append(("open", index_uid, sid))
                else:
                    candidate = find_scale_down_candidate(
                        {sid: self.config.node_id for sid in shard_ids})
                    if candidate is None:
                        self.scaling_permits.release(key, decision,
                                                     granted=granted)
                        continue
                    _, sid = candidate
                    self.ingester.close_shard(index_uid, source_id, sid)
                    self.shard_rate_tracker.forget(
                        shard_queue_id(index_uid, source_id, sid))
                    actions.append(("close", index_uid, sid))
            except Exception:  # noqa: BLE001
                # a failed open/close must not eat the rate budget for
                # the retry on the next convergence tick
                self.scaling_permits.release(key, decision, granted=granted)
                raise
            self.ingest_router.refresh(index_uid, source_id)
        return actions

    # ------------------------------------------------------------------
    def advertised_roles(self) -> tuple[str, ...]:
        """Roles this node advertises to peers. A DRAINED compactor
        withdraws the role so indexers resume merging and other
        compactors take over its rendezvous ownership; a DRAINING one
        keeps advertising (its in-flight merges still claim splits only
        it knows about — letting indexers race in would duplicate
        merges), it just plans no new work."""
        from ..compaction import CompactorState
        roles = self.config.roles
        if (self.compactor is not None
                and self.compactor.state is CompactorState.DRAINED):
            roles = tuple(r for r in roles if r != "compactor")
        return roles

    def run_compaction_pass(self, synchronous: bool = False) -> int:
        """One compactor tick (reference compaction_planner tick +
        supervisor dispatch): plan merges for the indexes this compactor
        owns (rendezvous over alive compactor nodes) and submit them up
        to the supervisor's free slots. Returns tasks submitted."""
        from ..common.rendezvous import sort_by_rendezvous_hash
        if self.compactor is None or self.compaction_planner is None:
            return 0
        compactors = self.cluster.nodes_with_role("compactor") \
            or [self.config.node_id]
        indexes = self.metastore.list_indexes()
        owned = [m for m in indexes
                 if sort_by_rendezvous_hash(m.index_uid, compactors)[0]
                 == self.config.node_id]
        if not owned:
            return 0
        slots = self.compactor.available_slots()
        if slots == 0:
            return 0
        planner = self.compaction_planner

        def on_done(task, ok):
            (planner.complete_task if ok else planner.fail_task)(
                task.task_id)

        submitted = 0
        for task in planner.plan(max_tasks=slots, indexes=owned):
            if self.compactor.submit(task, on_done=on_done,
                                     synchronous=synchronous):
                submitted += 1
            else:
                planner.fail_task(task.task_id)  # slot raced away
        return submitted

    def run_merges(self, index_id: str) -> int:
        """One merge-planner pass (role of MergePlanner + MergePipeline)."""
        metadata = self.metastore.index_metadata(index_id)
        policy = merge_policy_from_config(metadata.index_config.merge_policy)
        splits = self.metastore.list_splits(ListSplitsQuery(
            index_uids=[metadata.index_uid], states=[SplitState.PUBLISHED]))
        operations = policy.operations(splits)
        if not operations:
            return 0
        storage = self.storage_resolver.resolve(metadata.index_config.index_uri)
        executor = MergeExecutor(metadata.index_uid,
                                 metadata.index_config.doc_mapper,
                                 self.metastore, storage, self.config.node_id)
        delete_tasks = self.metastore.list_delete_tasks(metadata.index_uid)
        for operation in operations:
            executor.execute(operation, delete_tasks=delete_tasks or None)
        return len(operations)

    # ------------------------------------------------------------------
    def start_scroll(self, request, ttl_secs: float) -> dict[str, Any]:
        """First page + scroll id (reference scroll flow, scroll.md)."""
        from dataclasses import replace
        from ..search.scroll import CACHE_WINDOW, ScrollContext
        page_size = request.max_hits
        window_request = replace(request,
                                 max_hits=max(CACHE_WINDOW, page_size),
                                 start_offset=0)
        response = self.root_searcher.search(window_request)
        context = ScrollContext(
            request=request, cached_hits=response.hits,
            cursor=min(page_size, len(response.hits)),
            total_hits=response.num_hits, ttl_secs=ttl_secs)
        scroll_id = self.scroll_store.put(context)
        self._replicate_scroll(scroll_id, context)
        page = response.to_dict()
        page["hits"] = page["hits"][:page_size]
        if "snippets" in page:  # parallel array: keep aligned with hits
            page["snippets"] = page["snippets"][:page_size]
        page["scroll_id"] = scroll_id
        page["index"] = request.index_ids[0] if request.index_ids else ""
        return page

    def end_scroll(self, scroll_id: str) -> bool:
        """Release a scroll context early (clear-scroll)."""
        return self.scroll_store.delete(scroll_id)

    def _scroll_affinity_peers(self, scroll_id: str) -> list:
        """ALL other searcher members in rendezvous order (rendezvous
        weights are per-node, so every node computes the same relative
        order regardless of which node is excluded): replication targets
        the first, recovery walks the whole list — a 3-node cluster where
        the serving node restarts still finds the replica wherever the
        next page lands."""
        from ..common.rendezvous import sort_by_rendezvous_hash
        peers = {m.node_id: m for m in self.cluster.members()
                 if m.node_id != self.config.node_id
                 and "searcher" in m.roles and m.rest_endpoint}
        if not peers:
            return []
        ordered = sort_by_rendezvous_hash(scroll_id, list(peers))
        return [peers[n] for n in ordered]

    def _replicate_scroll(self, scroll_id: str, context) -> None:
        """Best-effort put_kv to the best-affinity peer (reference:
        scroll_context.rs:146): a node restart then no longer kills live
        scrolls — the next page is served from the replica."""
        from ..search.scroll import context_to_dict
        for member in self._scroll_affinity_peers(scroll_id)[:1]:
            client = self.clients.get(member.node_id)
            if client is None:
                continue
            try:
                client._post("/internal/kv", {
                    "key": scroll_id, "kind": "scroll",
                    "value": context_to_dict(context)})
            except Exception:  # noqa: BLE001 - best-effort replication
                logger.debug("scroll replication to %s failed",
                             member.node_id)

    def _replicate_scroll_cursor(self, scroll_id: str, cursor: int) -> None:
        """Per-page cursor sync: a few bytes instead of re-shipping the
        whole cached window on every page."""
        for member in self._scroll_affinity_peers(scroll_id)[:1]:
            client = self.clients.get(member.node_id)
            if client is None:
                continue
            try:
                client._post("/internal/kv", {
                    "key": scroll_id, "kind": "scroll_cursor",
                    "value": cursor})
            except Exception:  # noqa: BLE001
                pass

    def _fetch_scroll(self, scroll_id: str):
        """Local miss (e.g. this node restarted, or the client hit a
        different node): recover the context from the affinity replica."""
        from ..search.scroll import context_from_dict
        for member in self._scroll_affinity_peers(scroll_id):
            client = self.clients.get(member.node_id)
            if client is None:
                continue
            try:
                payload = client._post("/internal/kv_get",
                                       {"key": scroll_id})
            except Exception:  # noqa: BLE001
                continue
            if payload and payload.get("value"):
                context = context_from_dict(payload["value"])
                self.scroll_store.put_with_id(scroll_id, context)
                return context
        return None

    def continue_scroll(self, scroll_id: str) -> dict[str, Any]:
        from dataclasses import replace
        context = self.scroll_store.get(scroll_id)
        if context is None:
            context = self._fetch_scroll(scroll_id)
        if context is None:
            raise ValueError("scroll id not found or expired")
        page_size = context.request.max_hits
        hits = context.cached_hits
        if context.cursor >= len(hits) and len(hits) < context.total_hits and hits:
            # refill the window via search_after from the last cached hit
            from ..search.scroll import CACHE_WINDOW
            # string search_after markers make text-sort refills work the
            # same as numeric ones (the raw term string IS the marker)
            last = hits[-1]
            sort_value = last.sort_values[0] if last.sort_values else last.score
            if len(context.request.sort_fields) > 1 and len(last.sort_values) > 1:
                marker = [sort_value, last.sort_values[1],
                          last.split_id, last.doc_id]
            else:
                marker = [sort_value, last.split_id, last.doc_id]
            refill_request = replace(
                context.request, start_offset=0, max_hits=CACHE_WINDOW,
                search_after=marker)
            response = self.root_searcher.search(refill_request)
            hits.extend(response.hits)
            self._replicate_scroll(scroll_id, context)  # window changed
        page_hits = hits[context.cursor: context.cursor + page_size]
        context.cursor += len(page_hits)
        self._replicate_scroll_cursor(scroll_id, context.cursor)
        return {
            "num_hits": context.total_hits,
            "hits": [h.doc for h in page_hits],
            "scroll_id": scroll_id,
            "index": (context.request.index_ids[0]
                      if context.request.index_ids else ""),
            "elapsed_time_micros": 0,
            "errors": [],
        }

    # ------------------------------------------------------------------
    # background service loops (role of the reference's long-running actors:
    # ingest pipelines, MergePlanner, janitor actors, chitchat heartbeats).
    # Supervision-lite: each loop catches and logs failures and keeps going.
    def start_background_services(self,
                                  ingest_interval_secs: float = 2.0,
                                  merge_interval_secs: float = 30.0,
                                  janitor_interval_secs: float = 300.0,
                                  heartbeat_interval_secs: float = 2.0) -> None:
        if getattr(self, "_bg_stop", None) is not None:
            return
        self._ensure_span_exporter()
        if self.grpc_server is None and self.config.grpc_port is not None:
            # stop/start cycles recreate the listener (stop tears it down)
            from .grpc_server import GrpcServer
            self.grpc_server = GrpcServer(
                self, host=self.config.rest_host,
                port=self.config.grpc_port,
                ssl_context=self.config.server_ssl_context(alpn=["h2"]))
        # qwlint: disable-next-line=QW008 - serve-layer transport
        # infrastructure (sockets, real IO) outside the DST-raced path; gating
        # it would block the token on real IO
        stop = self._bg_stop = threading.Event()
        owns_index = self.owns_index

        def ingest_tick() -> None:
            # Drains the LOCAL WAL — no ownership gate: only this node can
            # drain its own shards (node-prefixed ids keep checkpoint
            # partitions collision-free across nodes; a raced metastore
            # publish fails the version check and retries next tick).
            if "indexer" not in self.config.roles:
                return
            # failover: adopt replica shards whose leader died before
            # draining (checkpoints continue at the same positions), and
            # step down from shards the registry says another node now
            # leads (stale role recovered from a pre-crash WAL)
            self.reconcile_stale_leaders()
            self.promote_orphaned_replicas()
            live_uids = set()
            for metadata in self.metastore.list_indexes():
                live_uids.add(metadata.index_uid)
                shards = self.ingester.list_shards(metadata.index_uid)
                if any(s.log.next_position > s.publish_position for s in shards):
                    if self.config.cooperative_indexing:
                        self._cooperative_drain(metadata)
                    else:
                        self.run_ingest_pass(metadata.index_id)
                # configured external sources (file/kafka): owner-gated so
                # one node consumes each index's partitions (the reference
                # control plane assigns (source,partition)→indexer; our
                # rendezvous election is the same single-consumer rule)
                for source_id, source_config in metadata.sources.items():
                    # cheap filters FIRST: internal/disabled sources must
                    # not pay cluster-lock + rendezvous-hash per tick
                    if (not source_config.enabled
                            or source_config.source_type
                            in self._INTERNAL_SOURCE_TYPES):
                        continue
                    allowed = self.source_assignment_allows(
                        metadata.index_uid, source_id)
                    if allowed is None:  # no plan applied: legacy election
                        allowed = owns_index(metadata.index_uid)
                    if allowed:
                        try:
                            self.run_source_pass(metadata.index_id,
                                                 source_id)
                        except Exception as exc:  # noqa: BLE001
                            logger.warning(
                                "source %s/%s pass failed: %s",
                                metadata.index_id, source_id, exc)
            # deleted indexes release their cooperative state (index
            # churn must not grow these dicts forever)
            for state in (self._coop_cycles, self._coop_next_wake,
                          self.pipeline_metrics):
                for uid in list(state):
                    if uid not in live_uids:
                        del state[uid]
            for key in list(self._external_sources):
                if key[0] not in live_uids:
                    self._close_source(self._external_sources.pop(key)[1])

        def merge_tick() -> None:
            # compactor nodes own merging when present; indexers merge
            # only in clusters WITHOUT compactors (reference: the
            # standalone compactor role takes merge work off indexers).
            # DRAINING still holds the merge baton (its in-flight tasks
            # claim splits); only DRAINED hands merging back to indexers
            # — locally at once, remotely via withdrawn heartbeat roles.
            from ..compaction import CompactorState
            if self.compactor is not None:
                if self.compactor.state is CompactorState.RUNNING:
                    self.run_compaction_pass()
                if self.compactor.state is not CompactorState.DRAINED:
                    return
            if "indexer" not in self.config.roles:
                return
            others = [n for n in self.cluster.nodes_with_role("compactor")
                      if n != self.config.node_id]
            if others:
                return
            for metadata in self.metastore.list_indexes():
                if owns_index(metadata.index_uid):
                    self.run_merges(metadata.index_id)

        def janitor_tick() -> None:
            if "janitor" in self.config.roles:
                self.run_janitor()

        heartbeat_clients: dict[str, object] = {}

        def heartbeat_one(endpoint: str, payload: dict) -> None:
            # Runs in a bare worker thread (outside loop()'s supervision):
            # must never let an exception escape, or a malformed peer
            # response kills the worker with a traceback every tick.
            try:
                from ..common.tower import CircuitOpen
                from ..cluster.membership import substitute_wildcard_host
                from .http_client import HttpSearchClient, HttpTransportError
                client = heartbeat_clients.get(endpoint)
                if client is None:
                    client = heartbeat_clients[endpoint] = HttpSearchClient(
                        endpoint, timeout_secs=2.0,
                        **self.config.client_tls_kwargs())
                try:
                    info = client.heartbeat(payload)
                except (HttpTransportError, CircuitOpen) as exc:
                    # CircuitOpen: the cached client's breaker backs off from
                    # a dead peer; half-open probes re-admit it on recovery.
                    logger.debug("heartbeat to %s failed: %s", endpoint, exc)
                    return
                self.cluster.upsert_heartbeat(ClusterMember(
                    node_id=info["node_id"], roles=tuple(info["roles"]),
                    rest_endpoint=substitute_wildcard_host(
                        info.get("rest_endpoint", endpoint),
                        endpoint.rpartition(":")[0]),
                    grpc_endpoint=substitute_wildcard_host(
                        info.get("grpc_endpoint", ""),
                        endpoint.rpartition(":")[0])))
            except Exception:  # noqa: BLE001 - supervised worker
                logger.exception("heartbeat to %s: bad peer response", endpoint)

        def heartbeat_tick() -> None:
            payload = {"node_id": self.config.node_id,
                       "roles": list(self.advertised_roles()),
                       "rest_endpoint":
                           f"{self.config.rest_host}:{self.config.rest_port}",
                       "grpc_endpoint": self._grpc_advertise()}
            peers = set(self.config.peers)
            peers.update(m.rest_endpoint for m in self.cluster.members()
                         if m.node_id != self.config.node_id and m.rest_endpoint)
            # Fan out concurrently: N slow/unreachable peers must not stretch
            # the heartbeat period past the liveness window for healthy ones.
            # qwlint: disable-next-line=QW003 - liveness heartbeats to
            # peers; cluster plumbing, not query work
            # qwlint: disable-next-line=QW008 - serve-layer transport
            # infrastructure (sockets, real IO) outside the DST-raced path;
            # gating it would block the token on real IO
            workers = [threading.Thread(target=heartbeat_one,
                                        args=(endpoint, payload), daemon=True)
                       for endpoint in peers]
            for worker in workers:
                worker.start()
            for worker in workers:
                worker.join(timeout=4.0)

        def autoscale_tick() -> None:
            if "indexer" in self.config.roles:
                self.autoscale_shards()

        def control_plane_tick() -> None:
            # scheduler convergence (§3.4): plan, drift-check, re-apply.
            # Leader election happens inside the pass (lowest alive
            # control-plane node); followers no-op.
            if "control_plane" not in self.config.roles:
                return
            try:
                self.run_control_plane_pass()
            except Exception as exc:  # noqa: BLE001 - next tick retries
                logger.warning("control-plane pass failed: %s", exc)

        loops = [("ingest", ingest_interval_secs, ingest_tick),
                 ("merge", merge_interval_secs, merge_tick),
                 ("janitor", janitor_interval_secs, janitor_tick),
                 ("autoscale", max(ingest_interval_secs, 2.0),
                  autoscale_tick),
                 ("control-plane", max(merge_interval_secs, 10.0),
                  control_plane_tick)]
        if self.config.gossip_enabled:
            # UDP scuttlebutt replaces the REST heartbeat loop entirely
            from ..cluster.gossip import GossipService
            self._gossip = GossipService(
                self.cluster, self.config.node_id, self.config.roles,
                rest_endpoint=f"{self.config.rest_host}:"
                              f"{self.config.rest_port}",
                bind_host=self.config.rest_host,
                bind_port=self.config.rest_port,
                seeds=self.config.peers,
                interval_secs=min(heartbeat_interval_secs, 1.0),
                cluster_id=self.config.cluster_id,
                grpc_endpoint=self._grpc_advertise())
            self._gossip.start()
        else:
            loops.append(("heartbeat", heartbeat_interval_secs,
                          heartbeat_tick))
        # each background service is an actor on the shared Universe
        # (reference: the quickwit-actors supervision trees hosting
        # IndexingService / janitor / pipelines): one mailbox each,
        # periodic Tick messages from the scheduler, supervised restarts,
        # and tick coalescing (try_send) so a slow pass skips beats
        # instead of queueing them up
        from ..common.actors import Actor, Universe
        universe = self._bg_universe = Universe()

        class _Service(Actor):
            def __init__(self, name: str, tick):
                self.name = f"bg-{name}"
                self._tick = tick

            def on_message(self, message) -> None:
                if stop.is_set():
                    return
                self._tick()

        for name, interval, tick in loops:
            mailbox, _handle = universe.spawn(
                _Service(name, tick), capacity=1, supervised=True,
                max_restarts=1 << 30)  # services restart forever
            universe.schedule_periodic(
                interval, lambda m=mailbox: m.try_send("tick"))
        logger.info("background services started (%s)", self.config.node_id)

    def stop_background_services(self) -> None:
        if self.grpc_server is not None:
            self.grpc_server.stop()
            self.grpc_server = None
        if self.span_exporter is not None:
            from ..observability.tracing import TRACER
            TRACER.remove_processor(self.span_exporter)
            self.span_exporter.stop()
            self.span_exporter = None
        stop = getattr(self, "_bg_stop", None)
        if stop is not None:
            stop.set()
            self._bg_stop = None
        universe = getattr(self, "_bg_universe", None)
        if universe is not None:
            universe.quit(timeout=2.0)
            self._bg_universe = None
        gossip = getattr(self, "_gossip", None)
        if gossip is not None:
            gossip.stop()
            self._gossip = None
        if self.split_cache is not None:
            self.split_cache.stop()

    # ------------------------------------------------------------------
    def warmup_index(self, index_id: str,
                     requests: Optional[list] = None) -> dict[str, Any]:
        """Pre-warm the searcher for an index: run the given
        SearchRequests once, discarding results, so reader opens, storage
        byte-range fetches, host→device transfers, AND the
        per-plan-structure jit compilations happen before user traffic
        (the round-4 weak-point: first-query warmup costs seconds per
        plan structure). The REST route builds the requests through the
        SAME parser production queries use, so warmed plan structures
        match real traffic; `requests=None` warms a default match-all
        top-k + a date-histogram shape."""
        from ..query.ast import MatchAll
        from ..search.models import SearchRequest
        if not requests:
            metadata = self._metadata_or_template(index_id)
            doc_mapper = metadata.index_config.doc_mapper
            requests = [SearchRequest(index_ids=[index_id],
                                      query_ast=MatchAll(), max_hits=10)]
            if doc_mapper.timestamp_field:
                requests.append(SearchRequest(
                    index_ids=[index_id], query_ast=MatchAll(), max_hits=0,
                    aggs={"_warm_hist": {"date_histogram": {
                        "field": doc_mapper.timestamp_field,
                        "fixed_interval": "1d"}}}))
        timings = []
        for request in requests:
            t0 = time.monotonic()
            try:
                self.root_searcher.search(request)
                status = "ok"
            except Exception as exc:  # noqa: BLE001 - report, keep warming
                status = f"error: {exc}"
            timings.append({"status": status,
                            "elapsed_ms": round(
                                (time.monotonic() - t0) * 1000, 1)})
        return {"warmed": timings}

    # ------------------------------------------------------------------
    def run_janitor(self) -> dict[str, int]:
        """GC + retention + delete-task planning pass (role of
        quickwit-janitor's actors)."""
        from ..janitor.delete_planner import run_delete_planner
        from ..janitor.gc import run_garbage_collection
        from ..janitor.retention import apply_retention
        gc_stats = run_garbage_collection(self.metastore, self.storage_resolver)
        retention_stats = apply_retention(self.metastore)
        delete_stats = run_delete_planner(self.metastore,
                                          self.storage_resolver,
                                          node_id=self.config.node_id)
        return {**gc_stats, **retention_stats, **delete_stats}
