"""Node bootstrap + service wiring.

Role of the reference's `serve_quickwit` (`quickwit-serve/src/lib.rs:557`):
instantiate the services a node's roles require — searcher, indexer,
metastore, janitor — over a shared storage resolver and cluster membership,
and wire remote clients (HTTP) for peers. A node runs any subset of roles
(`lib.rs:566-700`); single-process all-roles is the default.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ..cluster.membership import Cluster, ClusterChange, ClusterMember
from ..indexing.merge import MergeExecutor, merge_policy_from_config
from ..indexing.pipeline import IndexingPipeline, PipelineParams
from ..indexing.sources import VecSource, make_source
from ..metastore.base import ListSplitsQuery, Metastore
from ..metastore.file_backed import FileBackedMetastore
from ..models.doc_mapper import DocMapper
from ..models.index_metadata import IndexConfig, IndexMetadata, SourceConfig
from ..models.split_metadata import SplitState
from ..query import ast as Q
from ..search.root import RootSearcher
from ..search.service import LocalSearchClient, SearcherContext, SearchService
from ..storage.base import StorageResolver

logger = logging.getLogger(__name__)

ALL_SERVICES = ("searcher", "indexer", "metastore", "janitor", "control_plane")


@dataclass
class NodeConfig:
    node_id: str = "node-0"
    roles: tuple[str, ...] = ALL_SERVICES
    metastore_uri: str = "ram:///qw/metastore"
    default_index_root_uri: str = "ram:///qw/indexes"
    rest_host: str = "127.0.0.1"
    rest_port: int = 7280
    peers: tuple[str, ...] = ()  # "host:port" seeds


class IndexService:
    """Index management operations (role of `quickwit-index-management`)."""

    def __init__(self, metastore: Metastore, storage_resolver: StorageResolver,
                 default_index_root_uri: str):
        self.metastore = metastore
        self.storage_resolver = storage_resolver
        self.default_index_root_uri = default_index_root_uri

    def create_index(self, index_config_json: dict[str, Any]) -> IndexMetadata:
        index_id = index_config_json["index_id"]
        if not index_id or not index_id.replace("-", "").replace("_", "").isalnum():
            raise ValueError(f"invalid index id {index_id!r}")
        doc_mapping = index_config_json.get("doc_mapping", {})
        doc_mapper = DocMapper.from_dict(doc_mapping) if "field_mappings" in doc_mapping \
            else DocMapper(field_mappings=[])
        index_uri = index_config_json.get(
            "index_uri", f"{self.default_index_root_uri}/{index_id}")
        config = IndexConfig(
            index_id=index_id, index_uri=index_uri, doc_mapper=doc_mapper,
            commit_timeout_secs=index_config_json.get(
                "indexing_settings", {}).get("commit_timeout_secs", 60),
            split_num_docs_target=index_config_json.get(
                "indexing_settings", {}).get("split_num_docs_target", 10_000_000),
            merge_policy=index_config_json.get(
                "indexing_settings", {}).get("merge_policy", {"type": "stable_log"}),
        )
        retention = index_config_json.get("retention")
        if retention:
            from ..models.index_metadata import RetentionPolicy
            config.retention = RetentionPolicy(
                period_seconds=_parse_period(retention["period"]),
                schedule=retention.get("schedule", "hourly"))
        metadata = IndexMetadata(
            index_uid=f"{index_id}:{int(time.time()) % 100000:05d}",
            index_config=config,
            sources={"_ingest-api-source": SourceConfig("_ingest-api-source", "vec")},
        )
        self.metastore.create_index(metadata)
        return metadata

    def delete_index(self, index_id: str) -> list[str]:
        metadata = self.metastore.index_metadata(index_id)
        splits = self.metastore.list_splits(
            ListSplitsQuery(index_uids=[metadata.index_uid]))
        storage = self.storage_resolver.resolve(metadata.index_config.index_uri)
        removed = []
        for split in splits:
            try:
                storage.delete(f"{split.metadata.split_id}.split")
                removed.append(split.metadata.split_id)
            except Exception:  # noqa: BLE001 - missing files are fine
                pass
        self.metastore.delete_index(metadata.index_uid)
        return removed


def _parse_period(period: str) -> int:
    period = period.strip()
    units = {"seconds": 1, "minutes": 60, "hours": 3600, "days": 86400,
             "weeks": 7 * 86400}
    parts = period.split()
    if len(parts) == 2 and parts[1] in units:
        return int(parts[0]) * units[parts[1]]
    raise ValueError(f"cannot parse retention period {period!r}")


class Node:
    """A running node: metastore + searcher + indexer + janitor services
    according to roles, plus the client pool for distributed search."""

    def __init__(self, config: NodeConfig,
                 storage_resolver: Optional[StorageResolver] = None):
        self.config = config
        self.storage_resolver = storage_resolver or StorageResolver.default()
        self.metastore: Metastore = FileBackedMetastore(
            self.storage_resolver.resolve(config.metastore_uri))
        self.cluster = Cluster(
            config.node_id, config.roles,
            rest_endpoint=f"{config.rest_host}:{config.rest_port}")
        self.searcher_context = SearcherContext(self.storage_resolver)
        self.search_service = SearchService(self.searcher_context, config.node_id)
        self.index_service = IndexService(self.metastore, self.storage_resolver,
                                          config.default_index_root_uri)
        self.clients: dict[str, Any] = {
            config.node_id: LocalSearchClient(self.search_service)}
        self.root_searcher = RootSearcher(
            self.metastore, self.clients,
            nodes_provider=lambda: self.cluster.nodes_with_role("searcher"))
        self.cluster.subscribe(self._on_cluster_change)
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def _on_cluster_change(self, change: ClusterChange) -> None:
        member = change.member
        if change.kind == "remove":
            if member.node_id != self.config.node_id:
                self.clients.pop(member.node_id, None)
            return
        if member.node_id == self.config.node_id:
            return
        if "searcher" in member.roles and member.rest_endpoint:
            from .http_client import HttpSearchClient
            self.clients[member.node_id] = HttpSearchClient(member.rest_endpoint)

    # ------------------------------------------------------------------
    # ingest (v1-style: REST batch → immediate split, commit semantics
    # per-request; the WAL-based v2 path lives in quickwit_tpu.ingest)
    def ingest(self, index_id: str, docs: list[dict],
               commit: str = "auto") -> dict[str, Any]:
        metadata = self.metastore.index_metadata(index_id)
        doc_mapper = metadata.index_config.doc_mapper
        storage = self.storage_resolver.resolve(metadata.index_config.index_uri)
        params = PipelineParams(
            index_uid=metadata.index_uid,
            source_id="_ingest-api-source",
            node_id=self.config.node_id,
            split_num_docs_target=metadata.index_config.split_num_docs_target,
        )
        source = VecSource(docs, partition_id=f"ingest-{time.time_ns()}")
        pipeline = IndexingPipeline(params, doc_mapper, source,
                                    self.metastore, storage)
        counters = pipeline.run_to_completion()
        return {"num_docs_for_processing": len(docs),
                "num_ingested_docs": counters.num_docs_processed,
                "num_invalid_docs": counters.num_docs_invalid}

    # ------------------------------------------------------------------
    def run_merges(self, index_id: str) -> int:
        """One merge-planner pass (role of MergePlanner + MergePipeline)."""
        metadata = self.metastore.index_metadata(index_id)
        policy = merge_policy_from_config(metadata.index_config.merge_policy)
        splits = self.metastore.list_splits(ListSplitsQuery(
            index_uids=[metadata.index_uid], states=[SplitState.PUBLISHED]))
        operations = policy.operations(splits)
        if not operations:
            return 0
        storage = self.storage_resolver.resolve(metadata.index_config.index_uri)
        executor = MergeExecutor(metadata.index_uid,
                                 metadata.index_config.doc_mapper,
                                 self.metastore, storage, self.config.node_id)
        delete_asts = [Q.ast_from_dict(t["query_ast"])
                       for t in self.metastore.list_delete_tasks(metadata.index_uid)]
        for operation in operations:
            executor.execute(operation, delete_query_asts=delete_asts or None)
        return len(operations)

    # ------------------------------------------------------------------
    def run_janitor(self) -> dict[str, int]:
        """GC + retention pass (role of quickwit-janitor's actors)."""
        from ..janitor.gc import run_garbage_collection
        from ..janitor.retention import apply_retention
        gc_stats = run_garbage_collection(self.metastore, self.storage_resolver)
        retention_stats = apply_retention(self.metastore)
        return {**gc_stats, **retention_stats}
