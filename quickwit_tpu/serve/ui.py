"""Minimal search console UI.

Role of `quickwit-ui` (the reference's React SPA served by the node): a
zero-dependency single-page console at `/ui` — query input, time range,
index picker, hit table, aggregation viewer, and a SQL tab driving
`POST /api/v1/_sql` — all against this node's own REST API from the
browser.
"""

UI_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>quickwit-tpu console</title>
<style>
  :root { --fg: #1a1f36; --muted: #667085; --line: #e4e7ec; --accent: #175cd3; }
  * { box-sizing: border-box; }
  body { font: 14px/1.45 system-ui, sans-serif; color: var(--fg); margin: 0; }
  header { padding: 14px 20px; border-bottom: 1px solid var(--line);
           display: flex; gap: 10px; align-items: center; }
  header h1 { font-size: 16px; margin: 0 14px 0 0; }
  main { padding: 16px 20px; }
  input, select, button { font: inherit; padding: 7px 10px;
    border: 1px solid var(--line); border-radius: 6px; }
  input#query { flex: 1; min-width: 240px; }
  button { background: var(--accent); color: #fff; border: none; cursor: pointer; }
  table { border-collapse: collapse; width: 100%; margin-top: 14px; }
  th, td { text-align: left; padding: 6px 10px; border-bottom: 1px solid var(--line);
           vertical-align: top; font-size: 13px; }
  th { color: var(--muted); font-weight: 600; }
  td pre { margin: 0; white-space: pre-wrap; word-break: break-all;
           font-size: 12px; max-height: 90px; overflow: auto; }
  #meta { color: var(--muted); margin-top: 10px; }
  #error { color: #b42318; margin-top: 10px; white-space: pre-wrap; }
  #aggs { margin-top: 14px; }
  #aggs pre { background: #f8fafc; border: 1px solid var(--line);
              border-radius: 6px; padding: 10px; font-size: 12px; overflow: auto; }
  nav { display: flex; gap: 4px; margin-right: 10px; }
  nav button { background: none; color: var(--muted); border: 1px solid
               transparent; padding: 6px 10px; }
  nav button.active { color: var(--accent); border-color: var(--line);
                      border-radius: 6px; background: #f8fafc; }
  #sqlbar { display: none; padding: 14px 20px; border-bottom: 1px solid
            var(--line); }
  #sqlbar textarea { width: 100%; font: 13px/1.4 ui-monospace, monospace;
    padding: 8px 10px; border: 1px solid var(--line); border-radius: 6px;
    min-height: 64px; resize: vertical; }
  #sqlbar .row { display: flex; gap: 10px; margin-top: 8px;
                 align-items: center; }
  #sqlbar .hint { color: var(--muted); font-size: 12px; }
</style>
</head>
<body>
<header>
  <h1>quickwit-tpu</h1>
  <nav>
    <button id="tab-search" class="active">Search</button>
    <button id="tab-sql">SQL</button>
  </nav>
  <select id="index"></select>
  <input id="query" placeholder='query, e.g. severity_text:ERROR AND body:"disk full"'>
  <input id="maxhits" type="number" value="20" min="0" max="1000" style="width:80px">
  <input id="sortby" placeholder="sort, e.g. -timestamp" style="width:140px">
  <button id="go">Search</button>
</header>
<div id="sqlbar">
  <textarea id="sql" placeholder="SELECT severity_text, COUNT(*) AS n FROM hdfs-logs GROUP BY severity_text ORDER BY n DESC"></textarea>
  <div class="row">
    <button id="run-sql">Run</button>
    <span class="hint">Ctrl-Enter runs · GROUP BY / HAVING / window
      functions / JOIN / subqueries — see the docs</span>
  </div>
</div>
<main>
  <div id="meta"></div>
  <div id="error"></div>
  <div id="hits"></div>
  <div id="aggs"></div>
</main>
<script>
const $ = (id) => document.getElementById(id);
const esc = (s) => String(s).replace(/[&<>"']/g,
  (c) => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
// request generation: a fetch resolving after a tab switch (or a newer
// request) must not write stale results into the visible panes
let gen = 0;
async function loadIndexes() {
  const my = gen;
  try {
    const res = await fetch('/api/v1/indexes');
    const indexes = await res.json();
    if (!res.ok) throw new Error(indexes.message || res.status);
    $('index').innerHTML = indexes.map(
      (ix) => `<option>${esc(ix.index_config.index_id)}</option>`).join('');
    if (!indexes.length && my === gen)
      $('error').textContent = 'no indexes yet';
  } catch (err) {
    if (my === gen)
      $('error').textContent = 'failed to list indexes: ' + err;
  }
}
async function search() {
  const my = ++gen;
  $('error').textContent = ''; $('hits').innerHTML = '';
  $('aggs').innerHTML = ''; $('meta').textContent = 'searching…';
  const params = new URLSearchParams({
    query: $('query').value || '*',
    max_hits: $('maxhits').value,
  });
  if ($('sortby').value) params.set('sort_by', $('sortby').value);
  const index = $('index').value;
  try {
    const res = await fetch(`/api/v1/${index}/search?` + params);
    const body = await res.json();
    if (my !== gen) return;
    if (!res.ok) { $('meta').textContent = '';
                   $('error').textContent = body.message || JSON.stringify(body);
                   return; }
    $('meta').textContent =
      `${body.num_hits} hits · ${(body.elapsed_time_micros / 1000).toFixed(1)} ms`;
    if (body.errors && body.errors.length) {
      $('error').textContent =
        'partial results — failures:\\n' + body.errors.join('\\n');
    }
    if (body.hits.length) {
      const rows = body.hits.map((h, i) =>
        `<tr><td>${i + 1}</td>` +
        `<td><pre>${esc(JSON.stringify(h, null, 1))}</pre></td></tr>`).join('');
      $('hits').innerHTML =
        `<table><tr><th>#</th><th>document</th></tr>${rows}</table>`;
    }
    if (body.aggregations) {
      $('aggs').innerHTML =
        `<h3>aggregations</h3><pre>${esc(JSON.stringify(body.aggregations, null, 2))}</pre>`;
    }
  } catch (err) {
    if (my !== gen) return;
    $('meta').textContent = ''; $('error').textContent = String(err);
  }
}
async function runSql() {
  const my = ++gen;
  $('error').textContent = ''; $('hits').innerHTML = '';
  $('aggs').innerHTML = ''; $('meta').textContent = 'running…';
  try {
    const res = await fetch('/api/v1/_sql', {
      method: 'POST', headers: {'Content-Type': 'application/json'},
      body: JSON.stringify({query: $('sql').value}),
    });
    const body = await res.json();
    if (my !== gen) return;
    if (!res.ok) { $('meta').textContent = '';
                   $('error').textContent = body.message || JSON.stringify(body);
                   return; }
    $('meta').textContent = `${body.rows.length} row(s)`;
    const head = body.columns.map((c) => `<th>${esc(c)}</th>`).join('');
    const rows = body.rows.map((r) =>
      `<tr>${r.map((v) => `<td>${v === null ? '<i>null</i>'
                           : esc(JSON.stringify(v))}</td>`).join('')}</tr>`
      ).join('');
    $('hits').innerHTML = `<table><tr>${head}</tr>${rows}</table>`;
  } catch (err) {
    if (my !== gen) return;
    $('meta').textContent = ''; $('error').textContent = String(err);
  }
}
function setMode(mode) {
  gen++;  // invalidate any in-flight request of the other tab
  const sql = mode === 'sql';
  $('tab-sql').classList.toggle('active', sql);
  $('tab-search').classList.toggle('active', !sql);
  $('sqlbar').style.display = sql ? 'block' : 'none';
  for (const id of ['index', 'query', 'maxhits', 'sortby', 'go'])
    $(id).style.display = sql ? 'none' : '';
  $('meta').textContent = ''; $('error').textContent = '';
  $('hits').innerHTML = ''; $('aggs').innerHTML = '';
}
$('go').onclick = search;
$('query').addEventListener('keydown', (e) => { if (e.key === 'Enter') search(); });
$('run-sql').onclick = runSql;
$('sql').addEventListener('keydown', (e) => {
  if (e.key === 'Enter' && (e.ctrlKey || e.metaKey)) runSql();
});
$('tab-search').onclick = () => setMode('search');
$('tab-sql').onclick = () => setMode('sql');
loadIndexes();
</script>
</body>
</html>
"""
