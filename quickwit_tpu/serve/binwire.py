"""Compact binary encoding for the internal search RPC payloads.

Role of the reference's protobuf messages + postcard-serialized
intermediate aggregation bytes on the root↔leaf boundary
(`search.proto:360,616`; `root.rs:1120-1170` merges serialized
intermediate results). The JSON transport encodes numpy aggregation
states as nested lists — O(n) Python objects per bucket array on both
sides; this codec writes array dtype + shape + raw little-endian bytes,
so a 10k-bucket histogram state costs one memcpy instead of 10k boxed
floats.

Self-describing tagged format, no schema compiler:
  N null, T/F bool, i varint-zigzag int, f f64, s utf-8 str, b bytes,
  l list, d dict (str keys), k dict (arbitrary keys), a ndarray,
  I ±inf (JSON-unrepresentable floats ride their own tag).
"""

from __future__ import annotations

import struct
from typing import Any

import numpy as np


class BinwireError(ValueError):
    pass


def _uvarint(value: int) -> bytes:
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag(value: int) -> int:
    return (value << 1) ^ (value >> 63) if value < 0 else value << 1


def _unzigzag(value: int) -> int:
    return (value >> 1) ^ -(value & 1)


def _encode(value: Any, out: bytearray) -> None:
    if value is None:
        out += b"N"
    elif value is True:
        out += b"T"
    elif value is False:
        out += b"F"
    elif isinstance(value, np.ndarray):
        out += b"a"
        dt = value.dtype.str.encode()
        out += _uvarint(len(dt)) + dt
        out += _uvarint(value.ndim)
        for dim in value.shape:
            out += _uvarint(dim)
        raw = np.ascontiguousarray(value).tobytes()
        out += _uvarint(len(raw)) + raw
    elif isinstance(value, np.generic):
        _encode(value.item(), out)
    elif isinstance(value, int):
        out += b"i" + _uvarint(_zigzag(value))
    elif isinstance(value, float):
        if value != value or value in (float("inf"), float("-inf")):
            out += b"I" + (b"+" if value > 0 else b"-" if value < 0 else b"n")
        else:
            out += b"f" + struct.pack("<d", value)
    elif isinstance(value, str):
        raw = value.encode()
        out += b"s" + _uvarint(len(raw)) + raw
    elif isinstance(value, (bytes, bytearray)):
        out += b"b" + _uvarint(len(value)) + bytes(value)
    elif isinstance(value, (list, tuple)):
        out += b"l" + _uvarint(len(value))
        for item in value:
            _encode(item, out)
    elif isinstance(value, dict):
        if all(isinstance(k, str) for k in value):
            out += b"d" + _uvarint(len(value))
            for k, v in value.items():
                raw = k.encode()
                out += _uvarint(len(raw)) + raw
                _encode(v, out)
        else:
            # bucket maps key by numbers/tuples; keys are full values
            out += b"k" + _uvarint(len(value))
            for k, v in value.items():
                _encode(k, out)
                _encode(v, out)
    else:
        raise BinwireError(f"unencodable type {type(value).__name__}")


def encode(value: Any) -> bytes:
    out = bytearray()
    _encode(value, out)
    return bytes(out)


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        raw = self.data[self.pos: self.pos + n]
        if len(raw) != n:
            raise BinwireError("truncated payload")
        self.pos += n
        return raw

    def uvarint(self) -> int:
        value = 0
        shift = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            value |= (b & 0x7F) << shift
            if not b & 0x80:
                return value
            shift += 7


def _decode(r: _Reader) -> Any:
    tag = r.take(1)
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"i":
        return _unzigzag(r.uvarint())
    if tag == b"f":
        return struct.unpack("<d", r.take(8))[0]
    if tag == b"I":
        sign = r.take(1)
        return {b"+": float("inf"), b"-": float("-inf"),
                b"n": float("nan")}[sign]
    if tag == b"s":
        return r.take(r.uvarint()).decode()
    if tag == b"b":
        return r.take(r.uvarint())
    if tag == b"l":
        return [_decode(r) for _ in range(r.uvarint())]
    if tag == b"d":
        out = {}
        for _ in range(r.uvarint()):
            key = r.take(r.uvarint()).decode()  # key strictly before value
            out[key] = _decode(r)
        return out
    if tag == b"k":
        out = {}
        for _ in range(r.uvarint()):
            key = _decode(r)
            if isinstance(key, list):
                key = tuple(key)
            out[key] = _decode(r)
        return out
    if tag == b"a":
        dtype = np.dtype(r.take(r.uvarint()).decode())
        shape = tuple(r.uvarint() for _ in range(r.uvarint()))
        raw = r.take(r.uvarint())
        return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()
    raise BinwireError(f"unknown tag {tag!r}")


def decode(data: bytes) -> Any:
    r = _Reader(data)
    try:
        value = _decode(r)
    except IndexError:
        raise BinwireError("truncated payload") from None
    if r.pos != len(data):
        raise BinwireError(f"{len(data) - r.pos} trailing bytes")
    return value
