"""REST server: quickwit API + ES-compatible API + internal search RPC.

Role of the reference's warp router + handlers (`quickwit-serve/src/rest.rs`,
`search_api/rest_handler.rs`, `elasticsearch_api/rest_handler.rs:245,674`,
`index_api/rest_handler.rs`) over Python's stdlib threading HTTP server:

  GET  /health/livez | /health/readyz
  GET  /metrics                                  (prometheus text)
  GET  /api/v1/cluster                           (members)
  POST /api/v1/indexes                           (create index from config)
  GET  /api/v1/indexes                           | /api/v1/indexes/{id}
  PUT  /api/v1/indexes/{id}                      (live config update)
  DELETE /api/v1/indexes/{id}
  GET  /api/v1/indexes/{id}/splits
  POST /api/v1/{index}/ingest?commit=...         (ndjson body)
  GET|POST /api/v1/{index}/search                (query params or JSON)
  POST /api/v1/{index}/search/stream             (alias of search, round 1)
  -- ES-compatible --
  POST|GET /api/v1/_elastic/{index}/_search
  POST /api/v1/_elastic/_msearch
  POST /api/v1/_elastic/_bulk | /{index}/_bulk
  GET  /api/v1/_elastic/_cat/indices
  GET  /api/v1/_elastic/{index}/_field_caps
  -- internal RPC (root↔leaf transport; gRPC's role) --
  POST /internal/leaf_search
  POST /internal/fetch_docs
  POST /internal/heartbeat
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional
from urllib.parse import parse_qs, urlparse

from ..metastore.base import ListSplitsQuery, MetastoreError
from ..observability.metrics import METRICS
from ..indexing.transform import TransformParseError
from ..ingest.router import (INGEST_API_SOURCE_ID, INGEST_V2_SOURCE_ID,
                             INTERNAL_SOURCE_IDS)
from ..query.aggregations import AggParseError
from ..query.es_dsl import EsDslParseError, es_query_to_ast
from ..query.parser import QueryParseError, parse_query_string
from ..search.models import (
    FetchDocsRequest, LeafSearchRequest, SearchRequest, SortField,
    normalize_sort_fields,
)
from ..search.plan import PlanError
from ..tenancy import (
    ES_FALLBACK_HEADER, GLOBAL_TENANCY, OverloadShed, TENANT_HEADER,
    TenantRateLimited, tenant_scope,
)
from .node import Node
from .serializers import leaf_response_from_dict, leaf_response_to_dict

logger = logging.getLogger(__name__)

_MAX_INFLATED_BYTES = 256 << 20  # gzip bodies inflate to at most 256 MiB



_REQUEST_COUNTER = METRICS.counter("qw_http_requests_total", "HTTP requests")
_REQUEST_LATENCY = METRICS.histogram("qw_http_request_duration_seconds",
                                     "HTTP request latency")


class ApiError(Exception):
    def __init__(self, status: int, message: str,
                 headers: Optional[dict[str, str]] = None,
                 payload: Any = None):
        super().__init__(message)
        self.status = status
        # extra response headers (e.g. Retry-After on 429) and an optional
        # structured body overriding the default {"message": ...}
        self.headers = headers or {}
        self.payload = payload


_PARSE_ERRORS = (QueryParseError, EsDslParseError, AggParseError,
                 PlanError, TransformParseError, json.JSONDecodeError,
                 ValueError)
_METASTORE_STATUS = {"not_found": 404, "already_exists": 400,
                     "invalid_argument": 400, "failed_precondition": 409}


def classify_exception(exc: BaseException) -> Optional[int]:
    """Exception → HTTP status, shared by the span classifier and the
    response writer so recorded span status can never diverge from the
    actual response code. None = unhandled (500 + traceback log)."""
    if isinstance(exc, ApiError):
        return exc.status
    if isinstance(exc, (TenantRateLimited, OverloadShed)):
        return 429
    if isinstance(exc, _PARSE_ERRORS):
        return 400
    if isinstance(exc, MetastoreError):
        return _METASTORE_STATUS.get(exc.kind, 500)
    return None


def _throttle_error(exc: Exception) -> ApiError:
    """TenantRateLimited / OverloadShed → 429 with a Retry-After header
    and an ES-compatible error body (clients with ES retry middleware
    back off without custom handling)."""
    import math
    retry_after = max(1, math.ceil(getattr(exc, "retry_after_secs", 1.0)))
    kind = ("rate_limit_exceeded" if isinstance(exc, TenantRateLimited)
            else "overloaded")
    return ApiError(
        429, str(exc), headers={"Retry-After": str(retry_after)},
        payload={"status": 429,
                 "error": {"type": kind, "reason": str(exc)}})


def _search_request_from_params(index_id: str, params: dict[str, Any],
                                default_fields) -> SearchRequest:
    query = params.get("query", "*")
    ast = parse_query_string(query, default_fields)
    sort_fields: tuple[SortField, ...] = (SortField(),)
    sort_by = params.get("sort_by") or params.get("sort_by_field")
    if sort_by:
        if sort_by.startswith("-"):
            sort_fields = (SortField(sort_by[1:].replace("+", ""), "desc"),)
        else:
            sort_fields = (SortField(sort_by.lstrip("+"), "asc"),)
    aggs = params.get("aggs")
    if isinstance(aggs, str):
        aggs = json.loads(aggs)
    def _ts(name):
        value = params.get(name)
        return int(value) * 1_000_000 if value is not None else None
    return SearchRequest(
        # comma-separated lists and glob patterns both resolve at the root
        # (reference: index id patterns on every search route)
        index_ids=index_id.split(","),
        query_ast=ast,
        max_hits=int(params.get("max_hits", 20)),
        start_offset=int(params.get("start_offset", 0)),
        sort_fields=sort_fields,
        aggs=aggs,
        start_timestamp=_ts("start_timestamp"),
        end_timestamp=_ts("end_timestamp"),
        count_hits_exact=str(params.get("count_all", "true")).lower()
        not in ("false", "0", "no"),
        snippet_fields=tuple(params["snippet_fields"].split(","))
        if params.get("snippet_fields") else (),
        timeout_millis=int(params["timeout_ms"])
        if params.get("timeout_ms") is not None else None,
        profile=str(params.get("profile", "false")).lower()
        in ("true", "1", "yes"),
        query_id=params.get("query_id"),
    )


def _search_response_to_json(response) -> dict[str, Any]:
    return response.to_dict()


_ES_DURATION_UNITS = {"nanos": 1e-6, "micros": 1e-3, "ms": 1.0,
                      "s": 1000.0, "m": 60_000.0, "h": 3_600_000.0,
                      "d": 86_400_000.0}


def _parse_es_duration_millis(value) -> Optional[int]:
    """ES time-unit strings ("500ms", "1s", "2m") → millis. Bare numbers
    are millis (ES's own default for `timeout`)."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return int(value)
    text = str(value).strip().lower()
    for unit in sorted(_ES_DURATION_UNITS, key=len, reverse=True):
        if text.endswith(unit):
            number = text[: -len(unit)]
            try:
                return int(float(number) * _ES_DURATION_UNITS[unit])
            except ValueError:
                break
    try:
        return int(float(text))
    except ValueError:
        raise ApiError(400, f"invalid time value: {value!r}")


class RestServer:
    def __init__(self, node: Node, host: Optional[str] = None,
                 port: Optional[int] = None,
                 ingest_rate_limit_mb_per_sec: float = 80.0):
        self.node = node
        from ..common.tower import TokenBucket
        # byte-cost token bucket on ingest (reference: ingest rate limiting)
        self.ingest_bucket = TokenBucket(
            rate_per_sec=ingest_rate_limit_mb_per_sec * 1e6,
            burst=ingest_rate_limit_mb_per_sec * 2e6)
        self.host = host if host is not None else node.config.rest_host
        self.port = port if port is not None else node.config.rest_port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def start(self) -> None:
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        config = self.node.config
        if config.tls_enabled:
            # terminate TLS on the REST listener. Handshake is deferred
            # to the per-connection handler thread
            # (do_handshake_on_connect=False): a client that connects and
            # never speaks must not wedge the shared accept loop.
            context = config.server_ssl_context()
            self._httpd.socket = context.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.port = self._httpd.server_address[1]
        self.node.config.rest_port = self.port
        # qwlint: disable-next-line=QW003 - REST listener: each request
        # binds deadline/tenant from its own headers/params downstream
        # qwlint: disable-next-line=QW008 - serve-layer transport
        # infrastructure (sockets, real IO) outside the DST-raced path; gating
        # it would block the token on real IO
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=f"rest-{self.port}", daemon=True)
        self._thread.start()
        logger.info("REST server listening on %s://%s:%d",
                    "https" if config.tls_enabled else "http",
                    self.host, self.port)

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    # ------------------------------------------------------------------
    # route implementations
    def route(self, method: str, path: str, params: dict[str, Any],
              body: bytes, client_host: str = "",
              content_type: str = "",
              traceparent: str = "",
              tenant_id: str = "") -> tuple[int, Any]:
        """Traced entry point: every request is a server span, joined to
        the caller's trace when a W3C `traceparent` header came in
        (reference: tracing_utils.rs context extraction). The resolved
        tenant (from the `x-qw-tenant` header, `x-opaque-id` fallback, or
        the configured default) is bound ambiently for the whole request;
        with tenancy disabled and no header it resolves to None and the
        stack stays tenant-blind."""
        from ..observability.tracing import TRACER
        with TRACER.span("http.request",
                         {"http.method": method, "http.target": path},
                         remote_parent=traceparent,
                         scope=self.node.config.node_id) as span:
            try:
                tenant = GLOBAL_TENANCY.resolve(tenant_id or None)
                if tenant is not None:
                    span.set_attribute("tenant.id", tenant.tenant_id)
                try:
                    with tenant_scope(tenant):
                        status, payload = self._route_inner(
                            method, path, params, body,
                            client_host=client_host,
                            content_type=content_type)
                except (TenantRateLimited, OverloadShed) as exc:
                    raise _throttle_error(exc)
            except Exception as exc:
                # handled client/server error: classify before the span
                # closes so routine 4xx don't pollute error-rate queries
                code = classify_exception(exc)
                if code is None:
                    raise  # unhandled → span closes with status=error
                span.set_attribute("http.status_code", code)
                span.status = "error" if code >= 500 else "ok"
                raise
            span.set_attribute("http.status_code", status)
            if status >= 500:
                span.status = "error"
            return status, payload

    def _route_inner(self, method: str, path: str, params: dict[str, Any],
                     body: bytes, client_host: str = "",
                     content_type: str = "") -> tuple[int, Any]:
        node = self.node
        if path == "/health/livez":
            return 200, True
        if path == "/health/readyz":
            return (200, True) if node.cluster.is_ready() else (503, False)
        if path == "/metrics":
            # fold buffered flight-recorder counts into qw_flight_* first:
            # emit() defers the labeled counter inc off the hot path
            from ..observability.flight import FLIGHT
            FLIGHT.flush_metrics()
            return 200, METRICS.expose_text()
        if path in ("/ui", "/ui/", "/") and method == "GET":
            from .ui import UI_HTML
            return 200, ("__html__", UI_HTML)
        if path == "/api/v1/cluster":
            return 200, {
                "node_id": node.config.node_id,
                "members": [
                    {"node_id": m.node_id, "roles": list(m.roles),
                     "rest_endpoint": m.rest_endpoint, "ready": m.is_ready}
                    for m in node.cluster.members()
                ],
            }

        # --- internal RPC ---------------------------------------------
        if path == "/internal/leaf_search" and method == "POST":
            request = LeafSearchRequest.from_dict(json.loads(body))
            response = node.search_service.leaf_search(request)
            return 200, leaf_response_to_dict(response)
        if path == "/internal/fetch_docs" and method == "POST":
            request = FetchDocsRequest.from_dict(json.loads(body))
            return 200, node.search_service.fetch_docs(request)
        if path == "/internal/replicate" and method == "POST":
            # follower side of ingest chained replication
            import base64

            from ..ingest.ingester import ReplicationGap
            payload = json.loads(body)
            if payload.get("reset"):
                # leader's retained WAL starts past our gap: restart the
                # replica log at the offered position (records below it
                # are already published; the metastore checkpoint covers)
                node.ingester.replica_reset(
                    payload["index_uid"], payload["source_id"],
                    payload["shard_id"], int(payload["first_position"]))
            try:
                last = node.ingester.replica_persist(
                    payload["index_uid"], payload["source_id"],
                    payload["shard_id"], int(payload["first_position"]),
                    [base64.b64decode(p) for p in payload["payloads"]])
            except ReplicationGap as gap:
                return 409, {"gap": True, "replica_position": gap.have}
            return 200, {"replica_position": last}
        if path == "/internal/kv" and method == "POST":
            # cluster KV (reference put_kv), dispatched on kind
            from ..search.scroll import context_from_dict
            payload = json.loads(body)
            kind = payload.get("kind")
            if kind == "scroll":
                node.scroll_store.put_with_id(
                    payload["key"], context_from_dict(payload["value"]))
            elif kind == "scroll_cursor":
                context = node.scroll_store.get(payload["key"])
                if context is not None:
                    context.cursor = max(context.cursor,
                                         int(payload["value"]))
            else:
                raise ApiError(400, f"unknown kv kind {kind!r}")
            return 200, {"ok": True}
        if path == "/internal/kv_get" and method == "POST":
            from ..search.scroll import context_to_dict
            payload = json.loads(body)
            context = node.scroll_store.get(payload["key"])
            if context is None:
                return 200, {"value": None}
            return 200, {"value": context_to_dict(context)}
        if path == "/internal/apply_indexing_plan" and method == "POST":
            payload = json.loads(body) if body else {}
            return 200, node.apply_indexing_plan(payload.get("tasks", []))
        if path == "/internal/indexing_tasks" and method == "POST":
            return 200, node.indexing_tasks_report()
        if path == "/internal/replica_truncate" and method == "POST":
            payload = json.loads(body)
            node.ingester.replica_truncate(
                payload["index_uid"], payload["source_id"],
                payload["shard_id"], int(payload["position"]))
            return 200, {"ok": True}
        if path == "/internal/heartbeat" and method == "POST":
            payload = json.loads(body)
            from ..cluster.membership import (ClusterMember,
                                              substitute_wildcard_host)
            node.cluster.upsert_heartbeat(ClusterMember(
                node_id=payload["node_id"], roles=tuple(payload["roles"]),
                rest_endpoint=substitute_wildcard_host(
                    payload.get("rest_endpoint", ""), client_host),
                grpc_endpoint=substitute_wildcard_host(
                    payload.get("grpc_endpoint", ""), client_host)))
            return 200, {"node_id": node.config.node_id,
                         "roles": list(node.config.roles),
                         "rest_endpoint": f"{self.host}:{self.port}",
                         "grpc_endpoint": node._grpc_advertise()}

        # --- developer / debug ----------------------------------------
        if path == "/api/v1/developer/pprof/flamegraph" and method == "GET":
            # on-demand CPU profile (reference developer_api/pprof.rs:167):
            # sample every thread for `duration` seconds at `hz`, render a
            # self-contained SVG (or ?format=collapsed for raw stacks).
            # One profile at a time (the reference serializes too):
            # concurrent profilers would sample each other and N×30s
            # GIL-heavy loops are a free DoS.
            from ..observability.profiler import (PROFILE_LOCK, collapse,
                                                  render_svg, sample_stacks)
            duration = min(float(params.get("duration", 2.0)), 30.0)
            hz = min(float(params.get("hz", 100.0)), 1000.0)
            if not PROFILE_LOCK.acquire(blocking=False):
                raise ApiError(429, "a profile is already running")
            try:
                counts = sample_stacks(duration_secs=duration, hz=hz)
            finally:
                PROFILE_LOCK.release()
            if params.get("format") == "collapsed":
                return 200, ("__raw__", collapse(counts).encode(),
                             "text/plain; charset=utf-8")
            svg = render_svg(counts,
                             title=f"{node.config.node_id} CPU profile "
                                   f"({duration:g}s @ {hz:g}Hz)")
            return 200, ("__raw__", svg.encode(), "image/svg+xml")
        if path == "/api/v1/developer/tenants" and method == "GET":
            # per-tenant config + live usage counters + overload state +
            # SLO burn; ?scope=cluster merges every alive peer's and
            # offload worker's report (tenancy/rollup.py)
            if params.get("scope") == "cluster":
                from ..tenancy.rollup import collect_cluster_tenant_report
                return 200, collect_cluster_tenant_report(node)
            from ..observability.slo import SLO_TRACKER
            report = GLOBAL_TENANCY.report()
            report["node_id"] = node.config.node_id
            report["slo"] = SLO_TRACKER.report()
            return 200, report
        if path == "/api/v1/developer/trace" and method == "GET":
            # flight-recorder export: the always-on device timeline as
            # Chrome trace-event JSON (load into Perfetto / chrome://tracing;
            # events carry query_id + tenant + OTLP span correlation)
            from ..observability.flight import FLIGHT
            limit = min(int(params.get("limit", 0) or 0), 1 << 20)
            trace = FLIGHT.to_chrome_trace(
                limit=limit or None,
                process_name=f"quickwit-tpu:{node.config.node_id}")
            return 200, trace
        if path == "/api/v1/developer/slowlog":
            # ring buffer of slow/shed/timed-out query profiles (role of the
            # reference's slow-query log). GET returns the buffer; POST with
            # {"threshold_ms": N} arms/re-arms capture, N=null disarms.
            from ..observability.slowlog import SLOW_QUERY_LOG
            if method == "POST":
                payload = json.loads(body) if body else {}
                threshold = payload.get("threshold_ms")
                SLOW_QUERY_LOG.configure(
                    float(threshold) if threshold is not None else None)
                return 200, {"armed": SLOW_QUERY_LOG.armed,
                             "threshold_ms": SLOW_QUERY_LOG.threshold_ms}
            return 200, {"armed": SLOW_QUERY_LOG.armed,
                         "threshold_ms": SLOW_QUERY_LOG.threshold_ms,
                         "entries": SLOW_QUERY_LOG.entries()}
        if path == "/api/v1/developer/debug":
            import sys as _sys
            import traceback
            from ..search.executor import executor_cache_size
            frames = {}
            for thread_id, frame in _sys._current_frames().items():
                frames[str(thread_id)] = traceback.format_stack(frame)[-4:]
            ctx = node.searcher_context
            return 200, {
                "node_id": node.config.node_id,
                "jit_cache_entries": executor_cache_size(),
                "leaf_cache": ctx.leaf_cache.stats,
                "predicate_cache": ctx.predicate_cache.stats,
                "mask_cache": (ctx.mask_cache.stats
                               if ctx.mask_cache is not None else None),
                "agg_cache": (ctx.agg_cache.stats
                              if ctx.agg_cache is not None else None),
                "open_split_readers": len(ctx._readers),
                "wal_shards": node.ingester.shard_throughput_state(),
                "threads": frames,
            }

        # --- index templates ------------------------------------------
        if path == "/api/v1/templates" and method == "POST":
            node.metastore.create_index_template(json.loads(body))
            return 200, {"created": True}
        if path == "/api/v1/templates" and method == "GET":
            return 200, node.metastore.list_index_templates()
        m = re.fullmatch(r"/api/v1/templates/([^/]+)", path)
        if m and method == "DELETE":
            node.metastore.delete_index_template(m.group(1))
            return 200, {"deleted": True}

        # --- index management -----------------------------------------
        if path == "/api/v1/indexes" and method == "POST":
            metadata = node.index_service.create_index(json.loads(body))
            return 200, metadata.to_dict()
        if path == "/api/v1/indexes" and method == "GET":
            return 200, [m.to_dict() for m in node.metastore.list_indexes()]
        m = re.fullmatch(r"/api/v1/indexes/([^/]+)", path)
        if m:
            index_id = m.group(1)
            if method == "GET":
                return 200, node.metastore.index_metadata(index_id).to_dict()
            if method == "PUT":
                # live config update (reference update_index): search
                # settings, retention, indexing settings, append-only
                # doc-mapping additions
                update = json.loads(body)
                if not isinstance(update, dict):
                    raise ApiError(400, "update must be a JSON object")
                metadata = node.index_service.update_index(index_id,
                                                           update)
                return 200, metadata.to_dict()
            if method == "DELETE":
                removed = node.index_service.delete_index(index_id)
                return 200, {"removed_splits": removed}
        m = re.fullmatch(r"/api/v1/indexes/([^/]+)/splits", path)
        if m and method == "GET":
            metadata = node.metastore.index_metadata(m.group(1))
            splits = node.metastore.list_splits(
                ListSplitsQuery(index_uids=[metadata.index_uid]))
            return 200, {"splits": [s.to_dict() for s in splits]}

        # --- searcher pre-warm (operability: run representative queries
        # once so jit compiles + transfers happen before user traffic) ---
        m = re.fullmatch(r"/api/v1/([^/_][^/]*)/warmup", path)
        if m and method == "POST":
            payload = json.loads(body) if body else {}
            index_id = m.group(1)
            requests = None
            if payload.get("queries"):
                # the SAME request construction production searches use:
                # warmed plan structures (sort, time filters, aggs, k)
                # match real traffic exactly
                fields = node.metastore.index_metadata(
                    index_id).index_config.doc_mapper.default_search_fields
                requests = [
                    _search_request_from_params(index_id, spec, fields)
                    for spec in payload["queries"]]
            return 200, node.warmup_index(index_id, requests)

        # --- delete tasks (reference: delete_task_api/handler.rs) -------
        m = re.fullmatch(r"/api/v1/([^/_][^/]*)/delete-tasks", path)
        if m and method == "POST":
            from ..query.es_dsl import es_query_to_ast
            metadata = node.metastore.index_metadata(m.group(1))
            payload = json.loads(body)
            delete_query = payload.get("query")
            if delete_query is None:
                return 400, {"error": "missing delete query"}
            ast = es_query_to_ast(
                delete_query,
                metadata.index_config.doc_mapper.default_search_fields)
            opstamp = node.metastore.create_delete_task(
                metadata.index_uid, ast.to_dict())
            return 200, {"opstamp": opstamp}
        if m and method == "GET":
            metadata = node.metastore.index_metadata(m.group(1))
            return 200, {"delete_tasks": node.metastore.list_delete_tasks(
                metadata.index_uid)}

        # --- source management (reference: index_api.rs source routes) --
        m = re.fullmatch(r"/api/v1/indexes/([^/]+)/sources", path)
        if m and method == "POST":
            from ..indexing.sources import parse_source_config
            metadata = node.metastore.index_metadata(m.group(1))
            source = parse_source_config(json.loads(body))
            node.metastore.add_source(metadata.index_uid, source)
            return 200, source.to_dict()
        m = re.fullmatch(r"/api/v1/indexes/([^/]+)/sources/([^/]+)", path)
        if m and method == "DELETE":
            if m.group(2) in INTERNAL_SOURCE_IDS:
                # reference: index_api.rs forbids deleting internal sources
                # (their checkpoints guard against WAL replay)
                raise ApiError(
                    400, f"source {m.group(2)!r} is internal and cannot be "
                         f"deleted")
            metadata = node.metastore.index_metadata(m.group(1))
            node.metastore.delete_source(metadata.index_uid, m.group(2))
            return 200, {"deleted": m.group(2)}
        m = re.fullmatch(
            r"/api/v1/indexes/([^/]+)/sources/([^/]+)/reset-checkpoint",
            path)
        if m and method == "PUT":
            # reference index_api reset_source_checkpoint: replay the
            # source from the beginning (exactly-once bookkeeping wiped).
            # The built-in ingest checkpoints guard the WAL against
            # replaying already-published records — never resettable.
            if m.group(2) in INTERNAL_SOURCE_IDS:
                raise ApiError(400, f"{m.group(2)} is a built-in source; "
                                    "its checkpoint guards the ingest "
                                    "WAL against replay")
            metadata = node.metastore.index_metadata(m.group(1))
            node.metastore.reset_source_checkpoint(metadata.index_uid,
                                                   m.group(2))
            return 200, {"source_id": m.group(2), "checkpoint": "reset"}
        m = re.fullmatch(r"/api/v1/indexes/([^/]+)/sources/([^/]+)/toggle",
                         path)
        if m and method == "PUT":
            metadata = node.metastore.index_metadata(m.group(1))
            parsed = json.loads(body) if body else {}
            if not isinstance(parsed, dict):
                raise ApiError(400, "toggle body must be a JSON object")
            enable = bool(parsed.get("enable", True))
            node.metastore.toggle_source(metadata.index_uid, m.group(2), enable)
            return 200, {"source_id": m.group(2), "enabled": enable}

        # --- ingest ----------------------------------------------------
        m = re.fullmatch(r"/api/v1/([^/_][^/]*)/ingest", path)
        if m and method == "POST":
            self._check_ingest_rate(body)
            docs = _parse_ndjson(body)
            if params.get("commit") == "wal":
                # v2 path: durable WAL append, indexed by the next ingest pass
                return 200, node.ingest_v2(m.group(1), docs)
            result = node.ingest(m.group(1), docs,
                                 commit=params.get("commit", "auto"))
            return 200, result

        # --- otlp / jaeger --------------------------------------------
        if path == "/api/v1/otlp/v1/logs" and method == "POST":
            if "protobuf" in content_type:  # binary OTLP/HTTP (the default
                # encoding of real OTel collectors/SDKs)
                from .otlp_proto import decode_logs_request
                node.otel.ingest_logs(decode_logs_request(body))
                # empty ExportLogsServiceResponse (all fields default)
                return 200, ("__raw__", b"", "application/x-protobuf")
            return 200, node.otel.ingest_logs(json.loads(body))
        if path == "/api/v1/otlp/v1/traces" and method == "POST":
            if "protobuf" in content_type:
                from .otlp_proto import decode_traces_request
                node.otel.ingest_traces(decode_traces_request(body))
                return 200, ("__raw__", b"", "application/x-protobuf")
            return 200, node.otel.ingest_traces(json.loads(body))
        if path == "/api/v1/jaeger/api/services":
            return 200, {"data": node.otel.services(), "total": 0}
        m = re.fullmatch(r"/api/v1/jaeger/api/services/([^/]+)/operations", path)
        if m:
            return 200, {"data": node.otel.operations(m.group(1)), "total": 0}
        m = re.fullmatch(r"/api/v1/jaeger/api/traces/([^/]+)", path)
        if m:
            spans = node.otel.get_trace(m.group(1))
            if not spans:
                raise ApiError(404, f"trace {m.group(1)!r} not found")
            return 200, {"data": [node.otel.jaeger_trace(m.group(1), spans)]}
        if path == "/api/v1/jaeger/api/traces":
            trace_ids = node.otel.find_traces(
                service=params.get("service"),
                operation=params.get("operation"),
                min_duration_micros=int(params["minDuration"])
                if params.get("minDuration") else None,
                limit=int(params.get("limit", 20)))
            return 200, {"data": [
                node.otel.jaeger_trace(t, node.otel.get_trace(t))
                for t in trace_ids]}

        # --- SQL analytics (role of the fork's datafusion_api) --------
        if path == "/api/v1/_sql" and method == "POST":
            from ..analytics import SqlError, execute_sql
            from ..search.models import SearchRequest as _SR
            payload = json.loads(body) if body else {}
            statement = payload.get("query")
            if not isinstance(statement, str) or not statement.strip():
                raise ApiError(400, "_sql expects {\"query\": \"SELECT ...\"}")

            def run_search(index_id, query_ast, max_hits, aggs):
                return node.root_searcher.search(_SR(
                    index_ids=[index_id], query_ast=query_ast,
                    max_hits=max_hits, aggs=aggs))

            try:
                return 200, execute_sql(statement, run_search)
            except SqlError as exc:
                raise ApiError(400, str(exc))
        # --- scroll / list apis ---------------------------------------
        if path == "/api/v1/scroll":
            scroll_id = params.get("scroll_id")
            if scroll_id is None and body:
                scroll_id = json.loads(body).get("scroll_id")
            if not scroll_id:
                raise ApiError(400, "missing scroll_id")
            if method == "DELETE":  # clear-scroll (frees the context early)
                return 200, {"released": node.end_scroll(scroll_id)}
            return 200, node.continue_scroll(scroll_id)
        m = re.fullmatch(r"/api/v1/([^/_][^/]*)/list-terms", path)
        if m:
            from ..search.list_apis import root_list_terms
            if "field" not in params:
                raise ApiError(400, "missing field parameter")
            terms = root_list_terms(
                node.metastore, node.search_service.context, m.group(1),
                params["field"], start_key=params.get("start_key"),
                end_key=params.get("end_key"),
                max_terms=int(params.get("max_terms", 100)))
            return 200, {"terms": terms}
        m = re.fullmatch(r"/api/v1/([^/]+)/fields", path)
        if m:
            from ..search.list_apis import list_fields
            return 200, {"fields": list_fields(node.metastore,
                                               m.group(1).split(","))}
        # --- query cancellation ----------------------------------------
        m = re.fullmatch(r"/api/v1/search/([^/]+)", path)
        if m and method == "DELETE":
            # cancel an in-flight query by its caller-chosen query_id: the
            # chunked leaf scan observes the token at its next chunk
            # boundary (reference role: ES `_tasks/<id>/_cancel`). Non-DELETE
            # methods fall through (an index named "search" keeps its routes).
            from ..observability.metrics import SEARCH_CANCEL_TOTAL
            from ..search.cancel import CANCEL_REGISTRY
            cancelled = CANCEL_REGISTRY.cancel(
                m.group(1), reason="REST DELETE")
            SEARCH_CANCEL_TOTAL.inc()
            # idempotent: cancelling a finished/unknown query is a no-op,
            # not an error (the race against completion is inherent)
            return 200, {"query_id": m.group(1), "cancelled": cancelled}
        # --- search ----------------------------------------------------
        m = re.fullmatch(r"/api/v1/([^/_][^/]*)/search(?:/stream)?", path)
        if m:
            if method not in ("GET", "POST"):
                raise ApiError(405, f"method {method} not allowed on search")
            index_id = m.group(1)
            if method == "POST" and body:
                payload = json.loads(body)
                params = {**params, **payload}
            default_fields = self._default_fields(index_id)
            request = _search_request_from_params(index_id, params, default_fields)
            if params.get("scroll"):
                ttl = _parse_scroll_ttl(params["scroll"])
                return 200, node.start_scroll(request, ttl)
            response = node.root_searcher.search(request)
            return 200, _search_response_to_json(response)

        # --- ES-compatible --------------------------------------------
        if path.startswith("/api/v1/_elastic"):
            return self._route_elastic(method, path[len("/api/v1/_elastic"):],
                                       params, body)
        raise ApiError(404, f"no route for {method} {path}")

    # ------------------------------------------------------------------
    def _check_ingest_rate(self, body: bytes) -> None:
        from ..common.tower import RateLimitExceeded
        cost = max(len(body), 1)
        if cost > self.ingest_bucket.burst:
            raise ApiError(413, f"ingest body of {cost} bytes exceeds the "
                                f"maximum batch size ({int(self.ingest_bucket.burst)})")
        try:
            self.ingest_bucket.acquire_or_raise(cost=cost)
        except RateLimitExceeded as exc:
            raise ApiError(429, str(exc))

    def _default_fields(self, index_pattern: str):
        # resolve lists/globs the same way the root searcher does, so
        # `logs-*` picks up a real index's default_search_fields. Metastore
        # backend failures propagate to the handler's kind mapping (a
        # metastore outage must not read as 404 not-found). The second
        # resolution inside root.search hits the TTL-cached metastore
        # state, so the cost is an in-memory scan, not another fetch.
        resolved = self.node.root_searcher._resolve_indexes(
            index_pattern.split(","))
        if not resolved:
            # fail on the real problem before query parsing can mask it
            # with a default_search_fields complaint
            raise ApiError(404, f"no index matches {index_pattern!r}")
        return resolved[0].index_config.doc_mapper.default_search_fields

    def _lenient_validator(self, index_pattern: str):
        """`valid(field, value|None)` for ES `query_string.lenient`:
        unknown fields and type-unparsable values become match-none. A
        clause survives if ANY resolved index maps the field validly
        (multi-index patterns: ES evaluates leniency per index)."""
        resolved = self.node.root_searcher._resolve_indexes(
            index_pattern.split(","))
        mappers = [meta.index_config.doc_mapper for meta in resolved]

        def valid(field: str, value) -> bool:
            if not mappers:
                return True
            from ..search.predicate_cache import canonical_query_term
            for mapper in mappers:
                fm = mapper.field(field)
                if fm is None:
                    continue
                if value is None:
                    return True
                try:
                    canonical_query_term(fm, str(value))
                    return True
                except (ValueError, TypeError):
                    continue
            return False

        return valid

    def _route_elastic(self, method: str, path: str, params: dict[str, Any],
                       body: bytes) -> tuple[int, Any]:
        node = self.node
        if path in ("", "/") and method == "GET":
            # ES cluster-info handshake (reference:
            # elasticsearch_api/rest_handler.rs:73 es_compat_cluster_info)
            from .. import __version__
            return 200, {
                "name": node.config.node_id,
                "cluster_name": node.config.cluster_id,
                "cluster_uuid": node.config.cluster_id,
                "tagline": "You Know, for Search",
                "version": {
                    "distribution": "quickwit-tpu",
                    "number": "7.17.0",
                    "build_hash": __version__,
                    "build_date": "2026-01-01T00:00:00Z",
                    "build_snapshot": False,
                    "lucene_version": "8.11.1",
                    "minimum_wire_compatibility_version": "6.8.0",
                    "minimum_index_compatibility_version": "6.0.0-beta1",
                },
            }
        m = re.fullmatch(r"/([^/]+)/_search", path)
        if m:
            payload = json.loads(body) if body else {}
            request = self._es_search_request(m.group(1), payload, params)
            if params.get("scroll"):
                if str(params.get("allow_partial_search_results", "true")
                       ).lower() == "false":
                    return 400, {"status": 400, "error": {
                        "reason": "Invalid argument: Quickwit only supports "
                                  "scroll API with "
                                  "allow_partial_search_results set to true"}}
                ttl = _parse_scroll_ttl(params["scroll"])
                if ttl > 1800:
                    return 400, {"status": 400, "error": {
                        "reason": "Invalid argument: Quickwit only supports "
                                  "scroll TTL period up to 1800 secs"}}
                page = node.start_scroll(request, ttl)
                return 200, self._es_scroll_page(
                    page, page.get("index", m.group(1)))
            response = node.root_searcher.search(request)
            return 200, self._es_search_response(response, request, params)
        if path == "/_search/scroll":
            payload = json.loads(body) if body else {}
            scroll_id = payload.get("scroll_id") or params.get("scroll_id")
            if not scroll_id:
                raise ApiError(400, "missing scroll_id")
            if method == "DELETE":
                # ES clear-scroll accepts a single id or an array of ids
                ids = scroll_id if isinstance(scroll_id, list) else [scroll_id]
                return 200, {"succeeded": all(
                    [node.end_scroll(str(sid)) for sid in ids])}
            if isinstance(scroll_id, list):
                raise ApiError(400, "scroll continuation takes one scroll_id")
            page = node.continue_scroll(scroll_id)
            return 200, self._es_scroll_page(page, page.get("index", ""))
        if path == "/_msearch" and method == "POST":
            lines = [json.loads(line) for line in body.split(b"\n") if line.strip()]
            responses = []
            for i in range(0, len(lines) - 1, 2):
                header, query_body = lines[i], lines[i + 1]
                index = header.get("index", "*")
                index = ",".join(index) if isinstance(index, list) else index
                try:
                    request = self._es_search_request(index, query_body,
                                                      params)
                    response = node.root_searcher.search(request)
                    entry = self._es_search_response(response, request,
                                                     params)
                    entry["status"] = 200
                except ApiError as exc:
                    # per-request failures (e.g. a missing index) ride in
                    # the response array, matching ES msearch semantics
                    if exc.status == 404 and header.get("ignore_unavailable"):
                        entry = {"status": 200, "took": 0,
                                 "timed_out": False,
                                 "hits": {"total": {"value": 0,
                                                    "relation": "eq"},
                                          "hits": []}}
                    else:
                        entry = {"status": exc.status,
                                 "error": {"reason": str(exc)}}
                responses.append(entry)
            return 200, {"responses": responses}
        m = re.fullmatch(r"(?:/([^/]+))?/_bulk", path)
        if m and method == "POST":
            self._check_ingest_rate(body)
            return 200, self._es_bulk(m.group(1), body, params)
        m = re.fullmatch(r"/([^/]+)/_count", path)
        if m and method in ("GET", "POST"):
            payload = json.loads(body) if body else {}
            request = self._es_search_request(m.group(1), payload, params)
            from dataclasses import replace as _dc_replace
            response = node.root_searcher.search(
                _dc_replace(request, max_hits=0, aggs=None))
            return 200, {"count": response.num_hits,
                         "_shards": {"total": 1, "successful": 1,
                                     "skipped": 0, "failed": 0}}
        m = re.fullmatch(r"(?:/([^/_][^/]*))?/_stats", path)
        if m and method == "GET":
            from ..models.split_metadata import SplitState
            pattern = m.group(1)
            indices = {}
            total_docs = total_bytes = total_segments = 0
            for im in sorted(node.metastore.list_indexes(),
                             key=lambda im: im.index_id):
                if pattern and not _matches_index_pattern(im.index_id,
                                                          pattern):
                    continue
                splits = node.metastore.list_splits(
                    ListSplitsQuery(index_uids=[im.index_uid],
                                    states=[SplitState.PUBLISHED]))
                docs = sum(s.metadata.num_docs for s in splits)
                size = sum(s.metadata.footprint_bytes for s in splits)
                total_docs += docs
                total_bytes += size
                total_segments += len(splits)
                stats = {"docs": {"count": docs, "deleted": 0},
                         "store": {"size_in_bytes": size},
                         "segments": {"count": len(splits)}}
                indices[im.index_id] = {"primaries": stats, "total": stats}
            if pattern and not indices and not any(
                    ch in pattern for ch in "*?"):
                # concrete name misses -> 404; an unmatched WILDCARD is an
                # empty 200 (ES allow_no_indices=true default)
                raise ApiError(404, f"no index matches {pattern!r}")
            all_stats = {"docs": {"count": total_docs, "deleted": 0},
                         "store": {"size_in_bytes": total_bytes},
                         "segments": {"count": total_segments}}
            return 200, {"_all": {"primaries": all_stats,
                                  "total": all_stats},
                         "indices": indices}
        m = re.fullmatch(r"/_cat/indices(?:/([^/]+))?", path)
        if m:
            # reference only supports format=json and the h/health params;
            # anything else is a 400
            if params.get("format") != "json":
                raise ApiError(400, "_cat/indices requires format=json")
            unknown = set(params) - {"format", "h", "health"}
            if unknown:
                raise ApiError(400, f"unsupported _cat parameters: "
                                    f"{sorted(unknown)}")
            pattern = m.group(1)
            columns = ([c.strip() for c in params["h"].split(",")]
                       if params.get("h") else None)
            out = []
            for im in sorted(node.metastore.list_indexes(),
                             key=lambda im: im.index_id):
                if pattern and not _matches_index_pattern(im.index_id,
                                                          pattern):
                    continue
                health = "green"
                if params.get("health") and params["health"] != health:
                    continue
                from ..models.split_metadata import SplitState
                splits = node.metastore.list_splits(
                    ListSplitsQuery(index_uids=[im.index_uid],
                                    states=[SplitState.PUBLISHED]))
                num_docs = sum(s.metadata.num_docs for s in splits)
                size = sum(s.metadata.footprint_bytes for s in splits)
                row = {
                    "health": health, "status": "open",
                    "index": im.index_id,
                    "uuid": im.index_uid,
                    "pri": "1", "rep": "0",
                    "docs.count": str(num_docs), "docs.deleted": "0",
                    "dataset.size": _human_size(size),
                    "store.size": _human_size(size),
                    "pri.store.size": _human_size(size),
                }
                if columns:
                    row = {c: row.get(c, "") for c in columns}
                out.append(row)
            return 200, out
        m = re.fullmatch(r"/_resolve/index/([^/]+)", path)
        if m:
            indices = [{"name": im.index_id, "attributes": ["open"]}
                       for im in sorted(node.metastore.list_indexes(),
                                        key=lambda im: im.index_id)
                       if _matches_index_pattern(im.index_id, m.group(1))]
            return 200, {"indices": indices, "aliases": [],
                         "data_streams": []}
        if path == "/_cluster/health":
            return 200, {"cluster_name": node.config.cluster_id,
                         "status": "green", "timed_out": False,
                         "number_of_nodes": len(node.cluster.members())}
        m = re.fullmatch(r"/([^/_][^/]*)", path)
        if m and method == "DELETE":
            # ES delete-index: comma lists; 404 on any missing name unless
            # ignore_unavailable=true
            names = [n for n in m.group(1).split(",") if n]
            known = {im.index_id for im in node.metastore.list_indexes()}
            missing = [n for n in names if n not in known]
            ignore = str(params.get("ignore_unavailable", "false")
                         ).lower() == "true"
            if missing and not ignore:
                raise ApiError(404, f"no such index {missing[0]!r}")
            for name in names:
                if name in known:
                    node.index_service.delete_index(name)
            return 200, {"acknowledged": True}
        if path == "/_field_caps":
            return self._es_field_caps("*", params, body)
        m = re.fullmatch(r"/([^/]+)/_field_caps", path)
        if m:
            return self._es_field_caps(m.group(1), params, body)
        raise ApiError(404, f"no elastic route for {method} {path}")

    # list-fields type class → ES field-caps entry types (reference:
    # elasticsearch_api/model/field_capability.rs:150 — Str expands to
    # keyword AND text entries with the same flags)
    _FIELD_CAPS_TYPES = {"str": ("keyword", "text"), "long": ("long",),
                         "double": ("double",), "boolean": ("boolean",),
                         "date": ("date_nanos",), "ip": ("ip",),
                         "binary": ("binary",)}

    def _es_field_caps(self, index_pattern: str, params: dict[str, Any],
                       body: bytes = b"") -> tuple[int, Any]:
        """ES `_field_caps`, driven by the per-split field registries
        (reference: build_list_field_request_for_es_api +
        convert_to_es_field_capabilities_response). A POST `index_filter`
        prunes splits via its conjunctive tag terms and time bounds;
        empty/invalid filters are 400 like ES."""
        from ..search.list_apis import list_field_entries
        node = self.node
        patterns = index_pattern.split(",")
        known = {im.index_id for im in node.metastore.list_indexes()}
        for p in patterns:
            # concrete (non-wildcard) names must exist; wildcards may
            # match nothing (ES expand_wildcards semantics)
            if p and "*" not in p and "?" not in p and p not in known:
                raise ApiError(404, f"no such index {p!r}")
        filter_ast = None
        if body:
            payload = json.loads(body)
            index_filter = payload.get("index_filter")
            if index_filter is not None:
                if not isinstance(index_filter, dict) or not index_filter:
                    raise ApiError(400, "index_filter must be a non-empty "
                                        "query object")
                try:
                    filter_ast = es_query_to_ast(index_filter)
                except EsDslParseError as exc:
                    raise ApiError(400, f"invalid index_filter: {exc}")
        field_patterns = None
        if params.get("fields"):
            field_patterns = [p.strip()
                              for p in str(params["fields"]).split(",")]
        entries = list_field_entries(
            node.metastore, node.search_service.context,
            patterns, field_patterns=field_patterns,
            filter_ast=filter_ast,
            start_timestamp=(int(params["start_timestamp"])
                             if params.get("start_timestamp") else None),
            end_timestamp=(int(params["end_timestamp"])
                           if params.get("end_timestamp") else None))
        indices = sorted({i for e in entries for i in e["index_ids"]})
        fields: dict[str, dict[str, Any]] = {}
        for e in entries:
            for es_type in self._FIELD_CAPS_TYPES.get(e["type_class"], ()):
                cap = {"metadata_field": False, "type": es_type,
                       "searchable": e["searchable"],
                       "aggregatable": e["aggregatable"]}
                if len(e["index_ids"]) != len(indices):
                    cap["indices"] = sorted(e["index_ids"])
                fields.setdefault(e["field_name"], {})[es_type] = cap
        return 200, {"indices": indices, "fields": fields}

    def _es_search_request(self, index: str, payload: dict[str, Any],
                           params: dict[str, Any]) -> SearchRequest:
        index_ids = index.split(",")
        default_fields = self._default_fields(index)  # full list/pattern
        if params.get("q"):
            # the `q` query-string param overrides any body query
            # (reference: es_compat_index_search semantics)
            ast = parse_query_string(params["q"], default_fields)
        elif "query" in payload:
            ast = es_query_to_ast(payload["query"], default_fields,
                                  self._lenient_validator(index))
        else:
            ast = parse_query_string("*")
        if params.get("extra_filters"):
            # quickwit extension: comma-separated query-string clauses
            # ANDed onto the query (reference: rest_handler extra_filters)
            from ..query.ast import Bool as QBool
            filters = tuple(
                parse_query_string(clause, default_fields)
                for clause in str(params["extra_filters"]).split(",")
                if clause)
            if filters:
                ast = QBool(must=(ast,), filter=filters)
        sort_fields: tuple[SortField, ...] = (SortField(),)
        sort_spec = payload.get("sort")
        if not sort_spec and params.get("sort"):
            # GET-param form: "field:order,field2:order2"
            sort_spec = [
                {part.partition(":")[0]: part.partition(":")[2] or "asc"}
                for part in str(params["sort"]).split(",") if part]
        if sort_spec:
            if isinstance(sort_spec, (str, dict)):
                # single string or single {field: spec} mapping
                sort_spec = [sort_spec] if isinstance(sort_spec, str) else [
                    {k: v} for k, v in sort_spec.items()]
            parsed = []
            for entry in sort_spec[:2]:  # up to two sort keys (reference max)
                if isinstance(entry, str):
                    field_name, _, order = entry.partition(":")
                    parsed.append(SortField(field_name, order or "asc"))
                else:
                    field_name, spec = next(iter(entry.items()))
                    order = (spec.get("order", "asc")
                             if isinstance(spec, dict) else spec)
                    parsed.append(SortField(field_name, order))
            sort_fields = tuple(parsed)
        # ES date sorts exchange epoch MILLIS by default (nanos with
        # format=epoch_nanos_int); internal sort keys are micros
        scales = self._es_sort_scales(index, sort_fields, sort_spec)
        search_after = None
        if payload.get("search_after"):
            marker = payload["search_after"]
            if not isinstance(marker, list):
                raise ApiError(400, "search_after must be an array (a hit's "
                                    "sort array)")
            if payload.get("from") or params.get("from"):
                # ES rejects the combination too; silently applying the
                # offset after the marker would skip docs on every page
                raise ApiError(
                    400, "search_after cannot be combined with from")
            # count the keys as the engine normalizes them (e.g. a _doc
            # secondary folds into the implicit tie-break) so the marker
            # arity matches the sort arrays our own hits emit
            n_keys = len(normalize_sort_fields(tuple(sort_fields)))
            tiebreak = marker[-1] if marker else None
            if (len(marker) == n_keys + 1 and isinstance(tiebreak, str)
                    and "|" in tiebreak):
                split_id, _, doc_id = tiebreak.rpartition("|")
                try:
                    search_after = (list(marker[:n_keys])
                                    + [split_id, int(doc_id)])
                except ValueError:
                    raise ApiError(400, f"malformed shard-doc tiebreak "
                                        f"{tiebreak!r}")
            elif len(marker) == n_keys:
                # value-only marker (no shard-doc tiebreak): ES resumes
                # strictly after the VALUE — docs tying the marker on every
                # key are skipped entirely
                search_after = list(marker) + [None, -1]
            if search_after is not None:
                search_after = ([self._scale_in(v, scales[i] if
                                                i < len(scales) else None)
                                 for i, v in
                                 enumerate(search_after[:n_keys])]
                                + search_after[n_keys:])
            else:
                raise ApiError(
                    400, "search_after must be the hit's sort array "
                         "(sort values, optionally with the trailing "
                         "shard-doc tiebreak emitted in hits.hits[].sort)")
        track_total = payload.get("track_total_hits",
                                   params.get("track_total_hits", True))
        if isinstance(track_total, str):  # query-param form is a string
            track_total = track_total.lower() not in ("false", "0", "no")
        request = SearchRequest(
            index_ids=index_ids,
            query_ast=ast,
            max_hits=int(payload.get("size", params.get("size", 10))),
            start_offset=int(payload.get("from", params.get("from", 0))),
            sort_fields=sort_fields,
            aggs=payload.get("aggs") or payload.get("aggregations"),
            count_hits_exact=track_total is not False,
            search_after=search_after,
            timeout_millis=_parse_es_duration_millis(
                payload.get("timeout", params.get("timeout"))),
            # ES `"profile": true` body flag (query-param form rides along
            # for GET searches)
            profile=bool(payload.get("profile")) or
            str(params.get("profile", "false")).lower()
            in ("true", "1", "yes"),
        )
        request._es_sort_scales = scales  # response-side display scaling
        return request

    def _es_sort_scales(self, index_pattern: str, sort_fields,
                        sort_spec) -> list:
        """Per-sort-key display scale: 'ms' (default ES date exchange
        format), 'ns' (format=epoch_nanos_int), or None (non-date)."""
        try:
            resolved = self.node.root_searcher._resolve_indexes(
                index_pattern.split(","))
            mapper = resolved[0].index_config.doc_mapper if resolved else None
        # qwlint: disable-next-line=QW004 - best-effort mapper lookup for
        # ES sort-scale shims; a failure here just skips scaling and the
        # real resolution error surfaces from the search itself
        except Exception:  # noqa: BLE001 - resolution errors surface later
            mapper = None
        scales = []
        specs = sort_spec if isinstance(sort_spec, list) else []
        for i, sf in enumerate(sort_fields):
            fm = mapper.field(sf.field) if mapper is not None else None
            if fm is None:
                scales.append(None)  # unknown: pass markers through
                continue
            if fm.type.value == "text":
                scales.append("txt")  # never coerce string markers
                continue
            if fm.type.value != "datetime":
                scales.append("num")  # numeric: coerce "5688" like ES
                continue
            fmt = None
            if i < len(specs) and isinstance(specs[i], dict):
                inner = next(iter(specs[i].values()))
                if isinstance(inner, dict):
                    fmt = inner.get("format")
            scales.append("ns" if fmt == "epoch_nanos_int" else "ms")
        return scales

    @staticmethod
    def _scale_in(value, scale):
        """Marker value (exchange format) → internal micros; numeric
        strings coerce like ES."""
        if value is None or isinstance(value, bool):
            return value
        if scale in (None, "txt"):
            return value  # text/unknown sort: markers pass through verbatim
        if isinstance(value, str):
            try:
                value = float(value) if "." in value else int(value)
            except ValueError:
                return value
        if scale == "ms":
            return int(value) * 1000
        if scale == "ns":
            return int(value) // 1000
        return value

    @staticmethod
    def _scale_out(value, scale):
        if value is None or isinstance(value, str) or \
                scale in (None, "txt", "num"):
            return value
        if scale == "ms":
            return int(value) // 1000
        return int(value) * 1000

    @staticmethod
    def _es_scroll_page(page: dict[str, Any], index: str) -> dict[str, Any]:
        """qw scroll page (raw-doc hits) → ES scroll response shape."""
        out = {
            "_scroll_id": page.get("scroll_id", ""),
            "took": page.get("elapsed_time_micros", 0) // 1000,
            "timed_out": False,
            "hits": {
                "total": {"value": page.get("num_hits", 0),
                          "relation": "eq"},
                "hits": [{"_index": index, "_source": doc}
                         for doc in page.get("hits", [])],
            },
        }
        if page.get("aggregations") is not None:
            out["aggregations"] = page["aggregations"]
        return out

    @staticmethod
    def _es_search_response(response, request: SearchRequest,
                            params: Optional[dict[str, Any]] = None
                            ) -> dict[str, Any]:
        includes = excludes = None
        if params:
            includes = _parse_source_param(params.get("_source_includes"))
            excludes = _parse_source_param(params.get("_source_excludes"))
        hits = []
        for hit in response.hits:
            source = hit.doc
            if includes or excludes:
                source = _filter_source(source, includes, excludes)
            entry = {
                "_index": request.index_ids[0],
                "_id": f"{hit.split_id}:{hit.doc_id}",
                "_score": hit.score,
                "_source": source,
            }
            if hit.sort_values:
                # trailing shard-doc tiebreak (role of ES's implicit
                # `_shard_doc` under PIT): feeding the whole array back as
                # `search_after` resumes exactly after this hit, ties incl.
                # Missing sort values stay as null (ES does the same) so a
                # page ending on a missing-value hit still yields a marker.
                scales = getattr(request, "_es_sort_scales", [])
                values = [RestServer._scale_out(
                    v, scales[i] if i < len(scales) else None)
                    for i, v in enumerate(hit.sort_values)]
                entry["sort"] = values + [f"{hit.split_id}|{hit.doc_id}"]
            if hit.snippets:
                entry["highlight"] = hit.snippets
            hits.append(entry)
        relation = "eq" if request.count_hits_exact else "gte"
        out = {
            "took": response.elapsed_time_micros // 1000,
            "timed_out": bool(getattr(response, "timed_out", False)),
            "hits": {
                "total": {"value": response.num_hits, "relation": relation},
                "max_score": max((h.score for h in response.hits
                                  if h.score is not None), default=None),
                "hits": hits,
            },
            **({"aggregations": response.aggregations}
               if response.aggregations is not None else {}),
            # phase waterfall (additive, only when the request asked): the
            # shape is ours, not ES's shard-profile schema — the flag is
            # what is ES-compatible
            **({"profile": response.profile}
               if getattr(response, "profile", None) is not None else {}),
        }
        failed = getattr(response, "failed_splits", None) or []
        if failed:
            # `_shards` is additive: emitted only when failures exist, so
            # fully-successful responses keep their exact historical shape
            attempted = (getattr(response, "num_attempted_splits", 0)
                         or len(failed))
            out["_shards"] = {
                "total": attempted,
                "successful": getattr(response, "num_successful_splits", 0),
                "skipped": 0,
                "failed": len(failed),
                "failures": [
                    {"shard": e.split_id,
                     "reason": {"type": "split_search_error",
                                "reason": e.error}}
                    for e in failed],
            }
        return out

    def _es_bulk(self, default_index: Optional[str], body: bytes,
                 params: dict[str, Any]) -> dict[str, Any]:
        lines = [line for line in body.split(b"\n") if line.strip()]
        docs_by_index: dict[str, list[dict]] = {}
        items = []
        i = 0
        while i < len(lines):
            action = json.loads(lines[i])
            kind = next(iter(action))
            if kind not in ("index", "create"):
                raise ApiError(400, f"unsupported bulk action {kind!r}")
            index = action[kind].get("_index", default_index)
            if index is None:
                raise ApiError(400, "bulk action missing _index")
            doc = json.loads(lines[i + 1])
            docs_by_index.setdefault(index, []).append(doc)
            items.append({kind: {"_index": index, "status": 201}})
            i += 2
        errors = False
        for index, docs in docs_by_index.items():
            try:
                self.node.ingest(index, docs, commit=params.get("refresh", "auto"))
            except MetastoreError as exc:
                errors = True
                for item in items:
                    entry = next(iter(item.values()))
                    if entry["_index"] == index:
                        entry["status"] = 404
                        entry["error"] = str(exc)
        return {"errors": errors, "items": items}


def _matches_index_pattern(index_id: str, pattern: str) -> bool:
    import fnmatch
    return any(fnmatch.fnmatch(index_id, p)
               for p in pattern.split(",") if p)


def _human_size(num_bytes: int) -> str:
    """ES _cat human sizes: 100b / 23.5kb / 1.2mb / 3.4gb."""
    value = float(num_bytes)
    for unit in ("b", "kb", "mb", "gb", "tb"):
        if value < 1024 or unit == "tb":
            if unit == "b":
                return f"{int(value)}b"
            return f"{value:.1f}{unit}"
        value /= 1024.0
    return f"{value:.1f}tb"


def _filter_source(doc: Any, includes: "list[str] | None",
                   excludes: "list[str] | None") -> Any:
    """ES `_source_includes`/`_source_excludes` filtering with dotted
    paths: an include keeps the named subtree (parents materialize along
    the path); excludes remove subtrees and win over includes."""
    def subtree(node: Any, path: list[str]) -> Any:
        if not path or not isinstance(node, dict):
            return node
        if path[0] not in node:
            return _MISSING
        inner = subtree(node[path[0]], path[1:])
        return _MISSING if inner is _MISSING else {path[0]: inner}

    def merge(a: Any, b: Any) -> Any:
        if isinstance(a, dict) and isinstance(b, dict):
            out = dict(a)
            for k, v in b.items():
                out[k] = merge(out[k], v) if k in out else v
            return out
        return b

    out = doc
    if includes:
        out = {}
        for inc in includes:
            part = subtree(doc, inc.split("."))
            if part is not _MISSING:
                out = merge(out, part)
    if excludes:
        def drop(node: Any, path: list[str]) -> Any:
            if not isinstance(node, dict) or not path:
                return node
            if len(path) == 1:
                return {k: v for k, v in node.items() if k != path[0]}
            return {k: (drop(v, path[1:]) if k == path[0] else v)
                    for k, v in node.items()}
        for exc in excludes:
            out = drop(out, exc.split("."))
    return out


_MISSING = object()


def _parse_source_param(value: "str | None") -> "list[str] | None":
    """Accepts `a,b.c` and the bracketed `['a','b']` form clients send."""
    if not value:
        return None
    text = value.strip()
    if text.startswith("["):
        text = text.strip("[]")
        parts = [p.strip().strip("'\"") for p in text.split(",")]
    else:
        parts = [p.strip() for p in text.split(",")]
    return [p for p in parts if p] or None


def _parse_scroll_ttl(text: str) -> float:
    text = text.strip()
    units = {"s": 1, "m": 60, "h": 3600}
    if text and text[-1] in units:
        return float(text[:-1]) * units[text[-1]]
    return float(text)


def _parse_ndjson(body: bytes) -> list[dict]:
    docs = []
    for line in body.split(b"\n"):
        line = line.strip()
        if line:
            docs.append(json.loads(line))
    return docs


def _make_handler(server: RestServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        _handshake_failed = False

        def setup(self):
            import ssl as _ssl
            if isinstance(self.request, _ssl.SSLSocket):
                # deferred TLS handshake, bounded so a silent client ties
                # up only this handler thread, never the accept loop
                try:
                    self.request.settimeout(10.0)
                    self.request.do_handshake()
                    self.request.settimeout(None)
                except (OSError, _ssl.SSLError) as exc:
                    # garbage/plain-HTTP/silent clients: drop quietly
                    logger.debug("tls handshake failed from %s: %s",
                                 self.client_address, exc)
                    self._handshake_failed = True
            super().setup()

        def handle(self):
            if not self._handshake_failed:
                super().handle()

        def log_message(self, fmt, *args):  # quiet
            logger.debug("http: " + fmt, *args)

        def _handle(self, method: str) -> None:
            t0 = time.monotonic()
            parsed = urlparse(self.path)
            params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            extra_headers: dict[str, str] = {}
            try:
                if body and "gzip" in (self.headers.get("Content-Encoding")
                                       or ""):
                    # OTel collectors' otlphttp exporter gzips by default;
                    # ES bulk clients too. Bounded against decompression
                    # bombs.
                    import zlib
                    try:
                        inflater = zlib.decompressobj(
                            wbits=zlib.MAX_WBITS | 16)
                        body = inflater.decompress(body, _MAX_INFLATED_BYTES)
                        if inflater.unconsumed_tail:
                            raise ApiError(413, "decompressed body too large")
                    except zlib.error as exc:
                        raise ApiError(400, f"bad gzip body: {exc}")
                status, payload = server.route(
                    method, parsed.path, params, body,
                    client_host=self.client_address[0],
                    content_type=self.headers.get("Content-Type", ""),
                    traceparent=self.headers.get("traceparent", ""),
                    tenant_id=(self.headers.get(TENANT_HEADER)
                               or self.headers.get(ES_FALLBACK_HEADER)
                               or ""))
            except Exception as exc:  # noqa: BLE001
                code = classify_exception(exc)
                if code is None:
                    logger.exception("internal error on %s %s", method,
                                     parsed.path)
                    status = 500
                    payload = {"message": f"internal error: {exc}"}
                else:
                    status = code
                    if isinstance(exc, ApiError) and exc.payload is not None:
                        payload = exc.payload
                    else:
                        payload = {"message": str(exc)}
                    if isinstance(exc, ApiError):
                        extra_headers = exc.headers
            if (isinstance(payload, tuple) and len(payload) == 3
                    and payload[0] == "__raw__"):
                data = payload[1]
                content_type = payload[2]
            elif (isinstance(payload, tuple) and len(payload) == 2
                    and payload[0] == "__html__"):
                data = payload[1].encode()
                content_type = "text/html; charset=utf-8"
            elif isinstance(payload, str):
                data = payload.encode()
                content_type = "text/plain; version=0.0.4"
            else:
                data = json.dumps(payload).encode()
                content_type = "application/json"
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            for name, value in extra_headers.items():
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(data)
            _REQUEST_COUNTER.inc(method=method, status=str(status))
            _REQUEST_LATENCY.observe(time.monotonic() - t0)

        def do_GET(self):
            self._handle("GET")

        def do_POST(self):
            self._handle("POST")

        def do_DELETE(self):
            self._handle("DELETE")

        def do_PUT(self):
            self._handle("PUT")

    return Handler
