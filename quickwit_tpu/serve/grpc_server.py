"""gRPC services over the stdlib HTTP/2 transport (`http2.py`).

Roles of the reference's tonic surfaces:
- OTLP gRPC ingest (`quickwit-opentelemetry/src/otlp/{traces,logs}.rs`):
  TraceService/LogsService Export with binary protobuf request decoding
  (the schema-driven decoder in `otlp_proto.py`).
- Jaeger gRPC SpanReaderPlugin (`quickwit-jaeger/src/lib.rs:78`):
  GetServices / GetOperations / FindTraceIDs / FindTraces / GetTrace
  translating to searches on the otel indexes, spans re-encoded as
  jaeger.api_v2 protobuf messages.

gRPC wire mechanics implemented here: the 5-byte message frame
(compressed flag + u32 length), `application/grpc` content type,
`grpc-status`/`grpc-message` trailers, unary and server-streaming
responses. `GrpcChannel` is the matching minimal client (tests, tools).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Any, Callable, Iterable, Optional

from ..common.deadline import DeadlineExceeded
from ..tenancy.overload import OverloadShed
from ..tenancy.registry import TenantRateLimited
from .http2 import (
    FLAG_ACK, FLAG_END_HEADERS, FLAG_END_STREAM, FRAME_DATA, FRAME_HEADERS,
    FRAME_PING, FRAME_SETTINGS, FRAME_WINDOW_UPDATE, Http2Server, HpackDecoder,
    PREFACE, frame, hpack_encode_raw, read_exact_from, read_frame,
)

GRPC_OK = 0
GRPC_UNKNOWN = 2
GRPC_DEADLINE_EXCEEDED = 4
GRPC_RESOURCE_EXHAUSTED = 8
GRPC_UNIMPLEMENTED = 12


class GrpcError(RuntimeError):
    def __init__(self, message: str, status: int = GRPC_UNKNOWN):
        super().__init__(message)
        self.status = status


# --- protobuf encoding helpers ----------------------------------------------


def pb_varint_raw(value: int) -> bytes:
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def pb_varint(field: int, value: int) -> bytes:
    if not value:
        return b""
    return pb_varint_raw(field << 3) + pb_varint_raw(value)


def pb_bytes(field: int, data: bytes) -> bytes:
    if not data:
        return b""
    return pb_varint_raw(field << 3 | 2) + pb_varint_raw(len(data)) + data


def pb_str(field: int, text: str) -> bytes:
    return pb_bytes(field, text.encode())


def pb_msg(field: int, encoded: bytes) -> bytes:
    # messages keep explicit presence even when empty
    return pb_varint_raw(field << 3 | 2) + pb_varint_raw(len(encoded)) + encoded


def _pb_timestamp(micros: int) -> bytes:
    return (pb_varint(1, micros // 1_000_000)
            + pb_varint(2, (micros % 1_000_000) * 1000))


def _pb_duration(micros: int) -> bytes:
    return _pb_timestamp(micros)  # same seconds/nanos shape


def _pb_keyvalue(key: str, value: Any) -> bytes:
    # jaeger.api_v2 KeyValue: key=1, v_type=2, v_str=3, v_bool=4
    # ValueType: STRING=0, BOOL=1, INT64=2
    if isinstance(value, bool):
        return pb_str(1, key) + pb_varint(2, 1) + pb_varint(4, 1 if value else 0)
    return pb_str(1, key) + pb_str(3, str(value))


def _hex_bytes(hex_id: str) -> bytes:
    text = hex_id or ""
    if len(text) % 2:
        text = "0" + text
    try:
        return bytes.fromhex(text)
    except ValueError:
        return text.encode()


def encode_jaeger_span(doc: dict[str, Any]) -> bytes:
    """One span doc → jaeger.api_v2.Span protobuf bytes."""
    start_micros = int(float(doc.get("span_start_timestamp", 0)) * 1_000_000)
    out = bytearray()
    out += pb_bytes(1, _hex_bytes(doc.get("trace_id", "")))
    out += pb_bytes(2, _hex_bytes(doc.get("span_id", "")))
    out += pb_str(3, doc.get("span_name", ""))
    parent = doc.get("parent_span_id")
    if parent:
        ref = (pb_bytes(1, _hex_bytes(doc.get("trace_id", "")))
               + pb_bytes(2, _hex_bytes(parent)))  # ref_type CHILD_OF = 0
        out += pb_msg(4, ref)
    out += pb_msg(6, _pb_timestamp(start_micros))
    out += pb_msg(7, _pb_duration(int(doc.get("span_duration_micros", 0))))
    for key, value in (doc.get("attributes") or {}).items():
        out += pb_msg(8, _pb_keyvalue(key, value))
    if doc.get("span_status") == "error":
        out += pb_msg(8, _pb_keyvalue("error", True))
    process = pb_str(1, doc.get("service_name", "unknown_service"))
    out += pb_msg(10, process)
    return bytes(out)


# --- request decoding (shares otlp_proto's field iterator) ------------------


def _fields(payload: bytes):
    from .otlp_proto import iter_fields
    return iter_fields(memoryview(payload))


def _decode_trace_query(payload: bytes) -> dict[str, Any]:
    """FindTracesRequest/FindTraceIDsRequest → query dict. The
    TraceQueryParameters message rides at field 1."""
    query: dict[str, Any] = {}
    for field, wire, value in _fields(payload):
        if field == 1 and wire == 2:
            for f2, w2, v2 in _fields(bytes(value)):
                if f2 == 1 and w2 == 2:
                    query["service"] = bytes(v2).decode("utf-8", "replace")
                elif f2 == 2 and w2 == 2:
                    query["operation"] = bytes(v2).decode("utf-8", "replace")
                elif f2 == 3 and w2 == 2:
                    # map<string,string> tags: repeated entries {key=1, value=2}
                    key = text = ""
                    for f3, w3, v3 in _fields(bytes(v2)):
                        if f3 == 1 and w3 == 2:
                            key = bytes(v3).decode("utf-8", "replace")
                        elif f3 == 2 and w3 == 2:
                            text = bytes(v3).decode("utf-8", "replace")
                    if key:
                        query.setdefault("tags", {})[key] = text
                elif f2 == 4 and w2 == 2:
                    query["start_min"] = _decode_timestamp_s(bytes(v2))
                elif f2 == 5 and w2 == 2:
                    query["start_max"] = _decode_timestamp_s(bytes(v2))
                elif f2 == 6 and w2 == 2:
                    query["duration_min_micros"] = \
                        _decode_duration_micros(bytes(v2))
                elif f2 == 7 and w2 == 2:
                    query["duration_max_micros"] = \
                        _decode_duration_micros(bytes(v2))
                elif f2 == 8 and w2 == 0:
                    query["num_traces"] = int(v2)
    return query


def _decode_timestamp_s(payload: bytes) -> int:
    seconds = 0
    for field, wire, value in _fields(payload):
        if field == 1 and wire == 0:
            seconds = int(value)
    return seconds


def _decode_duration_micros(payload: bytes) -> int:
    seconds = nanos = 0
    for field, wire, value in _fields(payload):
        if field == 1 and wire == 0:
            seconds = int(value)
        elif field == 2 and wire == 0:
            nanos = int(value)
    return seconds * 1_000_000 + nanos // 1000


# --- the server --------------------------------------------------------------


class GrpcServer:
    """gRPC endpoint for one node: OTLP collector services + the Jaeger
    span reader, mounted on the stdlib HTTP/2 server."""

    def __init__(self, node, host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None):
        self.node = node
        self._handlers: dict[str, Callable[[bytes], Iterable[bytes]]] = {
            "/opentelemetry.proto.collector.trace.v1.TraceService/Export":
                self._export_traces,
            "/opentelemetry.proto.collector.logs.v1.LogsService/Export":
                self._export_logs,
            "/jaeger.storage.v1.SpanReaderPlugin/GetServices":
                self._get_services,
            "/jaeger.storage.v1.SpanReaderPlugin/GetOperations":
                self._get_operations,
            "/jaeger.storage.v1.SpanReaderPlugin/FindTraceIDs":
                self._find_trace_ids,
            "/jaeger.storage.v1.SpanReaderPlugin/FindTraces":
                self._find_traces,
            "/jaeger.storage.v1.SpanReaderPlugin/GetTrace":
                self._get_trace,
            # internal search fan-out (reference search.proto:19
            # SearchService; payloads ride binwire instead of protobuf —
            # the numpy agg states go over as dtype+shape+raw bytes, the
            # role of the reference's postcard intermediate-agg bytes)
            "/quickwit.search.SearchService/LeafSearch":
                self._leaf_search,
            "/quickwit.search.SearchService/FetchDocs":
                self._fetch_docs,
            "/quickwit.search.SearchService/Replicate":
                self._replicate,
        }
        self._http2 = Http2Server(self._handle, host=host, port=port,
                                  ssl_context=ssl_context)
        self.host, self.port = self._http2.host, self._http2.port

    def stop(self) -> None:
        self._http2.stop()

    # -- transport glue
    def _handle(self, headers, body):
        header_map = dict(headers)
        path = header_map.get(":path", "")
        handler = self._handlers.get(path)
        response_headers = [(":status", "200"),
                            ("content-type", "application/grpc")]
        if handler is None:
            return (response_headers, [],
                    [("grpc-status", str(GRPC_UNIMPLEMENTED)),
                     ("grpc-message", f"unknown method {path}")])
        from ..observability.tracing import TRACER
        try:
            # every RPC is a server span joined to the caller's W3C trace
            # (the role of tonic's tracing interceptor)
            with TRACER.span("grpc.request", {"rpc.method": path},
                             remote_parent=header_map.get("traceparent", ""),
                             scope=self.node.config.node_id):
                messages = list(handler(_grpc_unframe(body)))
        except GrpcError as exc:
            return (response_headers, [],
                    [("grpc-status", str(exc.status)),
                     ("grpc-message", str(exc))])
        except DeadlineExceeded as exc:
            # str(exc) embeds the deadline mark, so the remote root's
            # is_deadline_error() classifier still recognizes the failure
            return (response_headers, [],
                    [("grpc-status", str(GRPC_DEADLINE_EXCEEDED)),
                     ("grpc-message", str(exc))])
        except (OverloadShed, TenantRateLimited) as exc:
            return (response_headers, [],
                    [("grpc-status", str(GRPC_RESOURCE_EXHAUSTED)),
                     ("grpc-message", f"{type(exc).__name__}: {exc}")])
        except Exception as exc:  # noqa: BLE001 - status trailer, not a 500
            return (response_headers, [],
                    [("grpc-status", str(GRPC_UNKNOWN)),
                     ("grpc-message", f"{type(exc).__name__}: {exc}")])
        chunks = [_grpc_frame(m) for m in messages]
        return response_headers, chunks, [("grpc-status", "0")]

    # -- internal SearchService (binwire payloads)
    def _leaf_search(self, payload: bytes):
        from ..search.models import LeafSearchRequest
        from .binwire import decode, encode
        from .serializers import leaf_response_to_wire
        request = LeafSearchRequest.from_dict(decode(payload))
        response = self.node.search_service.leaf_search(request)
        yield encode(leaf_response_to_wire(response))

    def _fetch_docs(self, payload: bytes):
        from ..search.models import FetchDocsRequest
        from .binwire import decode, encode
        request = FetchDocsRequest.from_dict(decode(payload))
        yield encode(self.node.search_service.fetch_docs(request))

    def _replicate(self, payload: bytes):
        from ..ingest.ingester import ReplicationGap
        from .binwire import decode, encode
        request = decode(payload)
        if request.get("reset"):
            self.node.ingester.replica_reset(
                request["index_uid"], request["source_id"],
                request["shard_id"], int(request["first_position"]))
        try:
            last = self.node.ingester.replica_persist(
                request["index_uid"], request["source_id"],
                request["shard_id"], int(request["first_position"]),
                list(request["payloads"]))
        except ReplicationGap as gap:
            yield encode({"gap": True, "replica_position": gap.have})
            return
        yield encode({"replica_position": last})

    # -- OTLP collector services
    def _export_traces(self, payload: bytes):
        from .otlp_proto import decode_traces_request
        self.node.otel.ingest_traces(decode_traces_request(payload))
        yield b""  # ExportTraceServiceResponse{}

    def _export_logs(self, payload: bytes):
        from .otlp_proto import decode_logs_request
        self.node.otel.ingest_logs(decode_logs_request(payload))
        yield b""  # ExportLogsServiceResponse{}

    # -- Jaeger SpanReaderPlugin
    def _get_services(self, payload: bytes):
        out = bytearray()
        for service in self.node.otel.services():
            out += pb_str(1, service)
        yield bytes(out)

    def _get_operations(self, payload: bytes):
        service = ""
        for field, wire, value in _fields(payload):
            if field == 1 and wire == 2:
                service = bytes(value).decode("utf-8", "replace")
        out = bytearray()
        for name in self.node.otel.operations(service):
            out += pb_str(1, name)                      # operationNames
            out += pb_msg(2, pb_str(1, name))           # Operation{name}
        yield bytes(out)

    @staticmethod
    def _trace_query_kwargs(query: dict[str, Any]) -> dict[str, Any]:
        return dict(
            service=query.get("service"), operation=query.get("operation"),
            min_duration_micros=query.get("duration_min_micros"),
            max_duration_micros=query.get("duration_max_micros"),
            tags=query.get("tags"),
            start_timestamp=query.get("start_min"),
            end_timestamp=query.get("start_max"),
            limit=query.get("num_traces", 20))

    def _find_trace_ids(self, payload: bytes):
        query = _decode_trace_query(payload)
        trace_ids = self.node.otel.find_traces(
            **self._trace_query_kwargs(query))
        out = bytearray()
        for trace_id in trace_ids:
            out += pb_bytes(1, _hex_bytes(trace_id))
        yield bytes(out)

    def _find_traces(self, payload: bytes):
        query = _decode_trace_query(payload)
        traces = self.node.otel.find_traces_with_spans(
            **self._trace_query_kwargs(query))
        # server-streaming: one SpansResponseChunk per trace
        for _trace_id, docs in traces:
            chunk = bytearray()
            for doc in docs:
                chunk += pb_msg(1, encode_jaeger_span(doc))
            yield bytes(chunk)

    def _get_trace(self, payload: bytes):
        trace_id = ""
        for field, wire, value in _fields(payload):
            if field == 1 and wire == 2:
                trace_id = bytes(value).hex()
        docs = self.node.otel.get_trace(trace_id)
        if not docs:
            raise GrpcError(f"trace {trace_id!r} not found", status=5)
        chunk = bytearray()
        for doc in docs:
            chunk += pb_msg(1, encode_jaeger_span(doc))
        yield bytes(chunk)


def _grpc_frame(message: bytes) -> bytes:
    return b"\x00" + struct.pack(">I", len(message)) + message


def _grpc_unframe(body: bytes) -> bytes:
    """First (unary) request message of a gRPC body."""
    if not body:
        return b""
    if body[0] != 0:
        raise GrpcError("compressed gRPC messages are not supported")
    length = struct.unpack(">I", body[1:5])[0]
    return body[5: 5 + length]


# --- minimal client (tests / tooling) ----------------------------------------


class GrpcChannel:
    """Blocking h2c gRPC client: one request per call over a persistent
    connection (raw-literal HPACK — no Huffman, by design)."""

    def __init__(self, host: str, port: int, timeout: float = 15.0,
                 ssl_context=None):
        self._timeout = timeout
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._scheme = "http"
        if ssl_context is not None:
            # gRPC-over-TLS — the secure cluster's binary plane; ALPN is
            # configured by whoever built the context (GrpcSearchClient);
            # server identity checked per the context's settings
            self._sock = ssl_context.wrap_socket(
                self._sock,
                server_hostname=host if ssl_context.check_hostname else None)
            self._scheme = "https"
        self._sock.sendall(
            PREFACE + frame(FRAME_SETTINGS, 0, 0, b""))
        self._decoder = HpackDecoder()
        self._stream_id = 1
        # qwlint: disable-next-line=QW008 - serve-layer transport
        # infrastructure (sockets, real IO) outside the DST-raced path; gating
        # it would block the token on real IO
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _read_exact(self, n: int) -> bytes:
        return read_exact_from(self._sock, n)

    def call(self, path: str, message: bytes,
             extra_headers: "tuple[tuple[str, str], ...]" = (),
             timeout_secs: Optional[float] = None
             ) -> tuple[list[bytes], int, str]:
        """(response messages, grpc-status, grpc-message).

        `timeout_secs` clamps THIS call to the caller's remaining deadline
        budget (never above the channel default): the budget covers the
        whole stream, so the socket timeout is re-armed with the remaining
        time before every frame read — N slow frames cannot each burn a
        full per-frame timeout. The shared socket's default timeout is
        restored afterwards (calls are serialized by the channel lock)."""
        budget = self._timeout if timeout_secs is None \
            else min(self._timeout, max(timeout_secs, 0.001))
        with self._lock:
            deadline = time.monotonic() + budget
            stream_id = self._stream_id
            self._stream_id += 2
            headers = [(":method", "POST"), (":scheme", self._scheme),
                       (":path", path), (":authority", "localhost"),
                       ("content-type", "application/grpc"), ("te", "trailers")]
            headers.extend(extra_headers)
            out = frame(FRAME_HEADERS, FLAG_END_HEADERS, stream_id,
                        hpack_encode_raw(headers))
            out += frame(FRAME_DATA, FLAG_END_STREAM, stream_id,
                         _grpc_frame(message))
            try:
                self._sock.settimeout(min(budget, self._timeout))
                self._sock.sendall(out)
                data, status, status_message = self._read_stream(
                    stream_id, deadline, path)
            finally:
                try:
                    self._sock.settimeout(self._timeout)
                except OSError:
                    pass  # socket already dead; the caller sees the error
            messages = []
            pos = 0
            while pos + 5 <= len(data):
                length = struct.unpack(">I", data[pos + 1: pos + 5])[0]
                messages.append(bytes(data[pos + 5: pos + 5 + length]))
                pos += 5 + length
            return messages, status, status_message

    def _read_stream(self, stream_id: int, deadline: float, path: str
                     ) -> tuple[bytearray, int, str]:
        data = bytearray()
        status, status_message = -1, ""
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout(
                    f"grpc call {path} exceeded its deadline budget")
            self._sock.settimeout(remaining)
            frame_type, flags, fid, payload = read_frame(self._read_exact)
            if frame_type == FRAME_SETTINGS:
                if not flags & FLAG_ACK:
                    self._sock.sendall(
                        frame(FRAME_SETTINGS, FLAG_ACK, 0, b""))
                continue
            if frame_type == FRAME_PING and not flags & FLAG_ACK:
                self._sock.sendall(
                    frame(FRAME_PING, FLAG_ACK, 0, payload))
                continue
            if frame_type == FRAME_WINDOW_UPDATE or fid != stream_id:
                continue
            if frame_type == FRAME_HEADERS:
                for name, value in self._decoder.decode(payload):
                    if name == "grpc-status":
                        status = int(value)
                    elif name == "grpc-message":
                        status_message = value
            elif frame_type == FRAME_DATA:
                data += payload
                if payload:
                    import struct as _struct
                    increment = _struct.pack(">I", len(payload))
                    self._sock.sendall(
                        frame(FRAME_WINDOW_UPDATE, 0, 0, increment)
                        + frame(FRAME_WINDOW_UPDATE, 0, stream_id,
                                increment))
            if flags & FLAG_END_STREAM:
                return data, status, status_message


class GrpcSearchClient:
    """Cross-node search client over the gRPC stack — the role of the
    reference's codegen'd SearchService gRPC client (`search.proto:19`,
    `quickwit-codegen/src/codegen.rs:12-45`). leaf_search / fetch_docs /
    replicate ride gRPC framing with binwire payloads on one persistent
    HTTP/2 connection; everything else (heartbeat, cluster KV `_post`
    surface) delegates to the JSON/HTTP client, which also owns the
    shared circuit breaker."""

    def __init__(self, grpc_endpoint: str, rest_endpoint: str,
                 timeout_secs: float = 30.0, **http_kwargs):
        from .http_client import HttpSearchClient, client_ssl_context
        self.endpoint = rest_endpoint
        self.grpc_endpoint = grpc_endpoint
        host, port = grpc_endpoint.rsplit(":", 1)
        self._grpc_host, self._grpc_port = host, int(port)
        self.timeout_secs = timeout_secs
        self.http = HttpSearchClient(rest_endpoint,
                                     timeout_secs=timeout_secs, **http_kwargs)
        self.circuit = self.http.circuit
        # a TLS cluster runs its gRPC plane over TLS too (same CA / mTLS
        # settings as the REST client), with h2 ALPN baked in at
        # construction — no per-reconnect context mutation
        self._channel_ssl = client_ssl_context(alpn=["h2"], **http_kwargs)
        self._channel: "GrpcChannel | None" = None
        # qwlint: disable-next-line=QW008 - serve-layer transport
        # infrastructure (sockets, real IO) outside the DST-raced path; gating
        # it would block the token on real IO
        self._channel_lock = threading.Lock()

    def close(self) -> None:
        with self._channel_lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = None

    def _call(self, path: str, payload: bytes,
              timeout_secs: Optional[float] = None) -> bytes:
        from .http_client import HttpStatusError, HttpTransportError

        def once() -> bytes:
            with self._channel_lock:
                if self._channel is None:
                    self._channel = GrpcChannel(
                        self._grpc_host, self._grpc_port,
                        timeout=self.timeout_secs,
                        ssl_context=self._channel_ssl)
                channel = self._channel
            from ..observability.tracing import TRACER
            from .http2 import Http2Error
            traceparent = TRACER.current_traceparent()
            extra = (("traceparent", traceparent),) if traceparent else ()
            try:
                messages, status, message = channel.call(
                    path, payload, extra_headers=extra,
                    timeout_secs=timeout_secs)
            except (OSError, Http2Error) as exc:
                # connection-level failure: drop the channel so the next
                # call reconnects; counts toward the breaker
                with self._channel_lock:
                    if self._channel is channel:
                        self._channel = None
                channel.close()
                raise HttpTransportError(
                    f"grpc {self.grpc_endpoint}{path}: {exc}") from exc
            if status != 0:
                # translate gRPC status into the HTTP status the root's
                # failure handling keys on: RESOURCE_EXHAUSTED is remote
                # backpressure (429 -> failed-node retry path, see
                # search/root.py), DEADLINE_EXCEEDED is a timeout (504);
                # the message carries the deadline mark for
                # is_deadline_error(). Anything else stays a generic 500.
                http_status = {GRPC_RESOURCE_EXHAUSTED: 429,
                               GRPC_DEADLINE_EXCEEDED: 504}.get(status, 500)
                raise HttpStatusError(
                    f"grpc {self.grpc_endpoint}{path} -> status {status}: "
                    f"{message}", status=http_status)
            return messages[0] if messages else b""

        return self.circuit.call(once)

    # -- gRPC-backed methods
    def leaf_search(self, request):
        from .binwire import decode, encode
        from .serializers import leaf_response_from_wire
        # clamp the call to the query's remaining deadline budget (plus
        # grace for trailers), mirroring HttpSearchClient.leaf_search —
        # a 2s-deadline query must not hold the shared channel for the
        # full 30s default
        timeout_secs = None
        if getattr(request, "deadline_millis", None) is not None:
            timeout_secs = request.deadline_millis / 1000.0 + 0.5
        raw = self._call("/quickwit.search.SearchService/LeafSearch",
                         encode(request.to_dict()),
                         timeout_secs=timeout_secs)
        return leaf_response_from_wire(decode(raw))

    def fetch_docs(self, request):
        from .binwire import decode, encode
        raw = self._call("/quickwit.search.SearchService/FetchDocs",
                         encode(request.to_dict()))
        return decode(raw)

    def replicate(self, payload):
        """Chained-replication append; WAL records ride as raw bytes (the
        JSON path base64-encodes them)."""
        import base64
        from .binwire import decode, encode
        wire = dict(payload)
        if "payloads" in wire:
            wire["payloads"] = [base64.b64decode(p) if isinstance(p, str)
                                else bytes(p) for p in wire["payloads"]]
        raw = self._call("/quickwit.search.SearchService/Replicate",
                         encode(wire))
        response = decode(raw)
        if response.get("gap"):
            # mirror the HTTP 409 contract the ingester's caller expects
            from .http_client import HttpStatusError
            import json as _json
            raise HttpStatusError(
                f"grpc replicate gap at {response['replica_position']}",
                status=409, body=_json.dumps(response).encode())
        return response

    # -- JSON/HTTP delegation (heartbeat, KV, truncate, ...)
    def heartbeat(self, payload):
        return self.http.heartbeat(payload)

    def _post(self, path: str, payload):
        return self.http._post(path, payload)
