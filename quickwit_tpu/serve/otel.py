"""OTLP ingestion + Jaeger-style trace query API.

Roles of the reference's `quickwit-opentelemetry` (`otlp/logs.rs:202`,
`otlp/traces.rs:653`) and `quickwit-jaeger` (`lib.rs:78`): accept OTLP
JSON payloads for logs and traces into well-known indexes
(`otel-logs-v0`, `otel-traces-v0`, auto-created with the reference's doc
mappings), and answer Jaeger HTTP queries (services, operations, trace
lookup, trace search) by translating them into searches — trace search uses
the trace-id collection pattern of `find_trace_ids_collector.rs` (terms over
trace ids ordered by max span timestamp).
"""

from __future__ import annotations

import time
from typing import Any, Optional

OTEL_LOGS_INDEX = "otel-logs-v0"
OTEL_TRACES_INDEX = "otel-traces-v0"

OTEL_LOGS_CONFIG = {
    "index_id": OTEL_LOGS_INDEX,
    "doc_mapping": {
        "field_mappings": [
            {"name": "timestamp", "type": "datetime", "fast": True,
             "input_formats": ["unix_timestamp", "rfc3339"]},
            {"name": "severity_text", "type": "text", "tokenizer": "raw", "fast": True},
            {"name": "severity_number", "type": "i64", "fast": True},
            {"name": "service_name", "type": "text", "tokenizer": "raw", "fast": True},
            {"name": "body", "type": "text", "record": "position"},
            {"name": "trace_id", "type": "text", "tokenizer": "raw"},
            {"name": "span_id", "type": "text", "tokenizer": "raw"},
        ],
        "timestamp_field": "timestamp",
        "default_search_fields": ["body"],
        "mode": "lenient",
    },
}

OTEL_TRACES_CONFIG = {
    "index_id": OTEL_TRACES_INDEX,
    "doc_mapping": {
        "field_mappings": [
            {"name": "span_start_timestamp", "type": "datetime", "fast": True,
             "input_formats": ["unix_timestamp"]},
            {"name": "trace_id", "type": "text", "tokenizer": "raw", "fast": True},
            {"name": "span_id", "type": "text", "tokenizer": "raw"},
            {"name": "parent_span_id", "type": "text", "tokenizer": "raw"},
            {"name": "service_name", "type": "text", "tokenizer": "raw", "fast": True},
            {"name": "span_name", "type": "text", "tokenizer": "raw", "fast": True},
            {"name": "span_duration_micros", "type": "i64", "fast": True},
            {"name": "span_status", "type": "text", "tokenizer": "raw", "fast": True},
        ],
        "timestamp_field": "span_start_timestamp",
        "default_search_fields": ["span_name"],
        "mode": "lenient",
    },
}


def _nanos_to_seconds(value) -> float:
    return int(value) / 1e9


def _attr_map(attributes: list[dict[str, Any]]) -> dict[str, Any]:
    out = {}
    for attr in attributes or []:
        value = attr.get("value", {})
        for key in ("stringValue", "intValue", "doubleValue", "boolValue"):
            if key in value:  # falsy values (false, 0, "") must survive
                out[attr.get("key", "")] = value[key]
                break
        else:
            out[attr.get("key", "")] = None
    return out


def otlp_logs_to_docs(payload: dict[str, Any]) -> list[dict[str, Any]]:
    """OTLP JSON `resourceLogs` → log docs (reference `otlp/logs.rs`)."""
    docs = []
    for resource_logs in payload.get("resourceLogs", []):
        resource_attrs = _attr_map(
            resource_logs.get("resource", {}).get("attributes", []))
        service = resource_attrs.get("service.name", "unknown_service")
        for scope_logs in resource_logs.get("scopeLogs", []):
            for record in scope_logs.get("logRecords", []):
                body = record.get("body", {})
                docs.append({
                    "timestamp": _nanos_to_seconds(
                        record.get("timeUnixNano")
                        or record.get("observedTimeUnixNano") or 0),
                    "severity_text": record.get("severityText", ""),
                    "severity_number": record.get("severityNumber", 0),
                    "service_name": service,
                    "body": body.get("stringValue", "") if isinstance(body, dict)
                    else str(body),
                    "trace_id": record.get("traceId", ""),
                    "span_id": record.get("spanId", ""),
                    "attributes": _attr_map(record.get("attributes", [])),
                })
    return docs


def _status_str(code: Any) -> str:
    """OTLP Status.code arrives as a proto3 JSON int (0/1/2), the enum
    name (STATUS_CODE_OK), or a bare string from lenient producers."""
    mapping = {0: "unset", 1: "ok", 2: "error",
               "STATUS_CODE_UNSET": "unset", "STATUS_CODE_OK": "ok",
               "STATUS_CODE_ERROR": "error"}
    return mapping.get(code, str(code).lower())


def otlp_traces_to_docs(payload: dict[str, Any]) -> list[dict[str, Any]]:
    """OTLP JSON `resourceSpans` → span docs (reference `otlp/traces.rs`)."""
    docs = []
    for resource_spans in payload.get("resourceSpans", []):
        resource_attrs = _attr_map(
            resource_spans.get("resource", {}).get("attributes", []))
        service = resource_attrs.get("service.name", "unknown_service")
        for scope_spans in resource_spans.get("scopeSpans", []):
            for span in scope_spans.get("spans", []):
                start_nanos = int(span.get("startTimeUnixNano", 0))
                end_nanos = int(span.get("endTimeUnixNano", start_nanos))
                docs.append({
                    "span_start_timestamp": start_nanos / 1e9,
                    "trace_id": span.get("traceId", ""),
                    "span_id": span.get("spanId", ""),
                    "parent_span_id": span.get("parentSpanId", ""),
                    "service_name": service,
                    "span_name": span.get("name", ""),
                    "span_duration_micros": max((end_nanos - start_nanos) // 1000, 0),
                    "span_status": _status_str(
                        (span.get("status", {}) or {}).get("code", "unset")),
                    "attributes": _attr_map(span.get("attributes", [])),
                })
    return docs


class OtelService:
    """Glue: auto-create otel indexes, ingest OTLP payloads, answer
    Jaeger-style queries via the root searcher."""

    def __init__(self, node):
        self.node = node

    def _ensure_index(self, config: dict[str, Any]) -> None:
        from ..metastore.base import MetastoreError
        try:
            self.node.metastore.index_metadata(config["index_id"])
        except MetastoreError:
            self.node.index_service.create_index(config)

    def ingest_logs(self, payload: dict[str, Any]) -> dict[str, Any]:
        self._ensure_index(OTEL_LOGS_CONFIG)
        docs = otlp_logs_to_docs(payload)
        return self.node.ingest(OTEL_LOGS_INDEX, docs)

    def ingest_traces(self, payload: dict[str, Any]) -> dict[str, Any]:
        self._ensure_index(OTEL_TRACES_CONFIG)
        docs = otlp_traces_to_docs(payload)
        return self.node.ingest(OTEL_TRACES_INDEX, docs)

    # --- Jaeger-style reads ------------------------------------------------
    def _traces_index_exists(self) -> bool:
        """Jaeger reads on a node that never ingested a span answer
        empty, not error (the index appears on first OTLP ingest)."""
        from ..metastore.base import MetastoreError
        try:
            self.node.metastore.index_metadata(OTEL_TRACES_INDEX)
            return True
        except MetastoreError:
            return False

    def services(self) -> list[str]:
        from ..query.ast import MatchAll
        from ..search.models import SearchRequest
        if not self._traces_index_exists():
            return []
        response = self.node.root_searcher.search(SearchRequest(
            index_ids=[OTEL_TRACES_INDEX], query_ast=MatchAll(), max_hits=0,
            aggs={"services": {"terms": {"field": "service_name", "size": 1000}}}))
        return sorted(b["key"] for b in
                      response.aggregations["services"]["buckets"])

    def operations(self, service: str) -> list[str]:
        from ..query.ast import Term
        from ..search.models import SearchRequest
        if not self._traces_index_exists():
            return []
        response = self.node.root_searcher.search(SearchRequest(
            index_ids=[OTEL_TRACES_INDEX],
            query_ast=Term("service_name", service), max_hits=0,
            aggs={"ops": {"terms": {"field": "span_name", "size": 1000}}}))
        return sorted(b["key"] for b in response.aggregations["ops"]["buckets"])

    def get_trace(self, trace_id: str) -> list[dict[str, Any]]:
        from ..query.ast import Term
        from ..search.models import SearchRequest, SortField
        response = self.node.root_searcher.search(SearchRequest(
            index_ids=[OTEL_TRACES_INDEX],
            query_ast=Term("trace_id", trace_id), max_hits=1000,
            sort_fields=(SortField("span_start_timestamp", "asc"),)))
        return [h.doc for h in response.hits]

    def jaeger_trace(self, trace_id: str,
                     spans: list[dict[str, Any]]) -> dict[str, Any]:
        """Span docs → the Jaeger UI's trace JSON (jaeger-ui expects
        operationName/startTime-micros/duration/processes — the reference's
        jaeger service emits the same projection, jaeger_api/mod.rs)."""
        processes: dict[str, dict[str, Any]] = {}
        process_of: dict[str, str] = {}
        out_spans = []
        for doc in spans:
            service = doc.get("service_name", "unknown_service")
            pid = process_of.get(service)
            if pid is None:
                pid = process_of[service] = f"p{len(process_of) + 1}"
                processes[pid] = {"serviceName": service, "tags": []}
            tags = [{"key": k, "type": "string", "value": str(v)}
                    for k, v in (doc.get("attributes") or {}).items()]
            status = doc.get("span_status", "unset")
            if status == "error":
                tags.append({"key": "error", "type": "bool", "value": "true"})
            span = {
                "traceID": doc.get("trace_id", trace_id),
                "spanID": doc.get("span_id", ""),
                "operationName": doc.get("span_name", ""),
                "startTime": int(float(doc.get("span_start_timestamp", 0))
                                 * 1_000_000),
                "duration": int(doc.get("span_duration_micros", 0)),
                "processID": pid,
                "tags": tags,
                "references": [],
                "logs": [],
            }
            parent = doc.get("parent_span_id")
            if parent:
                span["references"] = [{"refType": "CHILD_OF",
                                       "traceID": span["traceID"],
                                       "spanID": parent}]
            out_spans.append(span)
        return {"traceID": trace_id, "spans": out_spans,
                "processes": processes, "warnings": None}

    def find_traces(self, service: Optional[str] = None,
                    operation: Optional[str] = None,
                    min_duration_micros: Optional[int] = None,
                    max_duration_micros: Optional[int] = None,
                    tags: "Optional[dict[str, str]]" = None,
                    start_timestamp: Optional[int] = None,
                    end_timestamp: Optional[int] = None,
                    limit: int = 20,
                    span_cache: "Optional[dict]" = None) -> list[str]:
        """Trace ids of matching spans, most-recent first (the
        FindTraceIdsAggregation role: newest max-span-timestamp per trace).

        Tag filters post-filter fetched spans: span attributes live in the
        lenient-mode RAW doc, not in indexed columns, so a trace qualifies
        when at least one of its spans carries ALL requested tags (Jaeger
        semantics; `error=true` matches span_status == "error")."""
        from ..query.ast import Bool, MatchAll, Range, RangeBound, Term
        from ..search.models import SearchRequest, SortField
        must = []
        if service:
            must.append(Term("service_name", service))
        if operation:
            must.append(Term("span_name", operation))
        filters = []
        if min_duration_micros is not None:
            filters.append(Range("span_duration_micros",
                                 lower=RangeBound(min_duration_micros, True)))
        if max_duration_micros is not None:
            filters.append(Range("span_duration_micros",
                                 upper=RangeBound(max_duration_micros, True)))
        ast = Bool(must=tuple(must), filter=tuple(filters)) \
            if (must or filters) else MatchAll()

        def top_trace_ids(size: int) -> "tuple[list[str], bool]":
            # device-side FindTraceIdsAggregation (reference
            # find_trace_ids_collector.rs): a terms aggregation over the
            # trace_id fast column ordered by max span timestamp — the
            # dedup/top-N runs in the bucket kernels, not over fetched docs
            response = self.node.root_searcher.search(SearchRequest(
                index_ids=[OTEL_TRACES_INDEX], query_ast=ast, max_hits=0,
                aggs={"trace_ids": {
                    "terms": {"field": "trace_id", "size": size,
                              "order": {"max_ts": "desc"}},
                    "aggs": {"max_ts": {
                        "max": {"field": "span_start_timestamp"}}}}},
                start_timestamp=start_timestamp, end_timestamp=end_timestamp))
            buckets = (response.aggregations or {}).get(
                "trace_ids", {}).get("buckets", [])
            exhausted = len(buckets) < size
            return [b["key"] for b in buckets if b["key"]], exhausted

        # hard cap: a huge client `limit` (or, below, a never-matching
        # tag) must not widen the terms agg without bound (device
        # allocation) — BOTH the tagged and untagged paths clamp to it
        max_size = 10_000
        # size+1: spans ingested without a traceId bucket under "" and
        # are dropped above — the extra slot keeps `limit` real traces
        # even when the empty bucket ranks in the top N
        if not tags:
            trace_ids, _ = top_trace_ids(min(limit + 1, max_size))
            return trace_ids[:limit]
        # tag post-filtering prunes AFTER the agg, so widen the candidate
        # pool geometrically until `limit` matches or the index runs dry
        # (the cache is request-scoped — passed down, never instance state)
        cache = {} if span_cache is None else span_cache
        size = min(limit * 5 + 1, max_size)
        while True:
            trace_ids, exhausted = top_trace_ids(size)
            matches = [t for t in trace_ids
                       if self._trace_matches_tags(t, tags, cache)]
            if len(matches) >= limit or exhausted or size >= max_size:
                return matches[:limit]
            size = min(size * 4, max_size)

    def find_traces_with_spans(self, **kwargs) -> "list[tuple[str, list]]":
        """find_traces + the span docs of each match, fetching each trace's
        spans at most once across filter + response encoding (the gRPC
        FindTraces streaming path)."""
        cache: dict = {}
        trace_ids = self.find_traces(span_cache=cache, **kwargs)
        return [(t, cache[t] if t in cache else self.get_trace(t))
                for t in trace_ids]

    @staticmethod
    def _tag_value(value: Any) -> str:
        # jaeger clients send "true"/"false" for bool tags; OTLP decoding
        # stores Python bools — normalize both to the wire spelling
        if isinstance(value, bool):
            return "true" if value else "false"
        return str(value)

    def _trace_matches_tags(self, trace_id: str, tags: "dict[str, str]",
                            cache: dict) -> bool:
        if trace_id not in cache:
            cache[trace_id] = self.get_trace(trace_id)
        for doc in cache[trace_id]:
            attrs = dict(doc.get("attributes") or {})
            if doc.get("span_status") == "error":
                attrs.setdefault("error", "true")
            # exact string match (Jaeger tag semantics), with bools
            # normalized to their lowercase wire spelling
            if all(k in attrs
                   and self._tag_value(attrs[k]) == self._tag_value(v)
                   for k, v in tags.items()):
                return True
        return False
