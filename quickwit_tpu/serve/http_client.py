"""HTTP search client — the cross-node transport.

Role of the reference's codegen'd gRPC SearchService client with tower
retry/timeout layers: same `SearchClient` surface as LocalSearchClient, over
the peer's `/internal/*` endpoints using stdlib http.client (zero-dep).
"""

from __future__ import annotations

import http.client
import json
import ssl
from typing import Any, Optional

from ..common.tower import CircuitBreaker, CircuitOpen
from ..search.models import FetchDocsRequest, LeafSearchRequest, LeafSearchResponse
from .serializers import leaf_response_from_dict


class HttpTransportError(ConnectionError):
    """Connection-level failure (peer unreachable/timeout) — counts toward
    the circuit breaker."""


class HttpStatusError(HttpTransportError):
    """Peer answered with a non-200 — an application error, NOT evidence the
    peer is dead; does not open the circuit."""

    def __init__(self, message: str, status: int = 0, body: bytes = b""):
        super().__init__(message)
        self.status = status
        self.body = body


def client_ssl_context(tls: bool = False, ca_path: Optional[str] = None,
                       skip_verify: bool = False,
                       client_cert_path: Optional[str] = None,
                       client_key_path: Optional[str] = None,
                       alpn: Optional[list[str]] = None
                       ) -> Optional[ssl.SSLContext]:
    """Peer-facing TLS context (role of quickwit-transport's rustls client
    side), shared by the JSON/HTTP client and the gRPC channel: `ca_path`
    pins the cluster CA for self-signed deployments; `skip_verify` is for
    tests only; a client cert is the mTLS identity toward verify-client
    peers; `alpn` is set here (ONE construction path) so callers never
    mutate a context they share."""
    if not tls:
        return None
    if skip_verify:
        context = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        context.check_hostname = False
        context.verify_mode = ssl.CERT_NONE
    else:
        context = ssl.create_default_context(cafile=ca_path)
    if client_cert_path:
        context.load_cert_chain(client_cert_path, client_key_path)
    if alpn:
        try:
            context.set_alpn_protocols(alpn)
        except NotImplementedError:
            pass
    return context


class HttpSearchClient:
    def __init__(self, endpoint: str, timeout_secs: float = 30.0,
                 tls: bool = False, ca_path: Optional[str] = None,
                 skip_verify: bool = False,
                 client_cert_path: Optional[str] = None,
                 client_key_path: Optional[str] = None):
        self.endpoint = endpoint  # "host:port"
        host, port = endpoint.rsplit(":", 1)
        self.host = host
        self.port = int(port)
        self.timeout_secs = timeout_secs
        self._ssl_context = client_ssl_context(
            tls, ca_path, skip_verify, client_cert_path, client_key_path)
        # stop hammering a dead peer; root search fails fast to its retry
        # path instead of stacking timeouts (reference tower circuit breaker)
        self.circuit = CircuitBreaker(
            failure_threshold=3, cooldown_secs=5.0,
            counts_as_failure=lambda exc: not isinstance(exc, HttpStatusError))

    def _post(self, path: str, payload: Any,
              timeout_secs: Optional[float] = None) -> Any:
        return self.circuit.call(
            lambda: self._post_once(path, payload, timeout_secs))

    def _post_once(self, path: str, payload: Any,
                   timeout_secs: Optional[float] = None) -> Any:
        timeout = (self.timeout_secs if timeout_secs is None
                   else min(self.timeout_secs, timeout_secs))
        if self._ssl_context is not None:
            conn: http.client.HTTPConnection = http.client.HTTPSConnection(
                self.host, self.port, timeout=timeout,
                context=self._ssl_context)
        else:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=timeout)
        try:
            data = json.dumps(payload).encode()
            headers = {"Content-Type": "application/json"}
            # propagate the active trace across the root->leaf hop
            # (reference: tracing_utils.rs inject_current_context)
            from ..observability.tracing import TRACER
            traceparent = TRACER.current_traceparent()
            if traceparent:
                headers["traceparent"] = traceparent
            conn.request("POST", path, body=data, headers=headers)
            response = conn.getresponse()
            body = response.read()
            if response.status != 200:
                raise HttpStatusError(
                    f"{self.endpoint}{path} -> {response.status}: {body[:200]!r}",
                    status=response.status, body=body)
            return json.loads(body)
        except HttpStatusError:
            raise  # ConnectionError subclass: must not be re-wrapped below
        except (OSError, http.client.HTTPException) as exc:
            raise HttpTransportError(f"{self.endpoint}{path}: {exc}") from exc
        finally:
            conn.close()

    def leaf_search(self, request: LeafSearchRequest) -> LeafSearchResponse:
        # socket timeout tracks the request's remaining budget (plus slack
        # for the leaf to serialize its partial response) instead of the
        # fixed per-connection default — a deadline-bound request must not
        # wait out a 30s socket timeout
        timeout_secs = None
        if request.deadline_millis is not None:
            timeout_secs = request.deadline_millis / 1000.0 + 0.5
        return leaf_response_from_dict(
            self._post("/internal/leaf_search", request.to_dict(),
                       timeout_secs=timeout_secs))

    def fetch_docs(self, request: FetchDocsRequest) -> list[dict[str, Any]]:
        return self._post("/internal/fetch_docs", request.to_dict())

    def heartbeat(self, payload: dict[str, Any]) -> dict[str, Any]:
        return self._post("/internal/heartbeat", payload)

    def replicate(self, payload: dict[str, Any]) -> dict[str, Any]:
        """Chained-replication append on the follower (ingest v2)."""
        return self._post("/internal/replicate", payload)
