from .node import Node, NodeConfig
from .rest import RestServer

__all__ = ["Node", "NodeConfig", "RestServer"]
