"""Binary OTLP/HTTP decoding (application/x-protobuf).

Role of the reference's protobuf OTLP services (`quickwit-opentelemetry/
src/otlp/{logs,traces}.rs` — tonic-generated ExportLogsServiceRequest /
ExportTraceServiceRequest handlers). The OTLP .proto files are not in this
image, so this is a minimal, schema-driven protobuf *wire format* decoder
(varint / fixed64 / length-delimited — the whole format) with the OTLP
field numbers inlined from the public opentelemetry-proto schema. It emits
the same camelCase dict shapes as the JSON path, so `otlp_logs_to_docs` /
`otlp_traces_to_docs` serve both encodings unchanged.

Unknown fields are skipped by wire type, exactly like a generated parser —
new OTLP fields degrade gracefully instead of erroring.
"""

from __future__ import annotations

import struct
from typing import Any, Iterator


class ProtoDecodeError(ValueError):
    """Malformed protobuf payload (maps to 400 at the REST layer)."""


# --------------------------------------------------------------------------
# wire format

def _read_varint(buf: memoryview, i: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if i >= len(buf):
            raise ProtoDecodeError("truncated varint")
        byte = buf[i]
        i += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, i
        shift += 7
        if shift > 63:
            raise ProtoDecodeError("varint too long")


def iter_fields(buf: memoryview) -> Iterator[tuple[int, int, Any]]:
    """(field_number, wire_type, value); length-delimited values are
    memoryviews, varints ints, fixed32/64 raw ints."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 0x7
        if wire == 0:  # varint
            value, i = _read_varint(buf, i)
        elif wire == 1:  # fixed64
            if i + 8 > n:
                raise ProtoDecodeError("truncated fixed64")
            value = struct.unpack_from("<Q", buf, i)[0]
            i += 8
        elif wire == 2:  # length-delimited
            length, i = _read_varint(buf, i)
            if i + length > n:
                raise ProtoDecodeError("truncated bytes field")
            value = buf[i: i + length]
            i += length
        elif wire == 5:  # fixed32
            if i + 4 > n:
                raise ProtoDecodeError("truncated fixed32")
            value = struct.unpack_from("<I", buf, i)[0]
            i += 4
        else:
            raise ProtoDecodeError(f"unsupported wire type {wire}")
        yield field, wire, value


def _text(value: memoryview) -> str:
    return bytes(value).decode("utf-8", errors="replace")


def _f64(raw: int) -> float:
    return struct.unpack("<d", struct.pack("<Q", raw))[0]


def _i64(raw: int) -> int:
    """Two's-complement reinterpretation for int64 varints."""
    return raw - (1 << 64) if raw >= (1 << 63) else raw


# --------------------------------------------------------------------------
# OTLP common (opentelemetry/proto/common/v1/common.proto)

def _any_value(buf: memoryview) -> dict[str, Any]:
    for field, _wire, value in iter_fields(buf):
        if field == 1:
            return {"stringValue": _text(value)}
        if field == 2:
            return {"boolValue": bool(value)}
        if field == 3:
            return {"intValue": _i64(value)}
        if field == 4:
            return {"doubleValue": _f64(value)}
        if field == 5:  # ArrayValue{1: repeated AnyValue}
            return {"arrayValue": {"values": [
                _any_value(v) for f, _, v in iter_fields(value) if f == 1]}}
        if field == 6:  # KeyValueList{1: repeated KeyValue}
            return {"kvlistValue": {"values": [
                _key_value(v) for f, _, v in iter_fields(value) if f == 1]}}
        if field == 7:
            return {"bytesValue": bytes(value).hex()}
    return {}


def _key_value(buf: memoryview) -> dict[str, Any]:
    out: dict[str, Any] = {"key": "", "value": {}}
    for field, _wire, value in iter_fields(buf):
        if field == 1:
            out["key"] = _text(value)
        elif field == 2:
            out["value"] = _any_value(value)
    return out


def _attributes(buf: memoryview, collected: list) -> None:
    collected.append(_key_value(buf))


def _resource(buf: memoryview) -> dict[str, Any]:
    attrs: list = []
    for field, _wire, value in iter_fields(buf):
        if field == 1:
            _attributes(value, attrs)
    return {"attributes": attrs}


# --------------------------------------------------------------------------
# logs (opentelemetry/proto/logs/v1/logs.proto)

def _log_record(buf: memoryview) -> dict[str, Any]:
    record: dict[str, Any] = {"attributes": []}
    for field, _wire, value in iter_fields(buf):
        if field == 1:
            record["timeUnixNano"] = value
        elif field == 11:
            record["observedTimeUnixNano"] = value
        elif field == 2:
            record["severityNumber"] = value
        elif field == 3:
            record["severityText"] = _text(value)
        elif field == 5:
            record["body"] = _any_value(value)
        elif field == 6:
            _attributes(value, record["attributes"])
        elif field == 9:
            record["traceId"] = bytes(value).hex()
        elif field == 10:
            record["spanId"] = bytes(value).hex()
    return record


def decode_logs_request(payload: bytes) -> dict[str, Any]:
    """ExportLogsServiceRequest bytes → the JSON-path `resourceLogs` shape."""
    try:
        return _decode_logs(memoryview(payload))
    except (TypeError, struct.error) as exc:
        # wire-type mismatch (e.g. a varint where a message was expected)
        # is client data, not a server fault
        raise ProtoDecodeError(f"wire-type mismatch: {exc}")


def _decode_logs(buf: memoryview) -> dict[str, Any]:
    resource_logs = []
    for field, _wire, value in iter_fields(buf):
        if field != 1:
            continue
        entry: dict[str, Any] = {"scopeLogs": []}
        for f2, _w2, v2 in iter_fields(value):
            if f2 == 1:
                entry["resource"] = _resource(v2)
            elif f2 == 2:
                records = []
                for f3, _w3, v3 in iter_fields(v2):
                    if f3 == 2:
                        records.append(_log_record(v3))
                entry["scopeLogs"].append({"logRecords": records})
        resource_logs.append(entry)
    return {"resourceLogs": resource_logs}


# --------------------------------------------------------------------------
# traces (opentelemetry/proto/trace/v1/trace.proto)

_STATUS_CODES = {0: "unset", 1: "ok", 2: "error"}


def _span(buf: memoryview) -> dict[str, Any]:
    span: dict[str, Any] = {"attributes": []}
    for field, _wire, value in iter_fields(buf):
        if field == 1:
            span["traceId"] = bytes(value).hex()
        elif field == 2:
            span["spanId"] = bytes(value).hex()
        elif field == 4:
            span["parentSpanId"] = bytes(value).hex()
        elif field == 5:
            span["name"] = _text(value)
        elif field == 7:
            span["startTimeUnixNano"] = value
        elif field == 8:
            span["endTimeUnixNano"] = value
        elif field == 9:
            _attributes(value, span["attributes"])
        elif field == 15:  # Status{3: code varint}
            for f2, _w2, v2 in iter_fields(value):
                if f2 == 3:
                    span["status"] = {"code": _STATUS_CODES.get(v2, "unset")}
    return span


def decode_traces_request(payload: bytes) -> dict[str, Any]:
    """ExportTraceServiceRequest bytes → the `resourceSpans` shape."""
    try:
        return _decode_traces(memoryview(payload))
    except (TypeError, struct.error) as exc:
        raise ProtoDecodeError(f"wire-type mismatch: {exc}")


def _decode_traces(buf: memoryview) -> dict[str, Any]:
    resource_spans = []
    for field, _wire, value in iter_fields(buf):
        if field != 1:
            continue
        entry: dict[str, Any] = {"scopeSpans": []}
        for f2, _w2, v2 in iter_fields(value):
            if f2 == 1:
                entry["resource"] = _resource(v2)
            elif f2 == 2:
                spans = []
                for f3, _w3, v3 in iter_fields(v2):
                    if f3 == 2:
                        spans.append(_span(v3))
                entry["scopeSpans"].append({"spans": spans})
        resource_spans.append(entry)
    return {"resourceSpans": resource_spans}
