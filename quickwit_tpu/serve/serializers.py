"""JSON wire encoding for the internal search RPCs.

Role of the reference's protobuf messages on the root↔leaf boundary
(`search.proto` LeafSearchRequest/Response): here JSON over HTTP — numpy
aggregation states encode as typed lists; `PartialHit` as flat tuples.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..search.models import LeafSearchResponse, PartialHit, SplitSearchError


def _encode_value(value: Any) -> Any:
    if isinstance(value, np.ndarray):
        return {"__nd__": value.dtype.str, "data": value.tolist()}
    if isinstance(value, np.generic):
        return value.item()
    if isinstance(value, dict):
        if any(not isinstance(k, str) for k in value):
            # histogram/terms bucket maps key by numbers; JSON would silently
            # stringify them and break cross-node merges
            return {"__kvlist__": [[_encode_value(k), _encode_value(v)]
                                   for k, v in value.items()]}
        return {k: _encode_value(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_encode_value(v) for v in value]
    if isinstance(value, float) and (value in (float("inf"), float("-inf"))):
        return {"__f__": "inf" if value > 0 else "-inf"}
    return value


def _decode_value(value: Any) -> Any:
    if isinstance(value, dict):
        if "__nd__" in value:
            return np.array(value["data"], dtype=np.dtype(value["__nd__"]))
        if "__f__" in value:
            return float(value["__f__"])
        if "__kvlist__" in value:
            return {_freeze(_decode_value(k)): _decode_value(v)
                    for k, v in value["__kvlist__"]}
        return {k: _decode_value(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_decode_value(v) for v in value]
    return value


def _freeze(value: Any) -> Any:
    return tuple(value) if isinstance(value, list) else value


def leaf_response_to_wire(response: LeafSearchResponse) -> dict[str, Any]:
    """Like `leaf_response_to_dict` but with intermediate agg states left
    as raw numpy — for the binary transport (`binwire.py`), which encodes
    arrays as dtype+shape+bytes instead of JSON lists."""
    d = leaf_response_to_dict(response)
    d["intermediate_aggs"] = response.intermediate_aggs
    return d


def leaf_response_from_wire(d: dict[str, Any]) -> LeafSearchResponse:
    response = leaf_response_from_dict({**d, "intermediate_aggs": {}})
    response.intermediate_aggs = d.get("intermediate_aggs", {})
    return response


def leaf_response_to_dict(response: LeafSearchResponse) -> dict[str, Any]:
    return {
        "num_hits": response.num_hits,
        "partial_hits": [
            [h.sort_value, h.split_id, h.doc_id, h.raw_sort_value,
             h.sort_value2, h.raw_sort_value2]
            for h in response.partial_hits
        ],
        "failed_splits": [
            {"split_id": e.split_id, "error": e.error, "retryable": e.retryable}
            for e in response.failed_splits
        ],
        "num_attempted_splits": response.num_attempted_splits,
        "num_successful_splits": response.num_successful_splits,
        "intermediate_aggs": _encode_value(response.intermediate_aggs),
        "resource_stats": response.resource_stats,
        # additive: absent unless the leaf profiled this request
        **({"profile": response.profile}
           if response.profile is not None else {}),
    }


def leaf_response_from_dict(d: dict[str, Any]) -> LeafSearchResponse:
    return LeafSearchResponse(
        num_hits=d["num_hits"],
        partial_hits=[
            PartialHit(sort_value=h[0], split_id=h[1], doc_id=h[2],
                       raw_sort_value=h[3],
                       sort_value2=h[4] if len(h) > 4 else 0.0,
                       raw_sort_value2=h[5] if len(h) > 5 else None)
            for h in d.get("partial_hits", [])
        ],
        failed_splits=[
            SplitSearchError(e["split_id"], e["error"], e.get("retryable", True))
            for e in d.get("failed_splits", [])
        ],
        num_attempted_splits=d.get("num_attempted_splits", 0),
        num_successful_splits=d.get("num_successful_splits", 0),
        intermediate_aggs=_decode_value(d.get("intermediate_aggs", {})),
        resource_stats=d.get("resource_stats", {}),
        profile=d.get("profile"),
    )
