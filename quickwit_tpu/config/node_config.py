"""Config loading: YAML/JSON with environment-variable interpolation.

Role of the reference's `quickwit-config` (`node_config/serialize.rs`):
layered node config (defaults < file < env) with `${VAR}` / `${VAR:-default}`
interpolation, plus index-config files for `quickwit index create`.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import yaml

from ..serve.node import NodeConfig

_ENV_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)(?::-([^}]*))?\}")


def interpolate_env(text: str, env: Optional[dict[str, str]] = None) -> str:
    env = env if env is not None else dict(os.environ)

    def replace(match: re.Match) -> str:
        name, default = match.group(1), match.group(2)
        if name in env:
            return env[name]
        if default is not None:
            return default
        raise ValueError(f"environment variable {name!r} is not set and has no default")

    return _ENV_RE.sub(replace, text)


def _load_yaml(path: str, env: Optional[dict[str, str]] = None) -> dict[str, Any]:
    with open(path) as f:
        raw = f.read()
    return yaml.safe_load(interpolate_env(raw, env)) or {}


def load_source_config(path: str,
                       env: Optional[dict[str, str]] = None) -> dict[str, Any]:
    """Source config file (yaml/json) -> the dict the source-create
    route consumes (reference: `source_config/mod.rs` yaml shape)."""
    data = _load_yaml(path, env)
    if not isinstance(data, dict):
        raise ValueError(
            f"source config {path} must be a YAML/JSON object, "
            f"got {type(data).__name__}")
    # field-level validation lives in parse_source_config (the one
    # shared REST/CLI site); this loader only owns file -> dict
    data.pop("version", None)
    return data


def load_node_config(path: Optional[str] = None,
                     env: Optional[dict[str, str]] = None) -> NodeConfig:
    """Precedence: defaults < config file < QW_* env vars
    (reference: `node_config/serialize.rs` load order)."""
    data: dict[str, Any] = {}
    if path:
        data = _load_yaml(path, env)
    environ = env if env is not None else dict(os.environ)

    def pick(env_key: str, file_key: str, default):
        if env_key in environ:
            return environ[env_key]
        return data.get(file_key, default)

    roles_raw = pick("QW_ENABLED_SERVICES", "enabled_services",
                     data.get("roles", "searcher,indexer,metastore,janitor,control_plane"))
    if isinstance(roles_raw, str):
        roles = tuple(r.strip() for r in roles_raw.split(",") if r.strip())
    else:
        roles = tuple(roles_raw)
    rest = data.get("rest", {})
    tls = rest.get("tls") or {}  # bare "tls:" key parses as None
    return NodeConfig(
        node_id=str(pick("QW_NODE_ID", "node_id", "node-0")),
        cluster_id=str(pick("QW_CLUSTER_ID", "cluster_id", "quickwit-tpu")),
        roles=roles,
        metastore_uri=str(pick("QW_METASTORE_URI", "metastore_uri",
                               "file:///tmp/quickwit_tpu/metastore")),
        default_index_root_uri=str(pick(
            "QW_DEFAULT_INDEX_ROOT_URI", "default_index_root_uri",
            "file:///tmp/quickwit_tpu/indexes")),
        rest_host=str(environ.get("QW_REST_HOST",
                                  rest.get("listen_host", "127.0.0.1"))),
        rest_port=int(environ.get("QW_REST_PORT",
                                  rest.get("listen_port", 7280))),
        peers=tuple(data.get("peer_seeds", ())),
        tls_cert_path=tls.get("cert_path"),
        tls_key_path=tls.get("key_path"),
        tls_ca_path=tls.get("ca_path"),
        tls_skip_verify=bool(tls.get("skip_verify", False)),
        tls_verify_client=bool(tls.get("verify_client", False)),
        gossip_enabled=bool(data.get("gossip", False)),
        replication_factor=int(pick("QW_REPLICATION_FACTOR",
                                    "replication_factor", 1)),
        offload=((data.get("searcher", {}) or {}).get("offload")
                 if isinstance((data.get("searcher", {}) or {}).get(
                     "offload"), dict) else None),
        offload_endpoint=(data.get("searcher", {}) or {}).get(
            "offload_endpoint"),
        offload_max_local_splits=int((data.get("searcher", {}) or {}).get(
            "offload_max_local_splits", 16)),
        **_split_cache_fields(data),
        tenancy=(data.get("tenancy")
                 if isinstance(data.get("tenancy"), dict) else None),
        grpc_port=(int(environ["QW_GRPC_PORT"])
                   if "QW_GRPC_PORT" in environ
                   else (int((data.get("grpc", {}) or {})["listen_port"])
                         if (data.get("grpc") or {}).get("listen_port")
                         is not None else None)),
    )


def _split_cache_fields(data: dict) -> dict[str, Any]:
    """`searcher.split_cache: {root_path, max_bytes, max_splits}` → the
    NodeConfig disk-split-cache fields (absent/None = disabled)."""
    cache = (data.get("searcher", {}) or {}).get("split_cache")
    if not isinstance(cache, dict) or not cache.get("root_path"):
        return {}
    return {
        "split_cache_dir": str(cache["root_path"]),
        "split_cache_max_bytes": int(cache.get("max_bytes", 10 << 30)),
        "split_cache_max_splits": int(cache.get("max_splits", 10_000)),
    }


def load_index_config(path: str, env: Optional[dict[str, str]] = None) -> dict[str, Any]:
    """Index config file (yaml/json) → the dict `IndexService.create_index`
    consumes; field mapping entries use the same shape as the reference's
    index config yaml."""
    data = _load_yaml(path, env)
    if "version" in data:
        data.pop("version")
    doc_mapping = data.get("doc_mapping", {})
    # accept the reference's nested field_mappings with `name`/`type` keys
    # verbatim; flatten "object"-typed nested mappings into dotted paths
    flat: list[dict[str, Any]] = []

    def walk(entries: list[dict[str, Any]], prefix: str = "") -> None:
        for entry in entries:
            name = f"{prefix}{entry['name']}"
            if entry.get("type") == "object":
                walk(entry.get("field_mappings", []), prefix=f"{name}.")
            else:
                flat.append({**entry, "name": name})

    walk(doc_mapping.get("field_mappings", []))
    doc_mapping = {**doc_mapping, "field_mappings": flat}
    data["doc_mapping"] = doc_mapping
    return data
