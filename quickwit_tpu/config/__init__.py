from .node_config import (load_node_config, load_index_config,
                          load_source_config)

__all__ = ["load_node_config", "load_index_config",
           "load_source_config"]
