"""Segmented write-ahead record log.

Role of the reference's `mrecordlog` crate (the WAL under ingest-v2 shards):
an append-only, fsync'd, position-addressed record log with truncation.
Records live in segment files (`wal-{first_position:020d}.seg`); truncation
drops whole segments whose records are all below the truncate position —
exactly how the indexer's published checkpoint reclaims WAL space.

Record format per entry: u32 length | payload. Positions are record
ordinals (not byte offsets), monotonically increasing across segments.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Iterator, Optional

_SEGMENT_MAX_BYTES = 8 << 20
_LEN = struct.Struct("<I")


class RecordLog:
    def __init__(self, directory: str, fsync: bool = True,
                 fault_injector=None):
        self.directory = directory
        self.fsync = fsync
        # chaos hook (common/faults.FaultInjector): perturbs "wal.fsync"
        # before each durability barrier — a latency rule models a slow
        # disk, an error rule a failed fsync the caller must surface
        self.fault_injector = fault_injector
        os.makedirs(directory, exist_ok=True)
        # qwlint: disable-next-line=QW008 - ingest WAL/router leaf locks; pure
        # in-memory ops inside, never a seam primitive
        self._lock = threading.Lock()
        # segments: sorted list of (first_position, path)
        self._segments: list[tuple[int, str]] = []
        self._active_file = None
        self._active_size = 0
        self.next_position = 0
        self._recover()

    # --- recovery ----------------------------------------------------------
    def _recover(self) -> None:
        names = sorted(n for n in os.listdir(self.directory)
                       if n.startswith("wal-") and n.endswith(".seg"))
        for name in names:
            first = int(name[4:-4])
            self._segments.append((first, os.path.join(self.directory, name)))
        if not self._segments:
            return
        # count records of the last segment to find next_position; earlier
        # segments' record counts derive from their successors' first position
        last_first, last_path = self._segments[-1]
        count, consumed = self._scan_segment(last_path)
        # drop any torn tail write now: appends reopen this file in 'ab'
        # mode, and new records written after torn bytes would be misframed
        # by the stale partial header on replay
        if consumed < os.path.getsize(last_path):
            with open(last_path, "r+b") as f:
                f.truncate(consumed)
        self.next_position = last_first + count

    @staticmethod
    def _scan_segment(path: str) -> tuple[int, int]:
        """(record_count, byte_offset_after_last_complete_record)."""
        count, consumed = 0, 0
        with open(path, "rb") as f:
            while True:
                header = f.read(_LEN.size)
                if len(header) < _LEN.size:
                    return count, consumed
                (length,) = _LEN.unpack(header)
                payload = f.read(length)
                if len(payload) < length:
                    return count, consumed
                count += 1
                consumed += _LEN.size + length

    # --- append ------------------------------------------------------------
    def append(self, payload: bytes) -> int:
        """Returns the position of the appended record."""
        with self._lock:
            if self._active_file is None or self._active_size > _SEGMENT_MAX_BYTES:
                self._roll()
            position = self.next_position
            data = _LEN.pack(len(payload)) + payload
            # perturb BEFORE the write: an error-kind "failed fsync" must
            # reject the record cleanly, not leave unaccounted bytes on disk
            if self.fault_injector is not None:
                self.fault_injector.perturb("wal.fsync")
            self._active_file.write(data)
            self._active_file.flush()
            if self.fsync:
                os.fsync(self._active_file.fileno())
            self._active_size += len(data)
            self.next_position += 1
            return position

    def append_batch(self, payloads: list[bytes]) -> tuple[int, int]:
        """(first_position, last_position) with a single fsync."""
        if not payloads:
            raise ValueError("empty batch")
        with self._lock:
            if self._active_file is None or self._active_size > _SEGMENT_MAX_BYTES:
                self._roll()
            first = self.next_position
            chunks = []
            for payload in payloads:
                chunks.append(_LEN.pack(len(payload)))
                chunks.append(payload)
            data = b"".join(chunks)
            if self.fault_injector is not None:
                self.fault_injector.perturb("wal.fsync")
            self._active_file.write(data)
            self._active_file.flush()
            if self.fsync:
                os.fsync(self._active_file.fileno())
            self._active_size += len(data)
            self.next_position += len(payloads)
            return first, self.next_position - 1

    def _roll(self) -> None:
        if self._active_file is not None:
            self._active_file.close()
        path = os.path.join(self.directory, f"wal-{self.next_position:020d}.seg")
        # a crash between a previous _roll() and the first append leaves an
        # empty last segment already registered under this path; re-registering
        # it would make read_from iterate the segment twice
        if not (self._segments and self._segments[-1][1] == path):
            self._segments.append((self.next_position, path))
        self._active_file = open(path, "ab")
        self._active_size = os.path.getsize(path)

    # --- read --------------------------------------------------------------
    @staticmethod
    def _iter_segment(path: str) -> Iterator[bytes]:
        with open(path, "rb") as f:
            while True:
                header = f.read(_LEN.size)
                if len(header) < _LEN.size:
                    return
                (length,) = _LEN.unpack(header)
                payload = f.read(length)
                if len(payload) < length:
                    return  # torn tail write: ignore (crash recovery)
                yield payload

    def read_from(self, position: int, max_records: int = 10_000
                  ) -> list[tuple[int, bytes]]:
        """Records with position >= `position`, up to max_records."""
        with self._lock:
            segments = list(self._segments)
        out: list[tuple[int, bytes]] = []
        for i, (first, path) in enumerate(segments):
            next_first = segments[i + 1][0] if i + 1 < len(segments) else None
            if next_first is not None and next_first <= position:
                continue
            pos = first
            try:
                for payload in self._iter_segment(path):
                    if pos >= position:
                        out.append((pos, payload))
                        if len(out) >= max_records:
                            return out
                    pos += 1
            except FileNotFoundError:
                # concurrent truncate() unlinked this segment; its records
                # were all below the published checkpoint anyway
                continue
        return out

    # --- tail rollback (replication atomicity) -----------------------------
    def tail_state(self) -> tuple:
        """Opaque pre-append snapshot for `rollback_to` — taken by a caller
        holding the batch atomic (persist+replicate) critical section."""
        with self._lock:
            active_path = self._segments[-1][1] if self._segments else None
            # on-disk size, not _active_size: after recovery the active file
            # holds bytes appended before restart that _roll hasn't measured
            size = (os.path.getsize(active_path)
                    if active_path and os.path.exists(active_path) else 0)
            return (self.next_position, active_path, size,
                    len(self._segments))

    def rollback_to(self, state: tuple) -> None:
        """Undo appends made since `tail_state()` (same critical section —
        no interleaved appends): chained replication needs 'durable on both
        or neither', so a failed replication rolls the local tail back."""
        next_position, active_path, active_size, num_segments = state
        with self._lock:
            # drop any segment the rolled-back append created
            while len(self._segments) > num_segments:
                _, path = self._segments.pop()
                if self._active_file is not None:
                    self._active_file.close()
                    self._active_file = None
                if os.path.exists(path):
                    os.unlink(path)
            if num_segments == 0:
                if self._active_file is not None:
                    self._active_file.close()
                self._active_file = None
                self._active_size = 0
            elif active_path is not None and os.path.exists(active_path):
                if self._active_file is not None:
                    self._active_file.close()
                with open(active_path, "r+b") as f:
                    f.truncate(active_size)
                self._active_file = open(active_path, "ab")
                self._active_size = active_size
            self.next_position = next_position

    def reset_to(self, position: int) -> None:
        """Drop everything and restart the log at `position` (replica
        catch-up past the leader's truncation watermark)."""
        with self._lock:
            if self._active_file is not None:
                self._active_file.close()
                self._active_file = None
            for _, path in self._segments:
                if os.path.exists(path):
                    os.unlink(path)
            self._segments = []
            self._active_size = 0
            self.next_position = position

    # --- truncate ----------------------------------------------------------
    def truncate(self, up_to_position: int) -> int:
        """Drop segments entirely below `up_to_position` (exclusive).
        Returns number of segments removed."""
        removed = 0
        with self._lock:
            while len(self._segments) > 1:
                first, path = self._segments[0]
                next_first = self._segments[1][0]
                if next_first <= up_to_position:
                    os.unlink(path)
                    self._segments.pop(0)
                    removed += 1
                else:
                    break
        return removed

    def close(self) -> None:
        with self._lock:
            if self._active_file is not None:
                self._active_file.close()
                self._active_file = None
