"""Ingest router: doc batches → WAL shards.

Role of the reference's `IngestRouter` + `RoutingTable`
(`quickwit-ingest/src/ingest_v2/router.rs:97`, `routing_table.rs`): front
door of the write path — resolve open shards for (index, source), spread
batches across them (round-robin over open shards), ask the control plane
for shards when none exist, and retry on closed shards (the workbench
logic, simplified to synchronous semantics).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from .ingester import Ingester, ShardState

INGEST_V2_SOURCE_ID = "_ingest-source"
INGEST_API_SOURCE_ID = "_ingest-api-source"  # the v1 synchronous REST path

# sources whose checkpoints guard the built-in ingest paths against replay
INTERNAL_SOURCE_IDS = (INGEST_V2_SOURCE_ID, INGEST_API_SOURCE_ID)


@dataclass
class RoutingEntry:
    shard_ids: list[str] = field(default_factory=list)
    cursor: int = 0


class IngestRouter:
    def __init__(self, ingester: Ingester,
                 get_or_create_shards: Optional[Callable[[str, str], list[str]]] = None,
                 shards_per_source: int = 1,
                 shard_prefix: str = ""):
        self.ingester = ingester
        self.shards_per_source = shards_per_source
        # `shard_prefix` (normally the node id) keeps WAL shard ids unique
        # across nodes: each node drains its own local WAL into a shared
        # metastore, and per-shard checkpoint partitions must not collide
        # (the reference's ingest-v2 shards are cluster-global for the
        # same reason, control_plane.proto:65).
        self.shard_prefix = f"{shard_prefix}-" if shard_prefix else ""
        # control-plane hook: GetOrCreateOpenShards (control_plane.proto:65);
        # default: local static placement
        self.get_or_create_shards = get_or_create_shards or self._default_shards
        self._table: dict[tuple[str, str], RoutingEntry] = {}
        # qwlint: disable-next-line=QW008 - ingest WAL/router leaf locks; pure
        # in-memory ops inside, never a seam primitive
        self._lock = threading.Lock()

    def _default_shards(self, index_uid: str, source_id: str) -> list[str]:
        return [f"{self.shard_prefix}shard-{i:02d}"
                for i in range(self.shards_per_source)]

    def _entry(self, index_uid: str, source_id: str) -> RoutingEntry:
        key = (index_uid, source_id)
        with self._lock:
            entry = self._table.get(key)
            if entry is None or not entry.shard_ids:
                shard_ids = self.get_or_create_shards(index_uid, source_id)
                entry = RoutingEntry(shard_ids=list(shard_ids))
                self._table[key] = entry
            return entry

    def refresh(self, index_uid: str,
                source_id: str = INGEST_V2_SOURCE_ID) -> None:
        """Drop the cached shard list so the next batch re-resolves it —
        called after the control plane opens or closes shards (reference:
        routing-table invalidation on shard-set change)."""
        with self._lock:
            self._table.pop((index_uid, source_id), None)

    def ingest(self, index_uid: str, docs: list[dict[str, Any]],
               source_id: str = INGEST_V2_SOURCE_ID) -> dict[str, Any]:
        """Route one batch; returns {shard_id: (first, last)} positions."""
        if not docs:
            return {"positions": {}, "num_docs": 0}
        entry = self._entry(index_uid, source_id)
        last_error: Optional[Exception] = None
        for _ in range(len(entry.shard_ids)):
            with self._lock:
                shard_id = entry.shard_ids[entry.cursor % len(entry.shard_ids)]
                entry.cursor += 1
            try:
                first, last = self.ingester.persist(
                    index_uid, source_id, shard_id, docs)
                return {"positions": {shard_id: [first, last]},
                        "num_docs": len(docs)}
            except ValueError as exc:  # closed shard: drop from table, retry
                last_error = exc
                with self._lock:
                    if shard_id in entry.shard_ids:
                        entry.shard_ids.remove(shard_id)
                    if not entry.shard_ids:
                        # refill inside the lock so concurrent ingests never
                        # observe an empty shard list
                        entry.shard_ids = list(
                            self.get_or_create_shards(index_uid, source_id))
        raise RuntimeError(f"no open shard accepted the batch: {last_error}")
