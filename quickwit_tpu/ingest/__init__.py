from .wal import RecordLog
from .ingester import Ingester, ShardState
from .router import IngestRouter

__all__ = ["RecordLog", "Ingester", "ShardState", "IngestRouter"]
