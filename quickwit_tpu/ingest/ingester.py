"""Ingester: hosts WAL shards.

Role of the reference's `Ingester` (`quickwit-ingest/src/ingest_v2/
ingester.rs:99`): persist doc batches durably into per-shard WAL queues,
serve fetch streams to the indexing source, truncate behind published
checkpoints, and recover shard state from disk on restart. Chained
replication (RF>1, `replication.rs`) is stubbed at the `replicate_to`
seam — the persist path invokes it for every batch so a follower client
slots in without protocol changes.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Optional

from .wal import RecordLog


class ReplicationGap(ValueError):
    """Follower is missing records before the offered batch; carries the
    follower's next position so the leader can backfill."""

    def __init__(self, message: str, have: int):
        super().__init__(message)
        self.have = have


class ShardState(str, Enum):
    OPEN = "open"
    CLOSED = "closed"  # no new writes; drains then gets deleted


@dataclass
class Shard:
    index_uid: str
    source_id: str
    shard_id: str
    log: RecordLog
    state: ShardState = ShardState.OPEN
    publish_position: int = 0  # truncation watermark
    # serializes persist+replicate as one critical section: replication
    # stays batch-ordered and a failed chain rolls the local tail back
    persist_lock: threading.Lock = field(default_factory=threading.Lock)
    # "leader" shards accept router writes and are drained by the indexer;
    # "replica" shards only accept replica_persist and sit out of drains
    # until promoted (reference: chained replication, replication.rs)
    role: str = "leader"
    # cumulative ingested payload bytes; the scaling arbiter turns deltas
    # of this into MiB/s (reference: per-shard ingestion-rate gossip)
    bytes_written: int = 0
    # positions below this are replication-chain committed and safe to
    # serve to fetch streams; maintained at every leadership event (shard
    # creation, recovery, promotion — where the full WAL is the
    # at-least-once committed floor — and each successful persist).
    # -1 = unset (replica shards; fetch falls back to the log head).
    # Without the clamp a fetch racing the persist critical section could
    # drain an appended-but-unreplicated tail that a failed chain then
    # rolls back, re-using the published positions for different
    # documents — the qwmc replication model's publish_from watermark
    # (tools/qwmc/models.py).
    committed_position: int = -1


def shard_queue_id(index_uid: str, source_id: str, shard_id: str) -> str:
    # ':' is not filesystem-friendly; '@' cannot occur in index ids, so the
    # encoding is reversible even for ids containing underscores
    return f"{index_uid.replace(':', '@')}/{source_id}/{shard_id}"


class Ingester:
    def __init__(self, wal_dir: str, fsync: bool = True,
                 replicate_to: Optional[Callable[
                     [str, str, str, int, list[bytes]], None]] = None,
                 fault_injector=None):
        self.wal_dir = wal_dir
        self.fsync = fsync
        self.replicate_to = replicate_to
        # chaos hook (common/faults.FaultInjector): threads into every
        # shard's RecordLog ("wal.fsync") and perturbs "ingest.replicate"
        # around the chained-replication hop — an error-kind rule there
        # exercises the rollback path exactly like a dropped follower
        self.fault_injector = fault_injector
        # on_truncate(index_uid, source_id, shard_id, position): leader-side
        # hook propagating truncation to the replica (space reclaim)
        self.on_truncate: Optional[Callable[[str, str, str, int],
                                            None]] = None
        self._shards: dict[str, Shard] = {}
        # qwlint: disable-next-line=QW008 - ingest WAL/router leaf locks; pure
        # in-memory ops inside, never a seam primitive
        self._lock = threading.Lock()
        self._recover()

    # --- recovery ----------------------------------------------------------
    def _recover(self) -> None:
        if not os.path.isdir(self.wal_dir):
            return
        for index_dir in os.listdir(self.wal_dir):
            index_path = os.path.join(self.wal_dir, index_dir)
            if not os.path.isdir(index_path):
                continue
            for source_id in os.listdir(index_path):
                source_path = os.path.join(index_path, source_id)
                for shard_id in os.listdir(source_path):
                    queue_id = f"{index_dir}/{source_id}/{shard_id}"
                    index_uid = index_dir.replace("@", ":")
                    shard_dir = os.path.join(source_path, shard_id)
                    role = "leader"
                    role_path = os.path.join(shard_dir, "_role")
                    if os.path.exists(role_path):
                        with open(role_path) as f:
                            role = f.read().strip() or "leader"
                    shard = Shard(
                        index_uid=index_uid, source_id=source_id,
                        shard_id=shard_id, role=role,
                        log=RecordLog(shard_dir, fsync=self.fsync,
                                      fault_injector=self.fault_injector))
                    if role == "leader":
                        # recovery commits the durable tail (at-least-once:
                        # the chain may or may not have acked it)
                        shard.committed_position = shard.log.next_position
                    self._shards[queue_id] = shard

    # --- shard lifecycle ---------------------------------------------------
    def open_shard(self, index_uid: str, source_id: str, shard_id: str,
                   role: str = "leader") -> Shard:
        queue_id = shard_queue_id(index_uid, source_id, shard_id)
        with self._lock:
            shard = self._shards.get(queue_id)
            if shard is None:
                shard_dir = os.path.join(self.wal_dir, queue_id)
                shard = Shard(
                    index_uid=index_uid, source_id=source_id, shard_id=shard_id,
                    role=role,
                    log=RecordLog(shard_dir, fsync=self.fsync,
                                  fault_injector=self.fault_injector))
                if role != "leader":
                    self._write_role(shard_dir, role)
                else:
                    shard.committed_position = shard.log.next_position
                self._shards[queue_id] = shard
            return shard

    @staticmethod
    def _write_role(shard_dir: str, role: str) -> None:
        os.makedirs(shard_dir, exist_ok=True)
        with open(os.path.join(shard_dir, "_role"), "w") as f:
            f.write(role)

    def promote_replica(self, queue_id: str,
                        min_position: Optional[int] = None) -> bool:
        """Replica → leader (the leader ingester died; this copy takes over
        draining — reference: AdviseResetShards / shard re-open,
        ingest_controller.rs:204). Checkpoint continuity holds because the
        replica hosts the SAME shard id at the same WAL positions.

        `min_position` is the published checkpoint: a promoted log whose
        head is BEHIND it forward-resets to the checkpoint, or the new
        leader would hand already-consumed positions to fresh appends
        (qwmc's behind-checkpoint promotion counterexample — the old
        leader's recovery-committed tail published past this copy's head).
        Everything dropped by the reset sits below the checkpoint, hence
        is already published."""
        with self._lock:
            shard = self._shards.get(queue_id)
            if shard is None or shard.role == "leader":
                return False
            if (min_position is not None
                    and shard.log.next_position < min_position):
                shard.log.reset_to(min_position)
                shard.publish_position = max(shard.publish_position,
                                             min_position)
            shard.role = "leader"
            # everything a replica holds came through the chain: committed
            shard.committed_position = shard.log.next_position
            self._write_role(os.path.join(self.wal_dir, queue_id), "leader")
            return True

    def demote_to_replica(self, queue_id: str, position: int) -> bool:
        """Leader → replica, WAL reset at `position` (the published
        checkpoint): a node that crashed and rejoined after another copy
        was promoted still recovers its shard with the old leader role —
        qwmc's stale-leader-rejoin counterexample shows the split-brain
        re-uses published positions and loses an acked record. The
        registered chain (metastore.shard_chain) holds every acked
        record, so the stale content is redundant; keeping it would
        collide with positions the promoted leader hands out."""
        with self._lock:
            shard = self._shards.get(queue_id)
            if shard is None or shard.role != "leader":
                return False
            shard.role = "replica"
            self._write_role(os.path.join(self.wal_dir, queue_id), "replica")
            shard.log.reset_to(position)
            shard.publish_position = max(shard.publish_position, position)
            shard.committed_position = -1
            return True

    def replica_shards(self) -> list[tuple[str, Shard]]:
        with self._lock:
            return [(qid, s) for qid, s in self._shards.items()
                    if s.role == "replica"]

    def close_shard(self, index_uid: str, source_id: str, shard_id: str) -> None:
        shard = self._shards.get(shard_queue_id(index_uid, source_id, shard_id))
        if shard is not None:
            shard.state = ShardState.CLOSED

    def list_shards(self, index_uid: Optional[str] = None,
                    include_replicas: bool = False) -> list[Shard]:
        with self._lock:  # snapshot: persist/open_shard mutate concurrently
            shards = list(self._shards.values())
        return [s for s in shards
                if (index_uid is None or s.index_uid == index_uid)
                and (include_replicas or s.role == "leader")]

    def shard(self, index_uid: str, source_id: str, shard_id: str) -> Optional[Shard]:
        return self._shards.get(shard_queue_id(index_uid, source_id, shard_id))

    # --- persist / fetch / truncate ---------------------------------------
    def persist(self, index_uid: str, source_id: str, shard_id: str,
                docs: list[dict[str, Any]]) -> tuple[int, int]:
        """Durable append of a doc batch; returns (first, last) positions
        (reference: `ingester.rs:430,1117` persist)."""
        shard = self.open_shard(index_uid, source_id, shard_id)
        if shard.state is not ShardState.OPEN:
            raise ValueError(f"shard {shard_id!r} is closed")
        if shard.role != "leader":
            raise ValueError(f"shard {shard_id!r} is a replica")
        payloads = [json.dumps(d, separators=(",", ":")).encode() for d in docs]
        with shard.persist_lock:
            # one critical section per shard: replication sees batches in
            # WAL order, and a failed chain rolls the local tail back so
            # the ack means "durable on leader AND follower or neither"
            # (reference: replication.rs persist semantics; a client retry
            # after an error therefore cannot duplicate documents)
            state = shard.log.tail_state()
            first, last = shard.log.append_batch(payloads)
            if self.replicate_to is not None:
                try:
                    if self.fault_injector is not None:
                        self.fault_injector.perturb("ingest.replicate")
                    self.replicate_to(index_uid, source_id, shard.shard_id,
                                      first, payloads)
                except Exception:
                    shard.log.rollback_to(state)
                    raise
            shard.bytes_written += sum(len(p) for p in payloads)
            shard.committed_position = shard.log.next_position
        return first, last

    def replica_persist(self, index_uid: str, source_id: str, shard_id: str,
                        first_position: int, payloads: list[bytes]) -> int:
        """Follower side of chained replication: position-aligned append.
        Idempotent — records already present (leader retry) are skipped;
        a gap (missed batch) is an error the leader must handle."""
        shard = self.open_shard(index_uid, source_id, shard_id,
                                role="replica")
        if shard.role == "leader":
            raise ValueError(
                f"shard {shard_id!r} is led from this node; refusing to "
                "replicate onto it")
        next_position = shard.log.next_position
        if first_position > next_position:
            raise ReplicationGap(
                f"replication gap on {shard_id!r}: have {next_position}, "
                f"got batch at {first_position}", have=next_position)
        skip = next_position - first_position
        if skip >= len(payloads):
            return next_position - 1  # full batch already replicated
        shard.log.append_batch(payloads[skip:])
        return shard.log.next_position - 1

    def replica_reset(self, index_uid: str, source_id: str, shard_id: str,
                      position: int) -> None:
        """Restart a replica log at `position` — used when the leader's
        retained WAL no longer covers the follower's gap (the missing
        records are already published; the shared metastore checkpoint is
        the durability floor there)."""
        shard = self.open_shard(index_uid, source_id, shard_id,
                                role="replica")
        if shard.role == "leader":
            raise ValueError(f"shard {shard_id!r} is led from this node")
        shard.log.reset_to(position)

    def replica_truncate(self, index_uid: str, source_id: str,
                         shard_id: str, up_to_position: int) -> None:
        """Follower-side truncation behind the leader's published
        checkpoint (replica WALs must not grow without bound)."""
        shard = self.shard(index_uid, source_id, shard_id)
        if shard is not None and shard.role == "replica":
            shard.publish_position = max(shard.publish_position,
                                         up_to_position)
            shard.log.truncate(up_to_position)

    def fetch(self, index_uid: str, source_id: str, shard_id: str,
              from_position: int, max_records: int = 10_000
              ) -> list[tuple[int, dict[str, Any]]]:
        """Records from the WAL for the indexing source's fetch stream
        (reference: `fetch.rs` FetchStreamTask)."""
        shard = self.shard(index_uid, source_id, shard_id)
        if shard is None:
            return []
        records = shard.log.read_from(from_position, max_records)
        if shard.role == "leader" and shard.committed_position >= 0:
            # never serve past the replication-committed watermark (see
            # Shard.committed_position)
            records = [(pos, payload) for pos, payload in records
                       if pos < shard.committed_position]
        return [(pos, json.loads(payload)) for pos, payload in records]

    def truncate(self, index_uid: str, source_id: str, shard_id: str,
                 up_to_position: int) -> None:
        """Reclaim WAL space behind the published checkpoint
        (reference: TruncateShards / `shard_positions.rs`)."""
        shard = self.shard(index_uid, source_id, shard_id)
        if shard is not None:
            shard.publish_position = max(shard.publish_position, up_to_position)
            shard.log.truncate(up_to_position)
            if self.on_truncate is not None and shard.role == "leader":
                # propagate to the replica (best-effort: replicas re-derive
                # the watermark from the shared metastore at promotion)
                try:
                    self.on_truncate(index_uid, source_id, shard_id,
                                     up_to_position)
                except Exception:  # noqa: BLE001 - space reclaim only
                    pass

    # --- observability ------------------------------------------------------
    def shard_throughput_state(self) -> dict[str, dict[str, int]]:
        """Per-shard positions for the control plane's capacity decisions
        (reference: shard-capacity gossip broadcast)."""
        with self._lock:
            items = list(self._shards.items())
        return {
            queue_id: {"head": shard.log.next_position,
                       "published": shard.publish_position,
                       "open": int(shard.state is ShardState.OPEN),
                       "bytes": shard.bytes_written}
            for queue_id, shard in items
        }
