"""Ingester: hosts WAL shards.

Role of the reference's `Ingester` (`quickwit-ingest/src/ingest_v2/
ingester.rs:99`): persist doc batches durably into per-shard WAL queues,
serve fetch streams to the indexing source, truncate behind published
checkpoints, and recover shard state from disk on restart. Chained
replication (RF>1, `replication.rs`) is stubbed at the `replicate_to`
seam — the persist path invokes it for every batch so a follower client
slots in without protocol changes.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Optional

from .wal import RecordLog


class ShardState(str, Enum):
    OPEN = "open"
    CLOSED = "closed"  # no new writes; drains then gets deleted


@dataclass
class Shard:
    index_uid: str
    source_id: str
    shard_id: str
    log: RecordLog
    state: ShardState = ShardState.OPEN
    publish_position: int = 0  # truncation watermark


def shard_queue_id(index_uid: str, source_id: str, shard_id: str) -> str:
    # ':' is not filesystem-friendly; '@' cannot occur in index ids, so the
    # encoding is reversible even for ids containing underscores
    return f"{index_uid.replace(':', '@')}/{source_id}/{shard_id}"


class Ingester:
    def __init__(self, wal_dir: str, fsync: bool = True,
                 replicate_to: Optional[Callable[[str, list[bytes]], None]] = None):
        self.wal_dir = wal_dir
        self.fsync = fsync
        self.replicate_to = replicate_to
        self._shards: dict[str, Shard] = {}
        self._lock = threading.Lock()
        self._recover()

    # --- recovery ----------------------------------------------------------
    def _recover(self) -> None:
        if not os.path.isdir(self.wal_dir):
            return
        for index_dir in os.listdir(self.wal_dir):
            index_path = os.path.join(self.wal_dir, index_dir)
            if not os.path.isdir(index_path):
                continue
            for source_id in os.listdir(index_path):
                source_path = os.path.join(index_path, source_id)
                for shard_id in os.listdir(source_path):
                    queue_id = f"{index_dir}/{source_id}/{shard_id}"
                    index_uid = index_dir.replace("@", ":")
                    self._shards[queue_id] = Shard(
                        index_uid=index_uid, source_id=source_id,
                        shard_id=shard_id,
                        log=RecordLog(os.path.join(source_path, shard_id),
                                      fsync=self.fsync))

    # --- shard lifecycle ---------------------------------------------------
    def open_shard(self, index_uid: str, source_id: str, shard_id: str) -> Shard:
        queue_id = shard_queue_id(index_uid, source_id, shard_id)
        with self._lock:
            shard = self._shards.get(queue_id)
            if shard is None:
                shard = Shard(
                    index_uid=index_uid, source_id=source_id, shard_id=shard_id,
                    log=RecordLog(os.path.join(self.wal_dir, queue_id),
                                  fsync=self.fsync))
                self._shards[queue_id] = shard
            return shard

    def close_shard(self, index_uid: str, source_id: str, shard_id: str) -> None:
        shard = self._shards.get(shard_queue_id(index_uid, source_id, shard_id))
        if shard is not None:
            shard.state = ShardState.CLOSED

    def list_shards(self, index_uid: Optional[str] = None) -> list[Shard]:
        with self._lock:  # snapshot: persist/open_shard mutate concurrently
            shards = list(self._shards.values())
        return [s for s in shards
                if index_uid is None or s.index_uid == index_uid]

    def shard(self, index_uid: str, source_id: str, shard_id: str) -> Optional[Shard]:
        return self._shards.get(shard_queue_id(index_uid, source_id, shard_id))

    # --- persist / fetch / truncate ---------------------------------------
    def persist(self, index_uid: str, source_id: str, shard_id: str,
                docs: list[dict[str, Any]]) -> tuple[int, int]:
        """Durable append of a doc batch; returns (first, last) positions
        (reference: `ingester.rs:430,1117` persist)."""
        shard = self.open_shard(index_uid, source_id, shard_id)
        if shard.state is not ShardState.OPEN:
            raise ValueError(f"shard {shard_id!r} is closed")
        payloads = [json.dumps(d, separators=(",", ":")).encode() for d in docs]
        first, last = shard.log.append_batch(payloads)
        if self.replicate_to is not None:
            self.replicate_to(shard_queue_id(index_uid, source_id, shard_id),
                              payloads)
        return first, last

    def fetch(self, index_uid: str, source_id: str, shard_id: str,
              from_position: int, max_records: int = 10_000
              ) -> list[tuple[int, dict[str, Any]]]:
        """Records from the WAL for the indexing source's fetch stream
        (reference: `fetch.rs` FetchStreamTask)."""
        shard = self.shard(index_uid, source_id, shard_id)
        if shard is None:
            return []
        return [(pos, json.loads(payload))
                for pos, payload in shard.log.read_from(from_position, max_records)]

    def truncate(self, index_uid: str, source_id: str, shard_id: str,
                 up_to_position: int) -> None:
        """Reclaim WAL space behind the published checkpoint
        (reference: TruncateShards / `shard_positions.rs`)."""
        shard = self.shard(index_uid, source_id, shard_id)
        if shard is not None:
            shard.publish_position = max(shard.publish_position, up_to_position)
            shard.log.truncate(up_to_position)

    # --- observability ------------------------------------------------------
    def shard_throughput_state(self) -> dict[str, dict[str, int]]:
        """Per-shard positions for the control plane's capacity decisions
        (reference: shard-capacity gossip broadcast)."""
        with self._lock:
            items = list(self._shards.items())
        return {
            queue_id: {"head": shard.log.next_position,
                       "published": shard.publish_position,
                       "open": int(shard.state is ShardState.OPEN)}
            for queue_id, shard in items
        }
