from .uri import Uri
from .pubsub import EventBroker
from .rendezvous import sort_by_rendezvous_hash

__all__ = ["Uri", "EventBroker", "sort_by_rendezvous_hash"]
