"""Process-injectable synchronization seam.

Role of rustc's Send/Sync discipline in the reference: the Rust codebase
gets data-race freedom checked at compile time; this reproduction has
dozens of lock/thread sites (cache tiers, offload pool, admission,
residency, batcher) that CPython happily lets race. This seam is the
dynamic-analysis counterpart: every `Lock`/`RLock`/`Condition`/`Event`/
`Semaphore`/`Thread` on a concurrency-relevant path is constructed through
the factories below, so the qwrace runtime (`tools/qwrace`) can substitute
instrumented primitives that

- serialize all instrumented threads under ONE seeded scheduler (every
  sync operation is a preemption point — loom/PCT style), making any
  interleaving reproducible from a seed;
- record acquire/release/start/join/wait/notify as happens-before edges
  for FastTrack-style vector-clock race detection;
- witness the runtime lock-order graph that `tools/qwrace bridge`
  cross-checks against qwlint QW007's static acquisition graph.

Contract (mirrors `common/clock.py`):

- With no runtime installed (production), every factory returns the plain
  `threading.*` object — byte-for-byte the pre-seam behavior, one global
  `is None` check of overhead.
- `set_runtime` / `use_runtime` install a `SyncRuntime`; the qwrace
  harness is the only installer.
- `note_read(owner, field)` / `note_write(owner, field)` annotate accesses
  to registered shared structures (ThresholdBox, WorkerPool, cache tiers,
  ResidentColumnStore, tenant registry, actor mailboxes). They are no-ops
  in production and feed the vector-clock detector under qwrace.
- `name=` strings follow qwlint QW007's lock-node naming
  (`ClassName._lock`, module-level `_SOME_LOCK`) so runtime witness edges
  and static edges meet in one namespace.

qwlint rule QW008 enforces adoption: raw `threading.{Lock,RLock,
Condition,Event,Semaphore,Thread}` construction outside this module is a
finding unless the site carries a justified suppression.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Callable, Iterator, Optional


class SyncRuntime:
    """Interface the qwrace runtime implements. Every method must return
    an object duck-compatible with the `threading` original (context
    manager protocol for locks, `wait`/`notify` for conditions, `start`/
    `join`/`is_alive` for threads)."""

    def make_lock(self, name: Optional[str]) -> Any:
        raise NotImplementedError

    def make_rlock(self, name: Optional[str]) -> Any:
        raise NotImplementedError

    def make_condition(self, lock: Any, name: Optional[str]) -> Any:
        raise NotImplementedError

    def make_event(self, name: Optional[str]) -> Any:
        raise NotImplementedError

    def make_semaphore(self, value: int, name: Optional[str]) -> Any:
        raise NotImplementedError

    def make_thread(self, target: Optional[Callable], args: tuple,
                    kwargs: dict, name: Optional[str],
                    daemon: Optional[bool]) -> Any:
        raise NotImplementedError

    def note_access(self, owner: Any, field: str, is_write: bool) -> None:
        raise NotImplementedError

    def register_shared(self, obj: Any, name: str) -> None:
        raise NotImplementedError


_runtime: Optional[SyncRuntime] = None
_runtime_lock = threading.Lock()


def get_runtime() -> Optional[SyncRuntime]:
    return _runtime


def set_runtime(runtime: Optional[SyncRuntime]) -> Optional[SyncRuntime]:
    """Install `runtime` process-wide (None restores plain threading);
    returns the previously installed runtime."""
    global _runtime
    with _runtime_lock:
        previous = _runtime
        _runtime = runtime
        return previous


@contextmanager
def use_runtime(runtime: SyncRuntime) -> Iterator[SyncRuntime]:
    previous = set_runtime(runtime)
    try:
        yield runtime
    finally:
        set_runtime(previous)


# --- factories ---------------------------------------------------------------

def lock(name: Optional[str] = None):
    """A mutex; `name` should match the QW007 static node for this lock
    (e.g. "WorkerPool._lock") so the lock-graph bridge can align the
    runtime witness edge with the static acquisition edge."""
    if _runtime is None:
        return threading.Lock()
    return _runtime.make_lock(name)


def rlock(name: Optional[str] = None):
    if _runtime is None:
        return threading.RLock()
    return _runtime.make_rlock(name)


def condition(lock: Any = None, name: Optional[str] = None):
    """A condition variable over `lock` (a fresh seam lock when None)."""
    if _runtime is None:
        return threading.Condition(lock)
    return _runtime.make_condition(lock, name)


def event(name: Optional[str] = None):
    if _runtime is None:
        return threading.Event()
    return _runtime.make_event(name)


def semaphore(value: int = 1, name: Optional[str] = None):
    if _runtime is None:
        return threading.Semaphore(value)
    return _runtime.make_semaphore(value, name)


def thread(target: Optional[Callable] = None, *, args: tuple = (),
           kwargs: Optional[dict] = None, name: Optional[str] = None,
           daemon: Optional[bool] = None):
    """A thread the qwrace scheduler can gate. `start()` on the returned
    object registers the child with the scheduler and establishes the
    start→first-op happens-before edge."""
    if _runtime is None:
        # qwlint: disable-next-line=QW003 - pass-through factory: context
        # propagation is the CALLER's contract (callers wrap their target
        # with run_with_context exactly as they did pre-seam), and QW003
        # keeps enforcing that at every call site of this factory
        t = threading.Thread(target=target, args=args,
                             kwargs=kwargs or {}, name=name)
        if daemon is not None:
            t.daemon = daemon
        return t
    return _runtime.make_thread(target, args, kwargs or {}, name, daemon)


# --- shared-access annotations ----------------------------------------------

def note_read(owner: Any, field: str) -> None:
    """Record a read of `owner.field` for race detection. No-op in
    production (one global check); under qwrace the access is stamped
    with the current thread's vector clock, lockset, and call site."""
    if _runtime is not None:
        _runtime.note_access(owner, field, False)


def note_write(owner: Any, field: str) -> None:
    """Record a write of `owner.field` for race detection (see
    `note_read`)."""
    if _runtime is not None:
        _runtime.note_access(owner, field, True)


def register_shared(obj: Any, name: str) -> None:
    """Give `obj` a stable human-readable identity in race reports
    ("WorkerPool#0" instead of an id()). Optional: unregistered owners
    auto-name by type on first noted access."""
    if _runtime is not None:
        _runtime.register_shared(obj, name)
