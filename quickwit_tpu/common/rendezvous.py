"""Rendezvous (highest-random-weight) hashing.

Role of the reference's `quickwit-common/src/rendezvous_hasher.rs`: stable
assignment of a key (split id) to a preference-ordered list of nodes, so that
the same split is searched by the same node across queries (cache affinity)
and reassignment on membership change is minimal.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, TypeVar

T = TypeVar("T")


def _weight(key: str, node: str) -> int:
    h = hashlib.blake2b(f"{key}\x00{node}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "little")


def sort_by_rendezvous_hash(key: str, nodes: Iterable[str]) -> list[str]:
    """Nodes sorted by descending affinity for `key` (ties by node id)."""
    return sorted(nodes, key=lambda node: (-_weight(key, node), node))
