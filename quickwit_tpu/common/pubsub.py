"""In-process typed pub/sub event broker.

Role of the reference's `quickwit-common/src/pubsub.rs`: decoupled event
dissemination between subsystems (e.g. shard-position updates, split report
events). Subscriptions are keyed by event type; handlers run inline or on a
background thread.
"""

from __future__ import annotations

import logging
import threading
from collections import defaultdict
from typing import Any, Callable, Type, TypeVar

logger = logging.getLogger(__name__)

E = TypeVar("E")


class EventSubscriptionHandle:
    def __init__(self, broker: "EventBroker", event_type: type, key: int):
        self._broker = broker
        self._event_type = event_type
        self._key = key

    def cancel(self) -> None:
        self._broker._unsubscribe(self._event_type, self._key)


class EventBroker:
    """Typed pub/sub: subscribe by event class, publish instances."""

    def __init__(self) -> None:
        # qwlint: disable-next-line=QW008 - leaf lock on the subscriber map; no
        # instrumented ops inside
        self._lock = threading.Lock()
        self._subscribers: dict[type, dict[int, Callable[[Any], None]]] = defaultdict(dict)
        self._next_key = 0

    def subscribe(self, event_type: Type[E], handler: Callable[[E], None]) -> EventSubscriptionHandle:
        with self._lock:
            key = self._next_key
            self._next_key += 1
            self._subscribers[event_type][key] = handler
        return EventSubscriptionHandle(self, event_type, key)

    def _unsubscribe(self, event_type: type, key: int) -> None:
        with self._lock:
            self._subscribers.get(event_type, {}).pop(key, None)

    def publish(self, event: Any) -> None:
        with self._lock:
            handlers = list(self._subscribers.get(type(event), {}).values())
        for handler in handlers:
            try:
                handler(event)
            except Exception:  # noqa: BLE001 - subscriber bugs must not kill publishers
                logger.exception("event handler failed for %r", type(event).__name__)
