"""Ambient-context propagation across thread hops.

Queries carry three contextvar bindings — deadline (common/deadline.py),
tenant (tenancy/context.py) and profile (observability/profile.py) — and
Python contextvars do NOT flow into `threading.Thread` targets or
`ThreadPoolExecutor` workers: a bare callable handed across a thread hop
silently drops all of them, so the downstream code sees no deadline (no
shedding), the default tenant (no isolation) and no profile (invisible
phases). Before this module each binding hand-rolled its own wrapper
(`bind_deadline`/`bind_tenant`/`bind_profile`, composed by hand at every
spawn site); `run_with_context` replaces the triple-wrap with ONE
snapshot of *all* contextvars, so a binding added later (e.g. a future
trace-baggage var) propagates without touching any spawn site.

qwlint rule QW003 flags spawn sites that pass bare callables and points
fixes here.
"""

from __future__ import annotations

import contextvars
import functools
from typing import Callable, TypeVar

T = TypeVar("T")


def run_with_context(fn: Callable[..., T],
                     context: "contextvars.Context | None" = None
                     ) -> Callable[..., T]:
    """Wrap `fn` so each invocation runs under a snapshot of the caller's
    contextvars (or an explicit `context`).

    Unlike `Context.run` on a shared snapshot — which raises RuntimeError
    when two threads enter the same Context concurrently — the wrapper
    replays the captured (var, value) pairs into a FRESH Context per
    call, so one wrapped callable can be handed to many threads (hedged
    storage attempts, pool workers) safely. Values are snapshotted at
    wrap time, matching the semantics of the bind_* helpers it replaces.
    """
    snapshot = context if context is not None \
        else contextvars.copy_context()
    items = list(snapshot.items())

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        fresh = contextvars.Context()

        def _replay_and_call():
            for var, value in items:
                var.set(value)
            return fn(*args, **kwargs)

        return fresh.run(_replay_and_call)

    return wrapper
