"""URI abstraction for storage locations.

Role of the reference's `quickwit-common/src/uri.rs`: a normalized URI with an
explicit protocol, used everywhere a storage location is named (index uri,
split files, metastore uri). Supported protocols: ``file``, ``ram``, ``s3``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from enum import Enum


class Protocol(str, Enum):
    FILE = "file"
    RAM = "ram"
    S3 = "s3"
    AZURE = "azure"
    GCS = "gs"

    @property
    def is_object_storage(self) -> bool:
        return self in (Protocol.S3, Protocol.AZURE, Protocol.GCS)


@dataclass(frozen=True)
class Uri:
    protocol: Protocol
    path: str  # path after `<protocol>://`, normalized, no trailing slash

    @staticmethod
    def parse(uri: str) -> "Uri":
        if "://" in uri:
            proto_str, path = uri.split("://", 1)
            try:
                protocol = Protocol(proto_str)
            except ValueError:
                raise ValueError(f"unsupported URI protocol: {proto_str!r} in {uri!r}")
        else:
            # Bare paths are file paths (reference behavior: default protocol file).
            protocol, path = Protocol.FILE, os.path.abspath(uri)
        path = path.rstrip("/")
        if protocol is Protocol.FILE:
            path = os.path.normpath(path)
        return Uri(protocol, path)

    def join(self, *segments: str) -> "Uri":
        for segment in segments:
            if segment.startswith("/"):
                raise ValueError(f"cannot join absolute path segment {segment!r}")
        path = "/".join([self.path, *segments]) if segments else self.path
        return Uri(self.protocol, path)

    def parent(self) -> "Uri | None":
        if "/" not in self.path:
            return None
        return Uri(self.protocol, self.path.rsplit("/", 1)[0])

    @property
    def file_path(self) -> str:
        if self.protocol is not Protocol.FILE:
            raise ValueError(f"not a file uri: {self}")
        return self.path

    def __str__(self) -> str:
        return f"{self.protocol.value}://{self.path}"
