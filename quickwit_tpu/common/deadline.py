"""End-to-end query deadlines and retry budgets.

Role of the reference's request-scoped timeouts (`search_job_placer` retry
budget + per-request tower timeouts): a query enters the cluster with one
wall-clock budget, and every downstream actor — root fan-out, leaf split
groups, the convoy batcher, HBM admission, storage hedging — checks the
*remaining* time instead of holding its own unrelated timeout. On expiry the
query fails partially and on time (`timed_out: true` + per-split errors),
never hangs.

`Deadline` is an absolute point on the monotonic clock; `QueryBudget` couples
a deadline with a bounded retry allowance and exponential backoff capped by
the remaining time. The ambient deadline travels through the stack via a
`contextvars.ContextVar` so deep layers (admission, storage wrappers) need no
signature changes; thread-pool hops must rebind explicitly with
`bind_deadline` because contextvars do not propagate into worker threads.
"""

from __future__ import annotations

import contextvars
import math
import threading
from contextlib import contextmanager
from typing import Callable, Optional

from .clock import get_clock

# Canonical marker for "ran out of time" errors. Split/storage error strings
# embed it so the root can tell deadline failures (-> timed_out partial
# response) apart from query-level failures (-> hard error).
DEADLINE_ERROR_MARK = "deadline exceeded"

# Canonical marker for "explicitly cancelled" errors — the caller asked for
# the query to stop, so the root answers with a typed cancelled/partial
# response instead of a timeout or a hard error.
CANCEL_ERROR_MARK = "query cancelled"


class DeadlineExceeded(Exception):
    """A step was attempted (or abandoned) after the query budget ran out."""

    def __init__(self, operation: str = ""):
        self.operation = operation
        suffix = f" during {operation}" if operation else ""
        super().__init__(f"{DEADLINE_ERROR_MARK}{suffix}")


class CancelledQuery(Exception):
    """The query was explicitly cancelled (REST DELETE, scroll teardown).

    Distinct from `DeadlineExceeded`: a cancel is a *success* of the control
    plane, not a budget failure — the root maps it to a typed
    `cancelled: true` partial response, never a retry."""

    def __init__(self, operation: str = "", reason: str = ""):
        self.operation = operation
        self.reason = reason
        suffix = f" during {operation}" if operation else ""
        why = f": {reason}" if reason else ""
        super().__init__(f"{CANCEL_ERROR_MARK}{suffix}{why}")


def is_deadline_error(message: str) -> bool:
    return DEADLINE_ERROR_MARK in (message or "")


def is_cancel_error(message: str) -> bool:
    return CANCEL_ERROR_MARK in (message or "")


class CancellationToken:
    """One query's cooperative cancel flag.

    Thread-safe and monotonic (once cancelled, forever cancelled). Deep
    layers — the batcher's readback shed, the chunked leaf loop's boundary
    checks — poll `cancelled` / call `check()`; the REST DELETE surface
    flips it from another thread via the query registry. Polling sites are
    read-only on the hot path: a single bool read, no lock."""

    __slots__ = ("_cancelled", "_reason")

    def __init__(self):
        self._cancelled = False
        self._reason = ""

    def cancel(self, reason: str = "") -> None:
        # bool store is atomic under the GIL; last reason wins (benign)
        self._reason = reason
        self._cancelled = True

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    @property
    def reason(self) -> str:
        return self._reason

    def check(self, operation: str = "") -> None:
        if self._cancelled:
            raise CancelledQuery(operation, self._reason)


class Deadline:
    """Absolute expiry instant on the monotonic clock (or unbounded)."""

    __slots__ = ("_expires_at",)

    def __init__(self, expires_at: float):
        self._expires_at = expires_at

    @classmethod
    def after(cls, timeout_secs: float) -> "Deadline":
        return cls(get_clock().monotonic() + max(timeout_secs, 0.0))

    @classmethod
    def never(cls) -> "Deadline":
        return cls(math.inf)

    @classmethod
    def from_millis(cls, timeout_millis: Optional[int]) -> "Deadline":
        """Wire helper: a missing/zero-or-negative budget means unbounded /
        already expired respectively (a leaf receiving `deadline_millis=0`
        must shed immediately, not inherit forever)."""
        if timeout_millis is None:
            return cls.never()
        return cls.after(timeout_millis / 1000.0)

    @property
    def bounded(self) -> bool:
        return self._expires_at != math.inf

    def remaining(self) -> float:
        """Seconds left; `inf` when unbounded, clamped at 0 after expiry."""
        if not self.bounded:
            return math.inf
        return max(self._expires_at - get_clock().monotonic(), 0.0)

    @property
    def expired(self) -> bool:
        return self.bounded and get_clock().monotonic() >= self._expires_at

    def check(self, operation: str = "") -> None:
        if self.expired:
            raise DeadlineExceeded(operation)

    def clamp(self, timeout_secs: Optional[float]) -> Optional[float]:
        """Smallest of `timeout_secs` and the remaining budget; `None` stays
        `None` for unbounded deadlines (blocking-call semantics)."""
        if not self.bounded:
            return timeout_secs
        remaining = self.remaining()
        if timeout_secs is None:
            return remaining
        return min(timeout_secs, remaining)

    def timeout_millis(self) -> Optional[int]:
        """Remaining budget as integer millis for the wire (None = unbounded).

        Serializing the *remaining* time (not the original budget) means root
        queue time is not silently re-granted to the leaf."""
        if not self.bounded:
            return None
        return max(int(self.remaining() * 1000.0), 0)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        if not self.bounded:
            return "Deadline(never)"
        return f"Deadline(remaining={self.remaining():.3f}s)"


class QueryBudget:
    """A deadline plus a bounded, thread-safe retry allowance.

    Retries across a root fan-out share one pool so a query with many failing
    splits cannot amplify into unbounded duplicate work. Backoff is
    exponential from the second retry on (the first retry stays immediate,
    preserving fast single-failure recovery) and always capped by the
    remaining deadline.
    """

    BACKOFF_BASE_SECS = 0.05
    BACKOFF_CAP_SECS = 2.0

    def __init__(self, deadline: Deadline, max_retries: int = 8):
        self.deadline = deadline
        self.max_retries = max_retries
        self._retries_used = 0
        # qwlint: disable-next-line=QW008 - leaf lock over deadline
        # bookkeeping; no instrumented ops inside, so it is never contended
        # under the gated scheduler
        self._lock = threading.Lock()

    @classmethod
    def for_timeout_millis(cls, timeout_millis: Optional[int],
                           max_retries: int = 8) -> "QueryBudget":
        return cls(Deadline.from_millis(timeout_millis), max_retries=max_retries)

    @property
    def retries_used(self) -> int:
        with self._lock:
            return self._retries_used

    def try_acquire_retry(self) -> Optional[int]:
        """Claim one retry slot; returns the 0-based retry index, or None when
        the pool is drained or the deadline has already passed."""
        if self.deadline.expired:
            return None
        with self._lock:
            if self._retries_used >= self.max_retries:
                return None
            index = self._retries_used
            self._retries_used += 1
            return index

    def backoff_secs(self, retry_index: int) -> float:
        """Pre-retry sleep: 0 for the first retry, then exponential, always
        capped by both the ceiling and the remaining budget."""
        if retry_index <= 0:
            return 0.0
        delay = min(self.BACKOFF_BASE_SECS * (2.0 ** (retry_index - 1)),
                    self.BACKOFF_CAP_SECS)
        remaining = self.deadline.remaining()
        if remaining == math.inf:
            return delay
        return min(delay, remaining)

    def sleep_before_retry(self, retry_index: int) -> bool:
        """Sleep the backoff; returns False when the deadline expired (the
        retry should be abandoned)."""
        delay = self.backoff_secs(retry_index)
        if delay > 0.0:
            get_clock().sleep(delay)
        return not self.deadline.expired


# --- ambient propagation --------------------------------------------------

_CURRENT_DEADLINE: contextvars.ContextVar[Optional[Deadline]] = (
    contextvars.ContextVar("quickwit_tpu_deadline", default=None))


def current_deadline() -> Optional[Deadline]:
    """The deadline bound to this thread of execution, if any."""
    return _CURRENT_DEADLINE.get()


@contextmanager
def deadline_scope(deadline: Optional[Deadline]):
    token = _CURRENT_DEADLINE.set(deadline)
    try:
        yield deadline
    finally:
        _CURRENT_DEADLINE.reset(token)


_CURRENT_CANCEL: contextvars.ContextVar[Optional[CancellationToken]] = (
    contextvars.ContextVar("quickwit_tpu_cancel", default=None))


def current_cancel_token() -> Optional[CancellationToken]:
    """The cancellation token bound to this thread of execution, if any."""
    return _CURRENT_CANCEL.get()


@contextmanager
def cancel_scope(token: Optional[CancellationToken]):
    ctx_token = _CURRENT_CANCEL.set(token)
    try:
        yield token
    finally:
        _CURRENT_CANCEL.reset(ctx_token)


def check_cancelled(operation: str = "") -> None:
    """Raise `CancelledQuery` when the ambient token has been cancelled;
    no-op when no token is bound (non-cancellable execution)."""
    token = _CURRENT_CANCEL.get()
    if token is not None:
        token.check(operation)


def bind_deadline(fn: Callable, deadline: Optional[Deadline] = None) -> Callable:
    """Wrap `fn` so it runs under `deadline` (default: the caller's current
    deadline) AND the caller's cancellation token. Needed for
    ThreadPoolExecutor hops — contextvars do not propagate into pool worker
    threads automatically. The cancel token rides along because every hop
    that must honor the deadline must honor an explicit cancel too."""
    captured = deadline if deadline is not None else current_deadline()
    captured_cancel = current_cancel_token()

    def wrapper(*args, **kwargs):
        with deadline_scope(captured), cancel_scope(captured_cancel):
            return fn(*args, **kwargs)

    return wrapper
