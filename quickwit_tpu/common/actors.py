"""Actor runtime: mailboxes, supervision, simulated time.

Role of the reference's `quickwit-actors` crate (`src/actor.rs:101`,
`src/mailbox.rs:46`, `src/supervisor.rs:44`, `src/scheduler.rs:66-130`):
the host-side services (indexing pipelines, janitor, control plane
loops) are single-threaded actors with

- **priority mailboxes**: bounded queues with a high-priority lane
  (supervision/command messages overtake data), where `send` BLOCKS when
  the queue is full — backpressure propagates upstream instead of
  buffering unboundedly;
- **supervision**: a crashed actor (handler exception) is restarted by
  its supervisor with exponential backoff, up to a restart budget, then
  marked failed (the reference's supervision tree);
- **simulated time**: `universe.sleep`/`schedule` run on a virtual
  clock; in accelerated mode (tests) the clock JUMPS to the next
  scheduled deadline whenever every actor is idle, so timeout/retry
  behavior runs in milliseconds (`scheduler.rs:72-130` accelerate_time).

This runtime is deliberately host-side only: the device compute path is
jitted JAX — actors coordinate IO, pipelines, and periodic work around
it.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from .clock import get_clock

logger = logging.getLogger(__name__)

_HIGH = 0
_LOW = 1


class MailboxClosed(RuntimeError):
    pass


class Mailbox:
    """Bounded two-lane queue: high-priority messages overtake low ones
    (reference: `channel_with_priority.rs:118`). `send` blocks when the
    low lane is full — that IS the backpressure mechanism."""

    def __init__(self, name: str, capacity: int = 64,
                 on_activity: Optional[Callable[[int], None]] = None):
        self.name = name
        self._low: "queue.Queue[Any]" = queue.Queue(maxsize=capacity)
        self._high: "queue.Queue[Any]" = queue.Queue()  # never blocks
        # qwlint: disable-next-line=QW008 - actor mailboxes rendezvous through
        # queue.Queue, which the qwrace scheduler cannot see; gating these
        # primitives would stall the gated token on invisible queue waits
        self._closed = threading.Event()
        # qwlint: disable-next-line=QW008 - actor mailboxes rendezvous through
        # queue.Queue, which the qwrace scheduler cannot see; gating these
        # primitives would stall the gated token on invisible queue waits
        self._not_empty = threading.Condition()
        # universe hook counting in-flight messages (idle detection for
        # accelerated time)
        self._on_activity = on_activity or (lambda delta: None)

    def send(self, message: Any, timeout: Optional[float] = None) -> None:
        if self._closed.is_set():
            raise MailboxClosed(self.name)
        self._on_activity(+1)
        try:
            self._low.put(message, timeout=timeout)
        except queue.Full:
            self._on_activity(-1)
            raise
        with self._not_empty:
            self._not_empty.notify()

    def try_send(self, message: Any) -> bool:
        if self._closed.is_set():
            raise MailboxClosed(self.name)
        try:
            self._low.put_nowait(message)
        except queue.Full:
            return False
        self._on_activity(+1)
        with self._not_empty:
            self._not_empty.notify()
        return True

    def send_priority(self, message: Any) -> None:
        """High lane: unbounded, overtakes data messages (supervision and
        commands must reach a backpressured actor)."""
        if self._closed.is_set():
            raise MailboxClosed(self.name)
        self._on_activity(+1)
        self._high.put(message)
        with self._not_empty:
            self._not_empty.notify()

    def recv(self, timeout: Optional[float] = None) -> tuple[int, Any]:
        """(lane, message); raises queue.Empty on timeout, MailboxClosed
        when closed and drained. The queue checks happen while HOLDING the
        condition, so a send's notify cannot slip between a failed check
        and the wait (no lost wakeups, no polling — idle actors sleep the
        full timeout)."""
        deadline = (get_clock().monotonic() + timeout
                    if timeout is not None else None)
        with self._not_empty:
            while True:
                try:
                    return _HIGH, self._high.get_nowait()
                except queue.Empty:
                    pass
                try:
                    return _LOW, self._low.get_nowait()
                except queue.Empty:
                    pass
                if self._closed.is_set():
                    raise MailboxClosed(self.name)
                remaining = (None if deadline is None
                             else deadline - get_clock().monotonic())
                if remaining is not None and remaining <= 0:
                    raise queue.Empty
                self._not_empty.wait(remaining)

    def close(self) -> None:
        self._closed.set()
        with self._not_empty:
            self._not_empty.notify_all()

    def __len__(self) -> int:
        return self._high.qsize() + self._low.qsize()


class Actor:
    """Override `on_message`; optionally `on_start` / `on_exit`.
    `self.universe` / `self.mailbox` are set at spawn."""

    name = "actor"

    def on_start(self) -> None:  # noqa: B027
        pass

    def on_message(self, message: Any) -> None:
        raise NotImplementedError

    def on_exit(self) -> None:  # noqa: B027
        pass


@dataclass
class ActorHandle:
    name: str
    mailbox: Mailbox
    thread: threading.Thread
    state: str = "running"     # running | exited | failed
    restarts: int = 0
    last_error: Optional[BaseException] = None
    _exited: threading.Event = field(default_factory=threading.Event)

    def join(self, timeout: Optional[float] = None) -> None:
        self._exited.wait(timeout)

    def is_healthy(self) -> bool:
        return self.state == "running"


class _Quit:
    pass


class Universe:
    """Actor spawner + virtual clock (reference `Universe`,
    `universe.rs:31`). `accelerated=True` gives tests simulated time:
    whenever every actor is idle and no message is in flight, `now()`
    jumps to the next scheduled deadline."""

    def __init__(self, accelerated: bool = False):
        self.accelerated = accelerated
        self._handles: list[ActorHandle] = []
        # qwlint: disable-next-line=QW008 - actor mailboxes rendezvous through
        # queue.Queue, which the qwrace scheduler cannot see; gating these
        # primitives would stall the gated token on invisible queue waits
        self._lock = threading.Lock()
        self._inflight = 0
        # qwlint: disable-next-line=QW008 - actor mailboxes rendezvous through
        # queue.Queue, which the qwrace scheduler cannot see; gating these
        # primitives would stall the gated token on invisible queue waits
        self._idle = threading.Condition()
        # virtual clock (only consulted in accelerated mode)
        self._virtual_now = 0.0
        self._timers: list[tuple[float, int, Callable[[], None]]] = []
        self._timer_seq = itertools.count()
        # qwlint: disable-next-line=QW008 - actor mailboxes rendezvous through
        # queue.Queue, which the qwrace scheduler cannot see; gating these
        # primitives would stall the gated token on invisible queue waits
        self._stop = threading.Event()
        # qwlint: disable-next-line=QW003 - the universe clock is
        # process-lifetime infrastructure with no query context to carry
        # qwlint: disable-next-line=QW008 - actor mailboxes rendezvous through
        # queue.Queue, which the qwrace scheduler cannot see; gating these
        # primitives would stall the gated token on invisible queue waits
        self._clock_thread = threading.Thread(
            target=self._clock_loop, name="universe-clock", daemon=True)
        self._clock_thread.start()

    # --- time ---------------------------------------------------------
    def now(self) -> float:
        if self.accelerated:
            with self._idle:
                return self._virtual_now
        return get_clock().monotonic()

    def schedule(self, delay: float, callback: Callable[[], None]) -> None:
        """Run `callback` after `delay` (virtual seconds when
        accelerated) — the reference SchedulerClient's schedule_event."""
        with self._idle:
            heapq.heappush(self._timers,
                           (self.now_locked() + delay,
                            next(self._timer_seq), callback))
            self._idle.notify_all()

    def now_locked(self) -> float:
        return (self._virtual_now if self.accelerated
                else get_clock().monotonic())

    def schedule_periodic(self, interval: float,
                          callback: Callable[[], None]) -> None:
        def tick() -> None:
            if self._stop.is_set():
                return
            try:
                callback()
            except Exception:  # noqa: BLE001 - periodic must survive
                logger.exception("periodic task failed")
            self.schedule(interval, tick)

        self.schedule(interval, tick)

    def _clock_loop(self) -> None:
        while not self._stop.is_set():
            with self._idle:
                if not self._timers:
                    # schedule()/quit() notify under this condition, so an
                    # unbounded wait cannot lose a wakeup
                    self._idle.wait(1.0)
                    continue
                deadline, _, callback = self._timers[0]
                now = self.now_locked()
                if now >= deadline:
                    heapq.heappop(self._timers)
                elif self.accelerated and self._inflight == 0 and \
                        all(len(h.mailbox) == 0 for h in self._handles):
                    # system idle: jump the virtual clock (the whole point
                    # of simulated time — timeouts run in microseconds)
                    self._virtual_now = deadline
                    heapq.heappop(self._timers)
                else:
                    # schedule()/quit() notify this condition, so real-time
                    # mode can sleep the full remaining interval; the 1ms
                    # poll exists only for accelerated idle detection
                    self._idle.wait(0.001 if self.accelerated else
                                    min(deadline - now, 5.0))
                    continue
            try:
                callback()
            except Exception:  # noqa: BLE001
                logger.exception("scheduled callback failed")

    # --- activity accounting (idle detection) -------------------------
    def _on_activity(self, delta: int) -> None:
        with self._idle:
            self._inflight += delta
            if self._inflight == 0:
                self._idle.notify_all()

    # --- spawning -----------------------------------------------------
    def spawn(self, actor: Actor, capacity: int = 64,
              supervised: bool = False, max_restarts: int = 3
              ) -> tuple[Mailbox, ActorHandle]:
        mailbox = Mailbox(actor.name, capacity,
                          on_activity=self._on_activity)
        handle = ActorHandle(actor.name, mailbox, thread=None)  # type: ignore[arg-type]

        def run() -> None:
            backoff = 0.1
            current = actor
            while True:
                current.universe = self
                current.mailbox = mailbox
                try:
                    current.on_start()
                    while True:
                        try:
                            _, message = mailbox.recv(timeout=0.5)
                        except queue.Empty:
                            continue
                        except MailboxClosed:
                            break
                        try:
                            if isinstance(message, _Quit):
                                break
                            current.on_message(message)
                        finally:
                            self._on_activity(-1)
                    current.on_exit()
                    handle.state = "exited"
                    break
                except BaseException as exc:  # noqa: BLE001 - supervise
                    handle.last_error = exc
                    if not supervised or handle.restarts >= max_restarts:
                        handle.state = "failed"
                        logger.error("actor %s failed permanently: %s",
                                     actor.name, exc)
                        # drain + close: queued messages must not count as
                        # in-flight forever (they would freeze the
                        # accelerated clock), and draining frees capacity
                        # so a blocked sender unblocks instead of hanging
                        mailbox.close()
                        while True:
                            try:
                                mailbox.recv(timeout=0)
                            except (queue.Empty, MailboxClosed):
                                break
                            self._on_activity(-1)
                        break
                    handle.restarts += 1
                    logger.warning("actor %s crashed (%s); restart #%d",
                                   actor.name, exc, handle.restarts)
                    # accelerated mode: messages queued behind the crash
                    # keep the system non-idle, so a virtual-clock backoff
                    # would deadlock — restart (near-)immediately instead
                    get_clock().sleep(0.001 if self.accelerated else backoff)
                    backoff = min(backoff * 2, 5.0)
            handle._exited.set()

        # qwlint: disable-next-line=QW003 - actor mailbox loops outlive
        # any query; messages carry their own metadata instead
        # qwlint: disable-next-line=QW008 - actor mailboxes rendezvous through
        # queue.Queue, which the qwrace scheduler cannot see; gating these
        # primitives would stall the gated token on invisible queue waits
        thread = threading.Thread(target=run, name=f"actor-{actor.name}",
                                  daemon=True)
        handle.thread = thread
        with self._lock:
            self._handles.append(handle)
        thread.start()
        return mailbox, handle

    # --- lifecycle ----------------------------------------------------
    def quit(self, timeout: float = 5.0) -> None:
        """Graceful: the quit marker rides the LOW lane, so pending data
        messages drain first (the reference's ExitStatus::Success); a
        backpressured mailbox gets the priority lane instead (kill)."""
        for handle in self._handles:
            try:
                if not handle.mailbox.try_send(_Quit()):
                    handle.mailbox.send_priority(_Quit())
            except MailboxClosed:
                pass
        for handle in self._handles:
            handle.join(timeout)
            handle.mailbox.close()
        self._stop.set()
        with self._idle:
            self._idle.notify_all()

    def handles(self) -> list[ActorHandle]:
        with self._lock:
            return list(self._handles)
