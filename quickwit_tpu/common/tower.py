"""Service middleware: rate limiting + circuit breaking.

Role of the reference's tower layer stack (`quickwit-common/src/tower/` —
rate-limit, circuit-breaker, load-shed wrapped around every codegen'd
client): protect services from overload and stop hammering dead peers.
"""

from __future__ import annotations

import threading
from typing import Callable, TypeVar

from .clock import monotonic

T = TypeVar("T")


class RateLimitExceeded(Exception):
    pass


class TokenBucket:
    """Token-bucket rate limiter (reference `tower/rate.rs` /
    `rate_limit.rs`): capacity `burst`, refilled at `rate_per_sec`."""

    def __init__(self, rate_per_sec: float, burst: float,
                 clock=monotonic):
        self.rate = float(rate_per_sec)
        self.burst = float(burst)
        self.clock = clock
        self._tokens = float(burst)
        self._last = clock()
        # qwlint: disable-next-line=QW008 - middleware leaf locks
        # (rate/concurrency counters); no instrumented ops inside their
        # critical sections
        self._lock = threading.Lock()

    def try_acquire(self, cost: float = 1.0) -> bool:
        with self._lock:
            now = self.clock()
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= cost:
                self._tokens -= cost
                return True
            return False

    def time_to_available(self, cost: float = 1.0) -> float:
        """Seconds until `cost` tokens will have refilled — the honest
        value for a 429 `Retry-After` header. Costs above the burst are
        clamped (they can never be fully banked; the caller charges them
        as a full-bucket drain instead)."""
        with self._lock:
            now = self.clock()
            tokens = min(self.burst,
                         self._tokens + (now - self._last) * self.rate)
            needed = min(cost, self.burst) - tokens
            if needed <= 0.0:
                return 0.0
            if self.rate <= 0.0:
                return float("inf")
            return needed / self.rate

    def release(self, cost: float = 1.0) -> None:
        """Refund tokens a failed operation did not really consume."""
        with self._lock:
            self._tokens = min(self.burst, self._tokens + cost)

    def acquire_or_raise(self, cost: float = 1.0) -> None:
        if not self.try_acquire(cost):
            raise RateLimitExceeded(
                f"rate limit exceeded ({self.rate}/s, burst {self.burst})")


class CircuitOpen(Exception):
    pass


class CircuitBreaker:
    """Consecutive-failure circuit breaker (reference
    `tower/circuit_breaker.rs:47`): after `failure_threshold` consecutive
    failures the circuit opens for `cooldown_secs`; the first call after the
    cooldown is the half-open probe."""

    def __init__(self, failure_threshold: int = 5, cooldown_secs: float = 10.0,
                 counts_as_failure: Callable[[BaseException], bool] = None):
        self.failure_threshold = failure_threshold
        self.cooldown_secs = cooldown_secs
        # which exceptions indicate a DEAD peer (connection-level); peer
        # application errors (4xx) must not open the circuit
        self.counts_as_failure = counts_as_failure or (lambda exc: True)
        self._consecutive_failures = 0
        self._opened_at: float | None = None
        # qwlint: disable-next-line=QW008 - middleware leaf locks
        # (rate/concurrency counters); no instrumented ops inside their
        # critical sections
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if monotonic() - self._opened_at >= self.cooldown_secs:
                return "half-open"
            return "open"

    def call(self, fn: Callable[[], T]) -> T:
        with self._lock:
            if self._opened_at is not None:
                if monotonic() - self._opened_at < self.cooldown_secs:
                    raise CircuitOpen(
                        f"circuit open ({self._consecutive_failures} consecutive failures)")
                # half-open: admit a SINGLE probe — re-arm the cooldown so
                # concurrent callers keep failing fast instead of piling
                # timeouts onto a possibly-dead peer
                self._opened_at = monotonic()
        try:
            result = fn()
        except Exception as exc:
            if self.counts_as_failure(exc):
                with self._lock:
                    self._consecutive_failures += 1
                    if self._consecutive_failures >= self.failure_threshold:
                        self._opened_at = monotonic()
            raise
        with self._lock:
            self._consecutive_failures = 0
            self._opened_at = None
        return result
