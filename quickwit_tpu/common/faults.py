"""Seeded, deterministic fault injection for the search path.

Role of chaos harnesses around the reference engine (S3 tail latency, node
loss, slow peers): every robustness claim in `search/root.py` /
`search/service.py` is only as good as the failures it has actually been
driven through. `FaultInjector` perturbs named operations — storage reads,
leaf-search RPCs, batcher dispatches — with latency spikes, typed errors,
and bounded hangs, from a plan keyed by `(seed, operation, occurrence)`.

Determinism contract: the decision for the Nth occurrence of operation `op`
is a pure function of `(seed, op, N)` (derived via blake2b, NOT the salted
builtin `hash()`), so two runs that issue the same per-operation call
sequences see the same failure schedule regardless of thread interleaving
across *different* operations. `schedule()` exposes the fired decisions for
cross-run equality asserts in the chaos suite.
"""

from __future__ import annotations

import hashlib
import random
import threading
from dataclasses import asdict, dataclass, field
from typing import Optional

from ..storage.base import Storage, StorageError
from .clock import get_clock

FAULT_ERROR_MARK = "injected fault"


class InjectedFault(RuntimeError):
    """Typed error raised by an `error`-kind fault rule."""


@dataclass(frozen=True)
class FaultRule:
    """One perturbation: which operations, what kind, how often.

    `operation` matches exactly, or by prefix when it ends with `*`
    (e.g. ``"storage.*"``). `every=N` fires on every Nth occurrence
    (1-based); `probability` fires pseudo-randomly per occurrence; when both
    are set, `every` gates first and `probability` refines. `max_fires`
    bounds total activations (0 = unlimited).
    """

    operation: str
    kind: str  # "latency" | "error" | "hang"
    every: int = 1
    probability: float = 1.0
    latency_secs: float = 0.05
    hang_secs: float = 2.0
    error_message: str = FAULT_ERROR_MARK
    max_fires: int = 0

    def __post_init__(self):
        if self.kind not in ("latency", "error", "hang"):
            raise ValueError(f"unknown fault kind: {self.kind!r}")

    def matches(self, operation: str) -> bool:
        if self.operation.endswith("*"):
            return operation.startswith(self.operation[:-1])
        return operation == self.operation


@dataclass
class FaultDecision:
    operation: str
    occurrence: int  # 1-based, per operation
    rule_index: int
    kind: str


class FaultInjector:
    """Deterministic perturbation engine shared by the wrappers below.

    Thread-safe: per-operation occurrence counters are taken under a lock;
    the decision itself is derived from `(seed, rule, op, occurrence)` only,
    never from global RNG state, so concurrency cannot reorder decisions
    within one operation stream.
    """

    def __init__(self, seed: int, rules: list[FaultRule]):
        self.seed = seed
        self.rules = list(rules)
        # qwlint: disable-next-line=QW008 - fault-injector leaf lock; pure
        # dict/counter ops inside, never a seam primitive
        self._lock = threading.Lock()
        self._occurrences: dict[str, int] = {}
        self._fires_per_rule: list[int] = [0] * len(self.rules)
        self._fired: list[FaultDecision] = []

    def _roll(self, rule_index: int, operation: str, occurrence: int) -> float:
        digest = hashlib.blake2b(
            f"{self.seed}:{rule_index}:{operation}:{occurrence}".encode(),
            digest_size=8).digest()
        return random.Random(int.from_bytes(digest, "big")).random()

    def perturb(self, operation: str) -> None:
        """Apply every matching, firing rule to this occurrence of
        `operation`: sleep for latency/hang kinds, raise for error kinds
        (latency rules are applied before an error rule raises)."""
        with self._lock:
            occurrence = self._occurrences.get(operation, 0) + 1
            self._occurrences[operation] = occurrence
            firing: list[tuple[int, FaultRule]] = []
            for rule_index, rule in enumerate(self.rules):
                if not rule.matches(operation):
                    continue
                if rule.max_fires and self._fires_per_rule[rule_index] >= rule.max_fires:
                    continue
                if rule.every > 1 and occurrence % rule.every != 0:
                    continue
                if rule.probability < 1.0 and (
                        self._roll(rule_index, operation, occurrence)
                        >= rule.probability):
                    continue
                self._fires_per_rule[rule_index] += 1
                self._fired.append(FaultDecision(
                    operation=operation, occurrence=occurrence,
                    rule_index=rule_index, kind=rule.kind))
                firing.append((rule_index, rule))
        for _, rule in firing:
            # fleet-visible audit of what the chaos plan actually did: a
            # soak run's failure counts can be cross-checked against the
            # faults that were really injected
            from ..observability.metrics import FAULTS_INJECTED_TOTAL
            FAULTS_INJECTED_TOTAL.inc(op=operation, kind=rule.kind)
        error: Optional[InjectedFault] = None
        for rule_index, rule in firing:
            # sleeps route through the process clock so the DST harness's
            # virtual clock absorbs them instantly (simulated latency, no
            # wall time) while production/chaos runs really stall
            if rule.kind == "latency":
                get_clock().sleep(rule.latency_secs)
            elif rule.kind == "hang":
                # A bounded stall: long enough that only deadline-aware
                # callers survive it, short enough that test runs terminate.
                get_clock().sleep(rule.hang_secs)
            elif error is None:
                error = InjectedFault(
                    f"{rule.error_message} (op={operation}, n={occurrence})")
        if error is not None:
            raise error

    def occurrences(self, operation: str) -> int:
        with self._lock:
            return self._occurrences.get(operation, 0)

    def to_plan(self) -> dict:
        """Serialize the full injector state — seed, rule set, per-operation
        occurrence cursors, per-rule fire counts — as a JSON-safe dict (the
        `faults` section of a DST replay artifact). `from_plan` restores an
        injector that continues the decision stream exactly where this one
        stands: decisions are pure functions of `(seed, rule, op, occurrence)`,
        so state is nothing but the cursors."""
        with self._lock:
            return {
                "seed": self.seed,
                "rules": [asdict(rule) for rule in self.rules],
                "occurrences": dict(sorted(self._occurrences.items())),
                "fires_per_rule": list(self._fires_per_rule),
            }

    @classmethod
    def from_plan(cls, plan: dict) -> "FaultInjector":
        """Rebuild an injector from `to_plan()` output. A fresh plan (cursors
        all zero) reproduces the original run's schedule from the start; a
        mid-run plan resumes it."""
        injector = cls(
            seed=int(plan["seed"]),
            rules=[FaultRule(**rule) for rule in plan.get("rules", [])])
        with injector._lock:
            injector._occurrences = {
                str(op): int(count)
                for op, count in plan.get("occurrences", {}).items()}
            fires = plan.get("fires_per_rule")
            if fires is not None:
                if len(fires) != len(injector.rules):
                    raise ValueError(
                        "fires_per_rule length does not match rule count")
                injector._fires_per_rule = [int(n) for n in fires]
        return injector

    def schedule(self) -> dict[str, list[tuple[int, int, str]]]:
        """Fired decisions keyed by operation, ordered by occurrence:
        `{op: [(occurrence, rule_index, kind), ...]}`. Two runs with the same
        seed and the same per-operation call sequences produce equal
        schedules — the chaos suite asserts exactly this."""
        with self._lock:
            out: dict[str, list[tuple[int, int, str]]] = {}
            for decision in self._fired:
                out.setdefault(decision.operation, []).append(
                    (decision.occurrence, decision.rule_index, decision.kind))
        for decisions in out.values():
            decisions.sort()
        return out


# --- wrappers -------------------------------------------------------------


class FaultyStorage(Storage):
    """Delegating storage wrapper that perturbs the read path.

    Error-kind faults surface as retryable `StorageError`s so the hedging /
    retry machinery in `storage/wrappers.py` is what gets exercised, exactly
    as with a flaky object store.
    """

    def __init__(self, inner: Storage, injector: FaultInjector,
                 op_prefix: str = "storage"):
        super().__init__(inner.uri)
        self._inner = inner
        self._injector = injector
        self._op_prefix = op_prefix

    def _perturb(self, method: str) -> None:
        try:
            self._injector.perturb(f"{self._op_prefix}.{method}")
        except InjectedFault as exc:
            raise StorageError(str(exc), kind="internal") from exc

    def get_slice(self, path: str, start: int, end: int) -> bytes:
        self._perturb("get_slice")
        return self._inner.get_slice(path, start, end)

    def get_all(self, path: str) -> bytes:
        self._perturb("get_all")
        return self._inner.get_all(path)

    def file_num_bytes(self, path: str) -> int:
        self._perturb("file_num_bytes")
        return self._inner.file_num_bytes(path)

    # mutations and listing pass through unperturbed: the chaos suite targets
    # the search read path, and a faulty put would corrupt fixture setup
    def put(self, path: str, payload: bytes) -> None:
        self._inner.put(path, payload)

    def delete(self, path: str) -> None:
        self._inner.delete(path)

    def bulk_delete(self, paths) -> None:
        self._inner.bulk_delete(paths)

    def list_files(self) -> list[str]:
        return self._inner.list_files()


class FaultyStorageResolver:
    """Resolver shim: wraps every resolved storage in `FaultyStorage` so a
    `SearcherContext` built on it sees injected faults on all split reads."""

    def __init__(self, inner, injector: FaultInjector,
                 op_prefix: str = "storage"):
        self._inner = inner
        self._injector = injector
        self._op_prefix = op_prefix

    def resolve(self, uri) -> Storage:
        return FaultyStorage(self._inner.resolve(uri), self._injector,
                             op_prefix=self._op_prefix)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyMetastore:
    """Metastore wrapper perturbing the root's plan-time reads
    (``metastore.list_splits``, ``metastore.index_metadata``).

    Error-kind faults surface as `MetastoreError` (kind="internal") — the
    typed failure the root's planning path owns; latency/hang faults model a
    slow metastore backend, which the root must absorb into its deadline and
    still answer with a typed partial response. Mutations pass through
    unperturbed (a faulty publish would corrupt fixture setup)."""

    def __init__(self, inner, injector: FaultInjector,
                 op_prefix: str = "metastore"):
        self._inner = inner
        self._injector = injector
        self._op_prefix = op_prefix

    def _perturb(self, method: str) -> None:
        from ..metastore.base import MetastoreError
        try:
            self._injector.perturb(f"{self._op_prefix}.{method}")
        except InjectedFault as exc:
            raise MetastoreError(str(exc), kind="internal") from exc

    def list_splits(self, query):
        self._perturb("list_splits")
        return self._inner.list_splits(query)

    def index_metadata(self, index_id: str):
        self._perturb("index_metadata")
        return self._inner.index_metadata(index_id)

    def list_indexes(self):
        self._perturb("list_indexes")
        return self._inner.list_indexes()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultyClient:
    """Leaf-search client wrapper perturbing RPCs to one node.

    Operations are namespaced per node (``client.leaf_search@node-1``) so a
    rule can fail one replica while its peers stay healthy — the shape of
    real node loss."""

    def __init__(self, inner, injector: FaultInjector, node_id: str):
        self._inner = inner
        self._injector = injector
        self.node_id = node_id

    def leaf_search(self, request):
        self._injector.perturb(f"client.leaf_search@{self.node_id}")
        return self._inner.leaf_search(request)

    def fetch_docs(self, request):
        self._injector.perturb(f"client.fetch_docs@{self.node_id}")
        return self._inner.fetch_docs(request)

    def __getattr__(self, name):
        return getattr(self._inner, name)
