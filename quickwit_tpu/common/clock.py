"""Process-injectable time and randomness seams.

Role of the reference's `quickwit-dst` time virtualization (the fork's
deterministic-simulation harness swaps tokio's clock for a mock one): every
wall-clock read, sleep, and un-seeded random draw on a *cluster path*
(gossip intervals, liveness aging, overload EWMA staleness, autoscaler
cooldowns, metastore polling TTLs, split-id minting, fault-latency sleeps)
routes through the process clock/rng installed here, so the DST harness
(`quickwit_tpu.dst`) can substitute a virtual clock and a seeded RNG and
run hour-long scenarios in milliseconds of wall time — deterministically.

Contract:

- `get_clock()` / `get_rng()` return the process-installed instances;
  the defaults (`SystemClock`, an entropy-seeded `random.Random`) make
  every production path behave byte-for-byte as before the seam existed.
- `set_clock` / `set_rng` swap the process instance and return the
  previous one; `use_clock` / `use_rng` are the context-managed form the
  simulation and tests use (always restores, even on failure).
- Implementations must be thread-safe: cluster paths read the clock from
  fan-out, gossip, and maintenance threads concurrently.

qwlint rule QW006 enforces adoption: direct `time.*` / `random.*` /
`datetime.now()` calls in simulation-scoped modules are findings.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from typing import Iterator, Optional


class Clock:
    """Time source interface. `monotonic()` is the scheduling clock (all
    deadlines, TTLs, and liveness ages compare against it); `time()` is
    the epoch clock (persisted timestamps); `sleep()` blocks the caller;
    `wait(event, timeout)` is `event.wait` routed through the clock so an
    accelerated implementation can compress interval loops."""

    def monotonic(self) -> float:
        raise NotImplementedError

    def time(self) -> float:
        raise NotImplementedError

    def time_ns(self) -> int:
        return int(self.time() * 1e9)

    def sleep(self, secs: float) -> None:
        raise NotImplementedError

    def wait(self, event: threading.Event, timeout: Optional[float]) -> bool:
        return event.wait(timeout)


class SystemClock(Clock):
    """The real clock — production default; behaviorally identical to
    calling the `time` module directly."""

    def monotonic(self) -> float:
        return time.monotonic()

    def time(self) -> float:
        return time.time()

    def time_ns(self) -> int:
        return time.time_ns()

    def sleep(self, secs: float) -> None:
        time.sleep(secs)


class ScaledClock(Clock):
    """Accelerated clock for interval-loop tests (gossip, convergence):
    sleeps and event waits run at `factor` of their requested duration in
    real time, while `monotonic()` reports the FULL requested durations as
    elapsed — so liveness aging, dead_after thresholds, and cooldowns see
    the virtual timeline. A 50ms gossip interval runs in 1ms of wall time
    yet ages peers by the full 50ms.

    Waits that return early (event set) advance virtual time by the real
    elapsed portion only, scaled back up, so a stop() does not fast-forward
    liveness past a peer's death threshold."""

    def __init__(self, factor: float = 0.02):
        if not 0.0 < factor <= 1.0:
            raise ValueError("factor must be in (0, 1]")
        self.factor = float(factor)
        # qwlint: disable-next-line=QW008 - clock infrastructure underpins the
        # seam itself; raw leaf primitive with no instrumented ops inside its
        # critical sections
        self._lock = threading.Lock()
        self._offset = 0.0  # virtual seconds ahead of the real clock

    def monotonic(self) -> float:
        with self._lock:
            return time.monotonic() + self._offset

    def time(self) -> float:
        with self._lock:
            return time.time() + self._offset

    def _advance(self, virtual_elapsed: float, real_elapsed: float) -> None:
        with self._lock:
            self._offset += max(virtual_elapsed - real_elapsed, 0.0)

    def sleep(self, secs: float) -> None:
        real = max(secs, 0.0) * self.factor
        time.sleep(real)
        self._advance(max(secs, 0.0), real)

    def wait(self, event: threading.Event, timeout: Optional[float]) -> bool:
        if timeout is None:
            return event.wait(None)
        start = time.monotonic()
        fired = event.wait(max(timeout, 0.0) * self.factor)
        real = time.monotonic() - start
        # early fire: only the portion actually waited ages the timeline
        virtual = real / self.factor if fired else max(timeout, 0.0)
        self._advance(virtual, real)
        return fired


class FakeClock(Clock):
    """Manually-advanced clock for unit tests: time moves only through
    `advance()` (or `sleep`, which advances by the requested amount and
    returns immediately)."""

    def __init__(self, start: float = 1000.0, epoch: float = 1_600_000_000.0):
        # qwlint: disable-next-line=QW008 - clock infrastructure underpins the
        # seam itself; raw leaf primitive with no instrumented ops inside its
        # critical sections
        self._lock = threading.Lock()
        self._now = float(start)
        self._epoch_skew = float(epoch) - float(start)

    def monotonic(self) -> float:
        with self._lock:
            return self._now

    def time(self) -> float:
        with self._lock:
            return self._now + self._epoch_skew

    def sleep(self, secs: float) -> None:
        self.advance(secs)

    def advance(self, secs: float) -> float:
        with self._lock:
            self._now += max(float(secs), 0.0)
            return self._now

    def wait(self, event: threading.Event, timeout: Optional[float]) -> bool:
        # a timed wait against frozen time: consume the timeout virtually,
        # yield the GIL so other threads progress, report the event state
        if timeout is not None:
            self.advance(timeout)
        time.sleep(0)
        return event.is_set()


_SYSTEM_CLOCK = SystemClock()
# qwlint: disable-next-line=QW008 - clock infrastructure underpins the seam
# itself; raw leaf primitive with no instrumented ops inside its critical
# sections
_clock_lock = threading.Lock()
_process_clock: Clock = _SYSTEM_CLOCK
# default RNG: entropy-seeded, exactly what bare `random.*` calls used
_process_rng: random.Random = random.Random()


def get_clock() -> Clock:
    return _process_clock


def set_clock(clock: Optional[Clock]) -> Clock:
    """Install `clock` process-wide (None restores the system clock);
    returns the previously installed clock."""
    global _process_clock
    with _clock_lock:
        previous = _process_clock
        _process_clock = clock if clock is not None else _SYSTEM_CLOCK
        return previous


@contextmanager
def use_clock(clock: Clock) -> Iterator[Clock]:
    previous = set_clock(clock)
    try:
        yield clock
    finally:
        set_clock(previous)


def get_rng() -> random.Random:
    return _process_rng


def set_rng(rng: Optional[random.Random]) -> random.Random:
    """Install a process RNG (None restores an entropy-seeded one);
    returns the previous instance."""
    global _process_rng
    with _clock_lock:
        previous = _process_rng
        _process_rng = rng if rng is not None else random.Random()
        return previous


@contextmanager
def use_rng(rng: random.Random) -> Iterator[random.Random]:
    previous = set_rng(rng)
    try:
        yield rng
    finally:
        set_rng(previous)


def monotonic() -> float:
    """Shorthand for `get_clock().monotonic()` — the drop-in replacement
    for `time.monotonic()` on simulation-scoped paths."""
    return _process_clock.monotonic()


def wall_time() -> float:
    """Shorthand for `get_clock().time()`."""
    return _process_clock.time()


def sleep(secs: float) -> None:
    """Shorthand for `get_clock().sleep(secs)`."""
    _process_clock.sleep(secs)
