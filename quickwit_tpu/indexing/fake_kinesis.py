"""Wire-accurate in-process Kinesis fake for tests.

Speaks the same x-amz-json-1.1 target protocol the real service does —
ListShards / GetShardIterator / GetRecords — over stdlib HTTP, and
VERIFIES SigV4 request signatures (service "kinesis") with the identical
canonicalization the real endpoint applies, so the client's signing path
is tested end-to-end (the role localstack plays for the reference's
`sqs_tests.rs`). Producer-side helpers (`put_record`) exist for tests;
they are not part of the consumer protocol under test.

Fault injection: `fail_requests` makes the next N calls return 500
(client retry behavior), `empty_pages` forces GetRecords to return empty
pages while behind (Kinesis semantics tests)."""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from ..storage.s3 import _sign


class FakeKinesisServer:
    def __init__(self, access_key: str = "", secret_key: str = "",
                 num_shards: int = 2):
        self.access_key = access_key
        self.secret_key = secret_key
        self.num_shards = num_shards
        # stream -> shard_id -> list[(sequence_number:int, data:bytes)]
        self.streams: dict[str, dict[str, list[tuple[int, bytes]]]] = {}
        self._sequence = 10**20  # realistic magnitude, strictly increasing
        # qwlint: disable-next-line=QW008 - indexing source loops and queue
        # test doubles outside the DST-raced path; rendezvous is
        # uninstrumentable real IO/time
        self.lock = threading.Lock()
        self.request_log: list[str] = []
        self.fail_requests = 0
        self.throttle_requests = 0  # next N calls: throughput-exceeded 400
        self.empty_pages = 0
        self.auth_failures = 0
        self.records_page_limit: Optional[int] = None  # force small pages
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # noqa: D102 - silence
                pass

            def _reply(self, status: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/x-amz-json-1.1")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _check_auth(self, body: bytes) -> bool:
                if not server.secret_key:
                    return True
                auth = self.headers.get("Authorization", "")
                if not auth.startswith("AWS4-HMAC-SHA256 "):
                    return False
                try:
                    fields = dict(
                        part.strip().split("=", 1)
                        for part in auth[len("AWS4-HMAC-SHA256 "):]
                        .split(","))
                    credential = fields["Credential"]
                    signed_headers = fields["SignedHeaders"]
                    signature = fields["Signature"]
                    _akid, datestamp, region, service, _term = \
                        credential.split("/")
                except (KeyError, ValueError):
                    return False
                if service != "kinesis":
                    return False
                names = signed_headers.split(";")
                canonical_headers = "".join(
                    f"{n}:{(self.headers.get(n) or '').strip()}\n"
                    for n in names)
                payload_sha = self.headers.get("x-amz-content-sha256", "")
                canonical_request = "\n".join([
                    "POST", "/", "", canonical_headers, signed_headers,
                    payload_sha])
                scope = f"{datestamp}/{region}/{service}/aws4_request"
                string_to_sign = "\n".join([
                    "AWS4-HMAC-SHA256",
                    self.headers.get("x-amz-date", ""), scope,
                    hashlib.sha256(canonical_request.encode()).hexdigest()])
                key = _sign(f"AWS4{server.secret_key}".encode(), datestamp)
                key = _sign(key, region)
                key = _sign(key, service)
                key = _sign(key, "aws4_request")
                expected = hmac.new(key, string_to_sign.encode(),
                                    hashlib.sha256).hexdigest()
                if not hmac.compare_digest(expected, signature):
                    server.auth_failures += 1
                    return False
                if hashlib.sha256(body).hexdigest() != payload_sha:
                    server.auth_failures += 1
                    return False
                return True

            def do_POST(self):  # noqa: N802 - stdlib naming
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                target = self.headers.get("X-Amz-Target", "")
                action = target.split(".")[-1]
                with server.lock:
                    server.request_log.append(action)
                    if server.fail_requests > 0:
                        server.fail_requests -= 1
                        return self._reply(500, {
                            "__type": "InternalFailure"})
                    if server.throttle_requests > 0:
                        server.throttle_requests -= 1
                        return self._reply(400, {
                            "__type": "ProvisionedThroughputExceeded"
                                      "Exception",
                            "message": "Rate exceeded"})
                if not self._check_auth(body):
                    return self._reply(400, {
                        "__type": "IncompleteSignatureException",
                        "message": "signature mismatch"})
                try:
                    payload = json.loads(body) if body else {}
                except ValueError:
                    return self._reply(400, {
                        "__type": "SerializationException"})
                handler = getattr(server, f"_api_{action}", None)
                if handler is None:
                    return self._reply(400, {
                        "__type": "UnknownOperationException",
                        "message": f"unknown action {action!r}"})
                try:
                    with server.lock:
                        out = handler(payload)
                except KeyError as exc:
                    return self._reply(400, {
                        "__type": "ResourceNotFoundException",
                        "message": str(exc)})
                except ValueError as exc:
                    return self._reply(400, {
                        "__type": "InvalidArgumentException",
                        "message": str(exc)})
                return self._reply(200, out)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self._httpd.server_port}"

    def start(self) -> "FakeKinesisServer":
        # qwlint: disable-next-line=QW003 - test-double HTTP server; no
        # query context exists on this path
        # qwlint: disable-next-line=QW008 - indexing source loops and queue
        # test doubles outside the DST-raced path; rendezvous is
        # uninstrumentable real IO/time
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- producer-side test helpers ----------------------------------------
    def create_stream(self, stream: str,
                      num_shards: Optional[int] = None) -> None:
        with self.lock:
            shards = num_shards or self.num_shards
            self.streams[stream] = {
                f"shardId-{i:012d}": [] for i in range(shards)}

    def add_shard(self, stream: str) -> str:
        """Simulate a scale-up reshard: one more shard appears."""
        with self.lock:
            shards = self.streams[stream]
            shard_id = f"shardId-{len(shards):012d}"
            shards[shard_id] = []
            return shard_id

    def put_record(self, stream: str, data: bytes,
                   shard: Optional[int] = None) -> str:
        """Append one record; returns its sequence number. Without an
        explicit shard, records round-robin (test determinism beats the
        real service's partition-key hashing here)."""
        with self.lock:
            shards = self.streams[stream]
            shard_ids = sorted(shards)
            if shard is None:
                shard = sum(len(r) for r in shards.values()) % len(shard_ids)
            self._sequence += 1
            shards[shard_ids[shard]].append((self._sequence, data))
            return str(self._sequence)

    # -- the consumer APIs --------------------------------------------------
    def _api_ListShards(self, payload: dict) -> dict:  # noqa: N802
        stream = payload.get("StreamName")
        if stream not in self.streams:
            raise KeyError(f"stream {stream!r} not found")
        return {"Shards": [{"ShardId": sid}
                           for sid in sorted(self.streams[stream])]}

    def _api_GetShardIterator(self, payload: dict) -> dict:  # noqa: N802
        stream = payload["StreamName"]
        shard_id = payload["ShardId"]
        if shard_id not in self.streams.get(stream, {}):
            raise KeyError(f"shard {shard_id!r} not found")
        kind = payload["ShardIteratorType"]
        if kind == "TRIM_HORIZON":
            after = 0
        elif kind == "AFTER_SEQUENCE_NUMBER":
            after = int(payload["StartingSequenceNumber"])
        elif kind == "AT_SEQUENCE_NUMBER":
            after = int(payload["StartingSequenceNumber"]) - 1
        elif kind == "LATEST":
            records = self.streams[stream][shard_id]
            after = records[-1][0] if records else 0
        else:
            raise ValueError(f"iterator type {kind!r} not supported")
        token = base64.b64encode(json.dumps(
            {"s": stream, "h": shard_id, "a": after}).encode()).decode()
        return {"ShardIterator": token}

    def _api_GetRecords(self, payload: dict) -> dict:  # noqa: N802
        token = json.loads(base64.b64decode(payload["ShardIterator"]))
        limit = int(payload.get("Limit", 10_000))
        if self.records_page_limit is not None:
            limit = min(limit, self.records_page_limit)
        records = self.streams[token["s"]][token["h"]]
        pending = [(seq, data) for seq, data in records
                   if seq > token["a"]]
        if self.empty_pages > 0 and pending:
            self.empty_pages -= 1
            page = []
        else:
            page = pending[:limit]
        last = page[-1][0] if page else token["a"]
        next_token = base64.b64encode(json.dumps(
            {"s": token["s"], "h": token["h"], "a": last}).encode()).decode()
        behind = len(pending) - len(page)
        return {
            "Records": [{
                "SequenceNumber": str(seq),
                "Data": base64.b64encode(data).decode(),
                "ApproximateArrivalTimestamp": 0,
                "PartitionKey": "pk",
            } for seq, data in page],
            "NextShardIterator": next_token,
            "MillisBehindLatest": 1000 if behind > 0 else 0,
        }
