"""SQS file-notification source speaking the real SQS JSON API.

Role of the reference's queue-source framework
(`quickwit-indexing/src/source/queue_sources/coordinator.rs:1`, the SQS
notification source): queue messages carry OBJECT NOTIFICATIONS (S3
event records or raw object URIs); the source fetches each notified
file through the storage layer, indexes its ndjson rows, and the file
URI becomes a checkpoint partition at EOF — at-least-once queue
delivery + checkpoint dedupe = exactly-once indexing, exactly the
reference's `QueueSharedState` design.

Message acknowledgment is garbage collection, not correctness: a
message is deleted only once the checkpoint PROVES its file published
(so a crash between indexing and deleting re-delivers the message, the
checkpoint shows the file done, and the message is deleted then). The
visibility timeout is the redelivery mechanism; no state lives in SQS.

Wire protocol: the AmazonSQS JSON target protocol (x-amz-json-1.0 +
SigV4, shared `AwsJsonClient` machinery) — ReceiveMessage /
DeleteMessageBatch; no SDK.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Iterator, Optional

from ..storage.s3 import S3Config
from .aws_json import AwsApiError, AwsJsonClient  # noqa: F401 - AwsApiError re-exported

logger = logging.getLogger(__name__)

# checkpoint position for a fully-indexed file, mirroring the
# reference's Position::Eof — padded above 20 chars so it orders AFTER
# every intermediate "%020d" chunk position under the checkpoint's
# (length, lexicographic) ordering
EOF_POSITION = "~" * 20 + "eof"


class SqsError(AwsApiError):
    pass


class SqsWireClient(AwsJsonClient):
    service = "sqs"
    target_prefix = "AmazonSQS"
    content_type = "application/x-amz-json-1.0"
    retryable_types = ("RequestThrottled",
                       "OverLimit")
    error_class = SqsError

    def receive(self, queue_url: str, max_messages: int = 10
                ) -> list[dict[str, Any]]:
        out = self.call("ReceiveMessage", {
            "QueueUrl": queue_url,
            "MaxNumberOfMessages": max(1, min(max_messages, 10)),
            "WaitTimeSeconds": 0,
        })
        return out.get("Messages", []) or []

    def delete_batch(self, queue_url: str,
                     handles: list[tuple[str, str]]) -> None:
        """handles: (message_id, receipt_handle) pairs, ≤10 per call.
        Deduplicated by message id — SQS rejects a whole batch whose
        entry Ids are not distinct."""
        unique = list({message_id: (message_id, handle)
                       for message_id, handle in handles}.values())
        for i in range(0, len(unique), 10):
            chunk = unique[i:i + 10]
            self.call("DeleteMessageBatch", {
                "QueueUrl": queue_url,
                "Entries": [{"Id": message_id, "ReceiptHandle": handle}
                            for message_id, handle in chunk],
            })


def notified_uris(body: str) -> list[str]:
    """Object URIs out of one message body: an S3 event notification
    (Records[].s3.bucket/object), an SNS envelope wrapping one, or a raw
    URI per line (the reference accepts raw paths too)."""
    try:
        payload = json.loads(body)
    except ValueError:
        payload = None
    if isinstance(payload, dict):
        if "Records" not in payload and isinstance(payload.get("Message"),
                                                   str):
            return notified_uris(payload["Message"])  # SNS envelope
        uris = []
        for record in payload.get("Records", []):
            s3 = record.get("s3") or {}
            bucket = (s3.get("bucket") or {}).get("name")
            key = (s3.get("object") or {}).get("key")
            if bucket and key:
                from urllib.parse import unquote_plus
                uris.append(f"s3://{bucket}/{unquote_plus(key)}")
        return uris
    return [line.strip() for line in body.splitlines() if line.strip()]


class SqsFileSource:
    """Checkpointed SQS notification source. Each notified file is a
    checkpoint partition; its position jumps BEGINNING → EOF when its
    rows publish. Bounded work per pass: at most `max_messages_per_pass`
    messages are received per batches() call."""

    def __init__(self, endpoint: str, queue_url: str, config: S3Config,
                 resolver=None, max_messages_per_pass: int = 50):
        self.queue_url = queue_url
        self.client = SqsWireClient(endpoint, config)
        from ..storage.base import StorageResolver
        self.resolver = resolver or StorageResolver.default()
        self.max_messages_per_pass = max_messages_per_pass
        # message_id -> (receipt_handle, {file uris}): a message deletes
        # only once EVERY file it notified reaches EOF in the checkpoint
        # (a multi-file message must not lose a sibling whose indexing is
        # still pending)
        self._pending_acks: dict[str, tuple[str, set]] = {}

    def close(self) -> None:
        self.client.close()

    def partition_ids(self) -> list[str]:
        return []  # partitions materialize per notified file

    def _read_file(self, uri: str) -> "Optional[list[dict]]":
        from ..common.uri import Uri
        try:
            parsed = Uri.parse(uri)
            parent, _, name = uri.rpartition("/")
            storage = self.resolver.resolve(parent or str(parsed))
            raw = storage.get_all(name)
        except Exception as exc:  # noqa: BLE001 - poisoned notification
            logger.warning("sqs-notified file %s unreadable: %s", uri, exc)
            return None
        docs = []
        for line in raw.splitlines():
            if not line.strip():
                continue
            try:
                docs.append(json.loads(line))
            except ValueError:
                docs.append({"_malformed":
                             line.decode("utf-8", "replace")
                             if isinstance(line, bytes) else line})
        return docs

    def _ack_published(self, checkpoint) -> None:
        done = [
            (message_id, handle)
            for message_id, (handle, uris) in self._pending_acks.items()
            if all(checkpoint.position_for(uri) == EOF_POSITION
                   for uri in uris)
        ]
        if done:
            self.client.delete_batch(self.queue_url, done)
            for message_id, _h in done:
                self._pending_acks.pop(message_id, None)

    def batches(self, checkpoint, batch_num_docs: int = 10_000
                ) -> Iterator[Any]:
        from ..metastore.checkpoint import BEGINNING, CheckpointDelta
        from .sources import SourceBatch

        # garbage-collect messages whose files a PREVIOUS pass published
        # (ack-after-publish: the checkpoint is the proof)
        self._ack_published(checkpoint)

        received = 0
        immediate_deletes: list[tuple[str, str]] = []
        # per-PASS emit guard: a message redelivered within one pass must
        # not double-yield a file. ACROSS passes the checkpoint governs —
        # a file yielded but never published (failed pipeline pass) re-
        # emits safely because nothing was applied.
        emitted: set[str] = set()
        while received < self.max_messages_per_pass:
            messages = self.client.receive(
                self.queue_url,
                min(10, self.max_messages_per_pass - received))
            if not messages:
                break
            received += len(messages)
            for message in messages:
                message_id = message.get("MessageId", "")
                receipt = message.get("ReceiptHandle", "")
                uris = notified_uris(message.get("Body", ""))
                if not uris:
                    # no object notifications at all (s3:TestEvent and
                    # the like): delete, or it redelivers forever and
                    # starves real notifications out of the receive slots
                    immediate_deletes.append((message_id, receipt))
                    continue
                tracked = False
                for uri in uris:
                    position = checkpoint.position_for(uri)
                    if position == EOF_POSITION or uri in emitted:
                        continue  # published, or yielded this pass
                    docs = self._read_file(uri)
                    if docs is None:
                        continue  # unreadable: visibility timeout retries
                    emitted.add(uri)
                    if not tracked:
                        self._pending_acks[message_id] = (receipt,
                                                          set(uris))
                        tracked = True
                    # crash-mid-file resume: an intermediate "%020d"
                    # position is the doc offset to continue from
                    start0 = 0 if position == BEGINNING else int(position)
                    for start in range(start0, max(len(docs), start0 + 1),
                                       batch_num_docs):
                        chunk = docs[start:start + batch_num_docs]
                        is_last = start + batch_num_docs >= len(docs)
                        delta = CheckpointDelta.from_range(
                            uri, BEGINNING if start == 0
                            else f"{start:020d}",
                            EOF_POSITION if is_last
                            else f"{start + batch_num_docs:020d}")
                        yield SourceBatch(chunk, delta)
                if not tracked and all(
                        checkpoint.position_for(u) == EOF_POSITION
                        for u in uris):
                    # crash-after-publish replay: every file in this
                    # message is provably published — delete it now
                    immediate_deletes.append((message_id, receipt))
        if immediate_deletes:
            self.client.delete_batch(self.queue_url, immediate_deletes)
        # files that published DURING this pass ack on the NEXT pass
        # (the checkpoint object is the pass-start snapshot; the metastore
        # applied the deltas at publish time)
