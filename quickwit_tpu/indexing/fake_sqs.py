"""Wire-accurate in-process SQS fake for tests (the localstack role the
reference's `sqs_tests.rs` plays). Speaks the AmazonSQS x-amz-json-1.0
target protocol — ReceiveMessage / DeleteMessageBatch — with SigV4
verification (service "sqs") via the same canonicalization the real
endpoint applies, plus visibility-timeout semantics so redelivery paths
are testable."""

from __future__ import annotations

import hashlib
import hmac
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..storage.s3 import _sign


class FakeSqsServer:
    def __init__(self, access_key: str = "", secret_key: str = "",
                 visibility_timeout: float = 30.0):
        self.access_key = access_key
        self.secret_key = secret_key
        self.visibility_timeout = visibility_timeout
        # message_id -> {"body", "receipt", "invisible_until"}
        self.messages: dict[str, dict] = {}
        self.deleted: list[str] = []
        # qwlint: disable-next-line=QW008 - indexing source loops and queue
        # test doubles outside the DST-raced path; rendezvous is
        # uninstrumentable real IO/time
        self.lock = threading.Lock()
        self.request_log: list[str] = []
        self.fail_requests = 0
        self.auth_failures = 0
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # noqa: D102 - silence
                pass

            def _reply(self, status: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type",
                                 "application/x-amz-json-1.0")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _check_auth(self, body: bytes) -> bool:
                if not server.secret_key:
                    return True
                auth = self.headers.get("Authorization", "")
                if not auth.startswith("AWS4-HMAC-SHA256 "):
                    return False
                try:
                    fields = dict(
                        part.strip().split("=", 1)
                        for part in auth[len("AWS4-HMAC-SHA256 "):]
                        .split(","))
                    credential = fields["Credential"]
                    signed_headers = fields["SignedHeaders"]
                    signature = fields["Signature"]
                    _akid, datestamp, region, service, _term = \
                        credential.split("/")
                except (KeyError, ValueError):
                    return False
                if service != "sqs":
                    return False
                names = signed_headers.split(";")
                canonical_headers = "".join(
                    f"{n}:{(self.headers.get(n) or '').strip()}\n"
                    for n in names)
                payload_sha = self.headers.get("x-amz-content-sha256", "")
                canonical_request = "\n".join([
                    "POST", "/", "", canonical_headers, signed_headers,
                    payload_sha])
                scope = f"{datestamp}/{region}/{service}/aws4_request"
                string_to_sign = "\n".join([
                    "AWS4-HMAC-SHA256",
                    self.headers.get("x-amz-date", ""), scope,
                    hashlib.sha256(canonical_request.encode()).hexdigest()])
                key = _sign(f"AWS4{server.secret_key}".encode(), datestamp)
                key = _sign(key, region)
                key = _sign(key, service)
                key = _sign(key, "aws4_request")
                expected = hmac.new(key, string_to_sign.encode(),
                                    hashlib.sha256).hexdigest()
                if not hmac.compare_digest(expected, signature) \
                        or hashlib.sha256(body).hexdigest() != payload_sha:
                    server.auth_failures += 1
                    return False
                return True

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                action = self.headers.get("X-Amz-Target",
                                          "").split(".")[-1]
                with server.lock:
                    server.request_log.append(action)
                    if server.fail_requests > 0:
                        server.fail_requests -= 1
                        return self._reply(500, {"__type": "InternalFailure"})
                if not self._check_auth(body):
                    return self._reply(400, {
                        "__type": "IncompleteSignatureException",
                        "message": "signature mismatch"})
                payload = json.loads(body) if body else {}
                handler = getattr(server, f"_api_{action}", None)
                if handler is None:
                    return self._reply(400, {
                        "__type": "UnknownOperationException",
                        "message": f"unknown action {action!r}"})
                with server.lock:
                    out = handler(payload)
                return self._reply(200, out)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self._httpd.server_port}"

    @property
    def queue_url(self) -> str:
        return f"{self.endpoint}/000000000000/test-queue"

    def start(self) -> "FakeSqsServer":
        # qwlint: disable-next-line=QW003 - test-double HTTP server; no
        # query context exists on this path
        # qwlint: disable-next-line=QW008 - indexing source loops and queue
        # test doubles outside the DST-raced path; rendezvous is
        # uninstrumentable real IO/time
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- producer-side test helper -----------------------------------------
    def send_message(self, body: str) -> str:
        with self.lock:
            message_id = uuid.uuid4().hex
            self.messages[message_id] = {
                "body": body, "receipt": uuid.uuid4().hex,
                "invisible_until": 0.0}
            return message_id

    def visible_count(self) -> int:
        with self.lock:
            return len(self.messages)

    def make_visible_all(self) -> None:
        """Test seam: expire every in-flight visibility timeout (what
        wall-clock passage does on the real service)."""
        with self.lock:
            for m in self.messages.values():
                m["invisible_until"] = 0.0

    # -- consumer APIs -------------------------------------------------------
    def _api_ReceiveMessage(self, payload: dict) -> dict:  # noqa: N802
        now = time.monotonic()
        limit = int(payload.get("MaxNumberOfMessages", 1))
        out = []
        for message_id, m in self.messages.items():
            if m["invisible_until"] > now:
                continue
            m["invisible_until"] = now + self.visibility_timeout
            m["receipt"] = uuid.uuid4().hex  # fresh handle per delivery
            out.append({"MessageId": message_id,
                        "ReceiptHandle": m["receipt"],
                        "Body": m["body"]})
            if len(out) >= limit:
                break
        return {"Messages": out}

    def _api_DeleteMessageBatch(self, payload: dict) -> dict:  # noqa: N802
        successful, failed = [], []
        for entry in payload.get("Entries", []):
            message_id = entry["Id"]
            m = self.messages.get(message_id)
            if m is not None and m["receipt"] == entry.get("ReceiptHandle"):
                del self.messages[message_id]
                self.deleted.append(message_id)
                successful.append({"Id": message_id})
            else:
                failed.append({"Id": message_id, "Code": "ReceiptHandleIsInvalid",
                               "SenderFault": True})
        return {"Successful": successful, "Failed": failed}
