"""In-process Kafka broker speaking the wire protocol the source
consumes — the test double for `kafka.py` (same role as
`storage/fake_s3.py` for the S3 backend: the seam is exercised over a
REAL socket with REAL wire bytes, not a mock).

Serves ApiVersions v0, Metadata v0-1, ListOffsets v0-1, Fetch v0-4 from
an in-memory {topic: [partition logs]} store. Also accepts Produce-less
test seeding via `seed()`. Fault injection: `fail_next_fetches` makes
the next N Fetch responses return a retryable error code.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from .kafka import EARLIEST, _Reader, _str, encode_record_batch


class FakeKafkaBroker:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 node_id: int = 0):
        self._topics: dict[str, list[list[bytes]]] = {}
        self._batches: dict[tuple[str, int], list[tuple[int, bytes]]] = {}
        # qwlint: disable-next-line=QW008 - indexing source loops and queue
        # test doubles outside the DST-raced path; rendezvous is
        # uninstrumentable real IO/time
        self._lock = threading.Lock()
        self.fail_next_fetches = 0
        self.node_id = node_id
        # multi-broker simulation: peers listed in metadata, and
        # partitions whose leader is another node — this broker then
        # refuses their Fetch/ListOffsets with NOT_LEADER
        self.peer_brokers: list["FakeKafkaBroker"] = []
        self.partition_leaders: dict[tuple[str, int], int] = {}
        self._truncated: dict[tuple[str, int], int] = {}
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(8)
        self.host, self.port = self._server.getsockname()
        self._running = True
        # qwlint: disable-next-line=QW003 - test-double broker accept
        # loop; serves no quickwit_tpu queries
        # qwlint: disable-next-line=QW008 - indexing source loops and queue
        # test doubles outside the DST-raced path; rendezvous is
        # uninstrumentable real IO/time
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    # -- test API
    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            self._topics[topic] = [[] for _ in range(partitions)]

    def seed(self, topic: str, partition: int, values: list[bytes]) -> None:
        """Append records (the producer side of the seam)."""
        with self._lock:
            log = self._topics[topic][partition]
            base = len(log)
            log.extend(values)
            self._batches.setdefault((topic, partition), []).append(
                (base, encode_record_batch(base, values)))

    def truncate_before(self, topic: str, partition: int,
                        offset: int) -> None:
        """Simulate retention: offsets below `offset` are gone; fetches
        below it return OFFSET_OUT_OF_RANGE."""
        with self._lock:
            self._truncated[(topic, partition)] = offset
            self._batches[(topic, partition)] = [
                (base, data) for base, data
                in self._batches.get((topic, partition), [])
                if base >= offset]

    def stop(self) -> None:
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass

    # -- server loop
    def _serve(self) -> None:
        while self._running:
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return
            # qwlint: disable-next-line=QW003 - test-double connection
            # handler; no query context exists on this path
            # qwlint: disable-next-line=QW008 - indexing source loops and queue
            # test doubles outside the DST-raced path; rendezvous is
            # uninstrumentable real IO/time
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            while True:
                size_raw = self._read_exact(conn, 4)
                if size_raw is None:
                    return
                size = struct.unpack(">i", size_raw)[0]
                frame = self._read_exact(conn, size)
                if frame is None:
                    return
                r = _Reader(frame)
                api_key = r.i16()
                api_version = r.i16()
                correlation = r.i32()
                r.string()  # client_id
                body = self._dispatch(api_key, api_version, r)
                response = struct.pack(">i", correlation) + body
                conn.sendall(struct.pack(">i", len(response)) + response)
        except (OSError, EOFError):
            pass
        finally:
            conn.close()

    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        chunks = bytearray()
        while len(chunks) < n:
            chunk = conn.recv(n - len(chunks))
            if not chunk:
                return None
            chunks += chunk
        return bytes(chunks)

    # -- API handlers
    def _dispatch(self, api_key: int, api_version: int, r: _Reader) -> bytes:
        if api_key == 18:
            return self._api_versions()
        if api_key == 3:
            return self._metadata(r, api_version)
        if api_key == 2:
            return self._list_offsets(r)
        if api_key == 1:
            return self._fetch(r)
        # UNSUPPORTED_VERSION
        return struct.pack(">h", 35)

    def _api_versions(self) -> bytes:
        supported = [(18, 0, 0), (3, 0, 1), (2, 0, 1), (1, 0, 4)]
        out = struct.pack(">h", 0) + struct.pack(">i", len(supported))
        for key, lo, hi in supported:
            out += struct.pack(">hhh", key, lo, hi)
        return out

    def _metadata(self, r: _Reader, version: int) -> bytes:
        count = r.i32()
        with self._lock:
            names = (list(self._topics) if count < 0 else
                     [r.string() for _ in range(count)])
            brokers = [(self.node_id, self.host, self.port)] + [
                (b.node_id, b.host, b.port) for b in self.peer_brokers]
            out = struct.pack(">i", len(brokers))
            for node_id, host, port in brokers:
                out += struct.pack(">i", node_id) + _str(host) \
                    + struct.pack(">i", port)
                if version >= 1:
                    out += _str(None)            # rack
            if version >= 1:
                out += struct.pack(">i", self.node_id)  # controller_id
            out += struct.pack(">i", len(names))
            for name in names:
                exists = name in self._topics
                out += struct.pack(">h", 0 if exists else 3)  # UNKNOWN_TOPIC
                out += _str(name)
                if version >= 1:
                    out += struct.pack(">b", 0)  # is_internal
                partitions = self._topics.get(name, [])
                out += struct.pack(">i", len(partitions))
                for index in range(len(partitions)):
                    leader = self.partition_leaders.get(
                        (name, index), self.node_id)
                    out += struct.pack(">hiii", 0, index, leader, 1)
                    out += struct.pack(">i", leader)        # replicas [leader]
                    out += struct.pack(">ii", 1, leader)    # isr [leader]
            return out

    def _list_offsets(self, r: _Reader) -> bytes:
        r.i32()  # replica_id
        out_topics = []
        for _ in range(r.i32()):
            topic = r.string()
            partitions = []
            for _ in range(r.i32()):
                partition = r.i32()
                timestamp = r.i64()
                with self._lock:
                    log = self._topics.get(topic, [])
                    if partition >= len(log):
                        partitions.append((partition, 3, -1))
                        continue
                    if self.partition_leaders.get(
                            (topic, partition), self.node_id) != self.node_id:
                        partitions.append((partition, 6, -1))  # NOT_LEADER
                        continue
                    floor = self._truncated.get((topic, partition), 0)
                    offset = (floor if timestamp == EARLIEST
                              else len(log[partition]))
                partitions.append((partition, 0, offset))
            out_topics.append((topic, partitions))
        out = struct.pack(">i", len(out_topics))
        for topic, partitions in out_topics:
            out += _str(topic) + struct.pack(">i", len(partitions))
            for partition, error, offset in partitions:
                out += struct.pack(">ihqq", partition, error, -1, offset)
        return out

    def _fetch(self, r: _Reader) -> bytes:
        r.i32()  # replica_id
        r.i32()  # max_wait
        r.i32()  # min_bytes
        r.i32()  # max_bytes
        r.i8()   # isolation_level
        out_topics = []
        for _ in range(r.i32()):
            topic = r.string()
            partitions = []
            for _ in range(r.i32()):
                partition = r.i32()
                fetch_offset = r.i64()
                r.i32()  # partition max_bytes
                with self._lock:
                    if self.fail_next_fetches > 0:
                        self.fail_next_fetches -= 1
                        partitions.append((partition, 6, 0, b""))  # NOT_LEADER
                        continue
                    log = self._topics.get(topic, [])
                    if partition >= len(log):
                        partitions.append((partition, 3, 0, b""))
                        continue
                    if self.partition_leaders.get(
                            (topic, partition), self.node_id) != self.node_id:
                        partitions.append((partition, 6, 0, b""))
                        continue
                    if fetch_offset < self._truncated.get(
                            (topic, partition), 0):
                        partitions.append((partition, 1, 0, b""))  # OOR
                        continue
                    high = len(log[partition])
                    record_set = b"".join(
                        data for base, data
                        in self._batches.get((topic, partition), [])
                        if base + _batch_len(data) > fetch_offset)
                partitions.append((partition, 0, high, record_set))
            out_topics.append((topic, partitions))
        out = struct.pack(">i", 0)  # throttle
        out += struct.pack(">i", len(out_topics))
        for topic, partitions in out_topics:
            out += _str(topic) + struct.pack(">i", len(partitions))
            for partition, error, high, record_set in partitions:
                out += struct.pack(">ihqq", partition, error, high, high)
                out += struct.pack(">i", 0)  # aborted txns
                out += struct.pack(">i", len(record_set)) + record_set
        return out


def _batch_len(batch_data: bytes) -> int:
    """Number of records in one encoded batch (trailing numRecords of the
    fixed header)."""
    # header: baseOffset(8) batchLength(4) leaderEpoch(4) magic(1) crc(4)
    # attributes(2) lastOffsetDelta(4) ... numRecords at offset 57-4? Use
    # lastOffsetDelta + 1 at fixed offset 23.
    last_offset_delta = struct.unpack_from(">i", batch_data, 23)[0]
    return last_offset_delta + 1
