"""Kinesis source speaking the real Kinesis JSON API on stdlib HTTP.

Role of the reference's Kinesis source
(`quickwit-indexing/src/source/kinesis/kinesis_source.rs`): consume doc
batches from Kinesis stream shards with per-shard checkpoint positions
flowing through the exactly-once `CheckpointDelta` publish protocol. This
build has no AWS SDK, so the API itself is implemented here — the
x-amz-json-1.1 target protocol with SigV4 (service "kinesis", reusing the
canonical signer from storage/s3.py) over persistent stdlib HTTP
connections:

  ListShards · GetShardIterator · GetRecords

Positions come from OUR metastore checkpoint (never Kinesis consumer
state), exactly like the reference: the `SourceCheckpoint` stores each
shard's last-processed sequence number and replays from
AFTER_SEQUENCE_NUMBER on any crash, making Kinesis→split ingestion
exactly-once (`checkpoint.rs:30`). Sequence numbers are decimal strings;
the checkpoint's (length, lexicographic) position ordering sorts them
numerically — the same encoding the reference uses.

Scope note: parent/child shard lineage after a reshard is consumed as a
flat shard list (each shard keeps its own checkpoint partition); strict
parent-before-child ordering is not enforced.
"""

from __future__ import annotations

import json
import time
from typing import Any, Iterator, Optional

from ..storage.s3 import S3Config
from .aws_json import AwsApiError, AwsJsonClient  # noqa: F401 - AwsApiError re-exported for callers

API_VERSION = "Kinesis_20131202"


class KinesisError(AwsApiError):
    pass


class KinesisWireClient(AwsJsonClient):
    """Minimal Kinesis API client: JSON target protocol + SigV4 on one
    persistent HTTP connection (shared AwsJsonClient machinery: retry
    envelope for throttles/transient 5xx, re-dial on dead keep-alives)."""

    service = "kinesis"
    target_prefix = API_VERSION
    content_type = "application/x-amz-json-1.1"
    # GetRecords is rate-capped per shard: throttles retry inside the call
    retryable_types = ("ProvisionedThroughputExceededException",
                       "LimitExceededException")
    error_class = KinesisError

    # -- the three consumer APIs -------------------------------------------
    def list_shards(self, stream: str) -> list[str]:
        shards: list[str] = []
        token: Optional[str] = None
        while True:
            payload: dict[str, Any] = (
                {"NextToken": token} if token else {"StreamName": stream})
            out = self.call("ListShards", payload)
            shards.extend(s["ShardId"] for s in out.get("Shards", []))
            token = out.get("NextToken")
            if not token:
                return sorted(shards)

    def get_shard_iterator(self, stream: str, shard_id: str,
                           iterator_type: str,
                           sequence_number: Optional[str] = None) -> str:
        payload: dict[str, Any] = {
            "StreamName": stream, "ShardId": shard_id,
            "ShardIteratorType": iterator_type}
        if sequence_number is not None:
            payload["StartingSequenceNumber"] = sequence_number
        return self.call("GetShardIterator", payload)["ShardIterator"]

    def get_records(self, shard_iterator: str, limit: int
                    ) -> dict[str, Any]:
        return self.call("GetRecords", {"ShardIterator": shard_iterator,
                                        "Limit": limit})


class KinesisSource:
    """Checkpointed Kinesis stream source (reference
    `kinesis_source.rs`). Partitions map to checkpoint partition ids
    "{stream}:{shard_id}"; positions are the LAST PROCESSED sequence
    number (Kinesis convention — resume is AFTER_SEQUENCE_NUMBER). Each
    pipeline turn drains every shard until GetRecords reports zero
    MillisBehindLatest (or returns empty), so the indexing pipeline's
    commit/turn machinery paces consumption."""

    def __init__(self, endpoint: str, stream: str, config: S3Config,
                 records_per_call: int = 1000,
                 max_pages_per_shard_pass: int = 100):
        self.stream = stream
        self.client = KinesisWireClient(endpoint, config)
        self.records_per_call = records_per_call
        # bounded work per pass: under continuous production a shard's
        # MillisBehindLatest may never reach zero, and chasing the live
        # tip would starve the other shards and make a "pass" unbounded
        # (same rationale as KafkaSource's per-pass watermark snapshot)
        self.max_pages_per_shard_pass = max_pages_per_shard_pass

    def close(self) -> None:
        self.client.close()

    def _stream_shards(self) -> list[str]:
        # re-listed every call: resharding creates child shards that must
        # start being consumed without a process restart
        return self.client.list_shards(self.stream)

    def partition_ids(self) -> list[str]:
        return [f"{self.stream}:{s}" for s in self._stream_shards()]

    def batches(self, checkpoint, batch_num_docs: int = 10_000
                ) -> Iterator[Any]:
        import base64

        from ..metastore.checkpoint import BEGINNING, CheckpointDelta
        from .sources import SourceBatch

        for shard_id in self._stream_shards():
            partition_id = f"{self.stream}:{shard_id}"
            position = checkpoint.position_for(partition_id)
            iterator = self.client.get_shard_iterator(
                self.stream, shard_id,
                "TRIM_HORIZON" if position == BEGINNING
                else "AFTER_SEQUENCE_NUMBER",
                None if position == BEGINNING else position)
            pages = 0
            while iterator and pages < self.max_pages_per_shard_pass:
                pages += 1
                out = self.client.get_records(
                    iterator, min(self.records_per_call, batch_num_docs))
                records = out.get("Records", [])
                iterator = out.get("NextShardIterator")
                if records:
                    docs = []
                    for record in records:
                        data = base64.b64decode(record["Data"])
                        try:
                            docs.append(json.loads(data))
                        except (ValueError, UnicodeDecodeError):
                            docs.append({"_malformed":
                                         data.decode("utf-8", "replace")})
                    to_pos = records[-1]["SequenceNumber"]
                    delta = CheckpointDelta.from_range(
                        partition_id, position, to_pos)
                    yield SourceBatch(docs, delta)
                    position = to_pos
                if out.get("MillisBehindLatest", 0) == 0:
                    # caught up with the shard tip: bound this pass (the
                    # next pipeline turn resumes from the checkpoint)
                    break
                if not records:
                    # behind but empty page (Kinesis allows empty reads
                    # mid-stream): avoid a hot spin
                    time.sleep(0.01)
        return
