"""Kafka source speaking the real Kafka wire protocol on stdlib sockets.

Role of the reference's `kafka_source.rs` (librdkafka-backed): consume
doc batches from Kafka topic partitions with per-partition checkpoint
positions flowing through the exactly-once `CheckpointDelta` publish
protocol. This build has no client SDK, so the protocol itself is
implemented here — the classic (non-flexible) encoding of the four APIs
a checkpointed consumer needs:

  ApiVersions(18) v0 · Metadata(3) v1 · ListOffsets(2) v1 · Fetch(1) v4

Offsets come from OUR metastore checkpoint (never Kafka consumer-group
state), exactly like the reference: quickwit stores partition offsets in
the `SourceCheckpoint` and replays from there after any crash, making
Kafka→split ingestion exactly-once (`checkpoint.rs:30`). Consumer-group
coordination is intentionally absent — the control plane assigns
(source, partition) work, so group rebalancing has no role.

RecordBatch v2 (magic=2) decoding with CRC32C verification; gzip
compression (attributes&7==1) handled; other codecs raise clearly.
"""

from __future__ import annotations

import gzip
import io
import socket
import struct
import threading
from typing import Any, Iterator, Optional

# --- primitive codecs (classic protocol: big-endian, i16-length strings) ---


def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    raw = s.encode()
    return struct.pack(">h", len(raw)) + raw


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        out = self.data[self.pos: self.pos + n]
        if len(out) != n:
            raise EOFError("short kafka frame")
        self.pos += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self.take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self.take(4))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self.take(n).decode()

    def raw_bytes(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else self.take(n)

    def varzig(self) -> int:
        """Zigzag varint (record fields)."""
        shift = 0
        value = 0
        while True:
            b = self.take(1)[0]
            value |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (value >> 1) ^ -(value & 1)


def _varzig(value: int) -> bytes:
    value = (value << 1) ^ (value >> 63) if value < 0 else value << 1
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


# --- CRC32C (Castagnoli) — RecordBatch v2 integrity --------------------------

_CRC32C_TABLE = []


def _crc32c_table():
    if not _CRC32C_TABLE:
        poly = 0x82F63B78
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            _CRC32C_TABLE.append(crc)
    return _CRC32C_TABLE


def crc32c(data: bytes) -> int:
    table = _crc32c_table()
    crc = 0xFFFFFFFF
    for byte in data:
        crc = table[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# --- record batches ----------------------------------------------------------


def encode_record_batch(base_offset: int, records: list[bytes],
                        first_timestamp: int = 0) -> bytes:
    """One RecordBatch v2 of null-key records (producer side — the fake
    broker and tests)."""
    body = bytearray()
    for i, value in enumerate(records):
        rec = bytearray()
        rec += b"\x00"                       # attributes
        rec += _varzig(0)                    # timestampDelta
        rec += _varzig(i)                    # offsetDelta
        rec += _varzig(-1)                   # null key
        rec += _varzig(len(value)) + value
        rec += _varzig(0)                    # headers
        body += _varzig(len(rec)) + bytes(rec)
    after_crc = (
        struct.pack(">hiqqqhii", 0, len(records) - 1, first_timestamp,
                    first_timestamp, -1, -1, -1, len(records))
        + bytes(body))
    crc = crc32c(after_crc)
    batch_tail = struct.pack(">ibI", 0, 2, crc) + after_crc
    return struct.pack(">qi", base_offset, len(batch_tail)) + batch_tail


def decode_record_batches(data: bytes) -> list[tuple[int, bytes]]:
    """(offset, value) pairs from a Fetch record_set (may hold several
    concatenated batches; a trailing partial batch is ignored, as per
    the protocol)."""
    out: list[tuple[int, bytes]] = []
    pos = 0
    while pos + 12 <= len(data):
        base_offset, batch_len = struct.unpack_from(">qi", data, pos)
        if pos + 12 + batch_len > len(data):
            break  # partial trailing batch
        batch = data[pos + 12: pos + 12 + batch_len]
        pos += 12 + batch_len
        r = _Reader(batch)
        r.i32()              # partitionLeaderEpoch
        magic = r.i8()
        if magic != 2:
            raise ValueError(f"unsupported record batch magic {magic}")
        crc = r.u32()
        after_crc = batch[r.pos:]
        if crc32c(after_crc) != crc:
            raise ValueError("record batch CRC32C mismatch")
        attributes = r.i16()
        if attributes & 0x20:
            continue  # control batch: transaction markers, not documents
        r.i32()              # lastOffsetDelta
        r.i64()              # firstTimestamp
        r.i64()              # maxTimestamp
        r.i64()              # producerId
        r.i16()              # producerEpoch
        r.i32()              # baseSequence
        num_records = r.i32()
        payload = batch[r.pos:]
        codec = attributes & 0x07
        if codec == 1:
            payload = gzip.decompress(payload)
        elif codec != 0:
            raise ValueError(
                f"unsupported kafka compression codec {codec} "
                "(none and gzip are handled)")
        rr = _Reader(payload)
        for _ in range(num_records):
            rec_len = rr.varzig()
            rec = _Reader(rr.take(rec_len))
            rec.i8()                     # attributes
            rec.varzig()                 # timestampDelta
            offset_delta = rec.varzig()
            key_len = rec.varzig()
            if key_len >= 0:
                rec.take(key_len)
            val_len = rec.varzig()
            value = rec.take(val_len) if val_len >= 0 else b""
            out.append((base_offset + offset_delta, value))
    return out


# --- wire client -------------------------------------------------------------

EARLIEST = -2
LATEST = -1


class KafkaProtocolError(RuntimeError):
    def __init__(self, message: str, code: Optional[int] = None):
        super().__init__(message)
        self.code = code


OFFSET_OUT_OF_RANGE = 1


class _KafkaApiError(Exception):
    """Internal typed API error (carries the Kafka error code so the
    leader-retry logic can distinguish NOT_LEADER from the rest)."""

    def __init__(self, code: int, api: str, topic: str, partition: int):
        super().__init__(f"{api} error {code} on {topic}/{partition}")
        self.code = code


class KafkaWireClient:
    """Minimal Kafka client (the four consumer APIs) with partition-
    leader routing: Metadata's broker/leader map directs ListOffsets and
    Fetch to the partition's leader connection; NOT_LEADER errors
    refresh the metadata and retry once. Requests are serialized per
    client (a pipeline turn drains partitions sequentially, matching the
    reference source's single consumer poll loop)."""

    def __init__(self, bootstrap_servers: list[str], client_id: str = "qwtpu",
                 timeout: float = 10.0):
        self.bootstrap = bootstrap_servers
        self.client_id = client_id
        self.timeout = timeout
        self._socks: dict[str, socket.socket] = {}   # "host:port" -> conn
        self._brokers: dict[int, str] = {}           # node_id -> "host:port"
        self._leaders: dict[tuple[str, int], int] = {}
        self._correlation = 0
        # qwlint: disable-next-line=QW008 - indexing source loops and queue
        # test doubles outside the DST-raced path; rendezvous is
        # uninstrumentable real IO/time
        self._lock = threading.Lock()

    # -- connection management
    def _connect(self, address: Optional[str] = None) -> tuple[str, socket.socket]:
        if address is not None:
            sock = self._socks.get(address)
            if sock is not None:
                return address, sock
            host, _, port = address.rpartition(":")
            try:
                sock = socket.create_connection(
                    (host, int(port)), timeout=self.timeout)
            except OSError as exc:
                raise KafkaProtocolError(
                    f"cannot reach broker {address}: {exc}") from exc
            self._socks[address] = sock
            return address, sock
        if self._socks:
            return next(iter(self._socks.items()))
        last_err: Optional[Exception] = None
        for server in self.bootstrap:
            try:
                return self._connect(server)
            except KafkaProtocolError as exc:
                last_err = exc
        raise KafkaProtocolError(
            f"cannot reach any bootstrap server {self.bootstrap}: {last_err}")

    def close(self) -> None:
        for sock in self._socks.values():
            try:
                sock.close()
            except OSError:
                pass
        self._socks.clear()

    def _drop(self, address: str) -> None:
        sock = self._socks.pop(address, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _leader_address(self, topic: str, partition: int) -> Optional[str]:
        leader = self._leaders.get((topic, partition))
        if leader is None:
            return None
        return self._brokers.get(leader)

    def _roundtrip(self, api_key: int, api_version: int, body: bytes,
                   address: Optional[str] = None) -> _Reader:
        with self._lock:
            self._correlation += 1
            correlation = self._correlation
            header = (struct.pack(">hhi", api_key, api_version, correlation)
                      + _str(self.client_id))
            frame = header + body
            address, sock = self._connect(address)
            try:
                sock.sendall(struct.pack(">i", len(frame)) + frame)
                raw = self._read_frame(sock)
            except OSError as exc:
                self._drop(address)
                raise KafkaProtocolError(f"kafka io error: {exc}") from exc
            r = _Reader(raw)
            got = r.i32()
            if got != correlation:
                self._drop(address)
                raise KafkaProtocolError(
                    f"correlation mismatch: {got} != {correlation}")
            return r

    def _read_frame(self, sock: socket.socket) -> bytes:
        size_raw = self._read_exact(sock, 4)
        size = struct.unpack(">i", size_raw)[0]
        return self._read_exact(sock, size)

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes:
        chunks = bytearray()
        while len(chunks) < n:
            chunk = sock.recv(n - len(chunks))
            if not chunk:
                raise OSError("connection closed by broker")
            chunks += chunk
        return bytes(chunks)

    # -- APIs
    def api_versions(self) -> dict[int, tuple[int, int]]:
        r = self._roundtrip(18, 0, b"")
        error = r.i16()
        if error:
            raise KafkaProtocolError(f"ApiVersions error {error}")
        out = {}
        for _ in range(r.i32()):
            key, lo, hi = r.i16(), r.i16(), r.i16()
            out[key] = (lo, hi)
        return out

    def metadata(self, topics: Optional[list[str]] = None) -> dict[str, Any]:
        body = struct.pack(">i", -1) if topics is None else (
            struct.pack(">i", len(topics))
            + b"".join(_str(t) for t in topics))
        r = self._roundtrip(3, 1, body)
        brokers = []
        for _ in range(r.i32()):
            node_id = r.i32()
            host = r.string()
            port = r.i32()
            r.string()  # rack
            brokers.append({"node_id": node_id, "host": host, "port": port})
        r.i32()  # controller_id
        out_topics = {}
        for _ in range(r.i32()):
            error = r.i16()
            name = r.string()
            r.i8()  # is_internal
            partitions = []
            for _ in range(r.i32()):
                p_error = r.i16()
                index = r.i32()
                leader = r.i32()
                for _ in range(r.i32()):
                    r.i32()  # replicas
                for _ in range(r.i32()):
                    r.i32()  # isr
                partitions.append({"partition": index, "leader": leader,
                                   "error": p_error})
            out_topics[name] = {"error": error, "partitions": partitions}
        # refresh the routing tables
        self._brokers = {b["node_id"]: f"{b['host']}:{b['port']}"
                         for b in brokers}
        for name, topic_meta in out_topics.items():
            for p in topic_meta["partitions"]:
                self._leaders[(name, p["partition"])] = p["leader"]
        return {"brokers": brokers, "topics": out_topics}

    _NOT_LEADER = 6

    def list_offsets(self, topic: str, partitions: list[int],
                     timestamp: int = EARLIEST) -> dict[int, int]:
        out: dict[int, int] = {}
        for partition in partitions:
            out[partition] = self._with_leader_retry(
                topic, partition,
                lambda addr, p=partition: self._list_offsets_one(
                    topic, p, timestamp, addr))
        return out

    def _list_offsets_one(self, topic: str, partition: int, timestamp: int,
                          address: Optional[str]) -> int:
        body = (struct.pack(">i", -1) + struct.pack(">i", 1) + _str(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iq", partition, timestamp))
        r = self._roundtrip(2, 1, body, address=address)
        offset = -1
        for _ in range(r.i32()):
            r.string()  # topic
            for _ in range(r.i32()):
                r.i32()  # partition
                error = r.i16()
                r.i64()  # timestamp
                offset = r.i64()
                if error:
                    raise _KafkaApiError(error, "ListOffsets",
                                         topic, partition)
        return offset

    def _with_leader_retry(self, topic: str, partition: int, call):
        """Run `call(leader_address)`; on NOT_LEADER (or a missing
        route), refresh metadata and retry once against the new leader."""
        address = self._leader_address(topic, partition)
        try:
            return call(address)
        except _KafkaApiError as exc:
            if exc.code != self._NOT_LEADER:
                raise KafkaProtocolError(str(exc), code=exc.code) from exc
            self.metadata([topic])
            new_address = self._leader_address(topic, partition)
            if new_address == address:
                raise KafkaProtocolError(str(exc), code=exc.code) from exc
            try:
                return call(new_address)
            except _KafkaApiError as exc2:
                raise KafkaProtocolError(str(exc2), code=exc2.code) from exc2

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 4 << 20, max_wait_ms: int = 100
              ) -> tuple[list[tuple[int, bytes]], int]:
        """((offset, value) records, high_watermark)."""
        return self._with_leader_retry(
            topic, partition,
            lambda addr: self._fetch_one(topic, partition, offset,
                                         max_bytes, max_wait_ms, addr))

    def _fetch_one(self, topic: str, partition: int, offset: int,
                   max_bytes: int, max_wait_ms: int,
                   address: Optional[str]) -> tuple[list[tuple[int, bytes]], int]:
        body = (struct.pack(">iiii", -1, max_wait_ms, 1, max_bytes)
                + struct.pack(">b", 0)          # isolation_level
                + struct.pack(">i", 1) + _str(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iqi", partition, offset, max_bytes))
        r = self._roundtrip(1, 4, body, address=address)
        r.i32()  # throttle_time
        records: list[tuple[int, bytes]] = []
        high_watermark = 0
        for _ in range(r.i32()):
            r.string()  # topic
            for _ in range(r.i32()):
                r.i32()  # partition
                error = r.i16()
                high_watermark = r.i64()
                r.i64()  # last_stable_offset
                aborted = r.i32()
                for _ in range(max(aborted, 0)):
                    r.i64()
                    r.i64()
                record_set = r.raw_bytes() or b""
                if error:
                    raise _KafkaApiError(error, "Fetch", topic, partition)
                # brokers return the whole batch CONTAINING the requested
                # offset; records before it are the consumer's to skip
                records.extend(
                    (off, value)
                    for off, value in decode_record_batches(record_set)
                    if off >= offset)
        return records, high_watermark


# --- the Source --------------------------------------------------------------


class KafkaSource:
    """Checkpointed Kafka topic source (reference `kafka_source.rs`).

    Partitions map to checkpoint partition ids "{topic}:{partition}";
    positions are THE NEXT OFFSET TO READ (Kafka convention). Each
    pipeline turn drains every partition up to its current high
    watermark — bounded work per turn, so the indexing pipeline's
    commit/turn machinery paces consumption (the reference's poll loop
    with its batch deadline plays this role)."""

    def __init__(self, bootstrap_servers: list[str], topic: str,
                 client_id: str = "qwtpu-source",
                 max_fetch_bytes: int = 4 << 20):
        self.topic = topic
        self.client = KafkaWireClient(bootstrap_servers, client_id)
        self.max_fetch_bytes = max_fetch_bytes
        self._partitions: Optional[list[int]] = None

    def close(self) -> None:
        self.client.close()

    def _topic_partitions(self) -> list[int]:
        if self._partitions is None:
            meta = self.client.metadata([self.topic])
            topic_meta = meta["topics"].get(self.topic)
            if topic_meta is None or topic_meta["error"]:
                raise KafkaProtocolError(
                    f"topic {self.topic!r} not available: {topic_meta}")
            self._partitions = sorted(
                p["partition"] for p in topic_meta["partitions"])
        return self._partitions

    def partition_ids(self) -> list[str]:
        return [f"{self.topic}:{p}" for p in self._topic_partitions()]

    def batches(self, checkpoint, batch_num_docs: int = 10_000):
        import json as _json

        from ..metastore.checkpoint import (
            BEGINNING, CheckpointDelta, offset_position)
        from .sources import SourceBatch

        partitions = self._topic_partitions()
        earliest = self.client.list_offsets(self.topic, partitions, EARLIEST)
        # snapshot the drain target per pass: under continuous production
        # the live high watermark keeps moving, and chasing it would make
        # a "pass" unbounded — the next tick picks up from here
        latest = self.client.list_offsets(self.topic, partitions, LATEST)
        for partition in partitions:
            partition_id = f"{self.topic}:{partition}"
            position = checkpoint.position_for(partition_id)
            offset = (earliest[partition] if position == BEGINNING
                      else int(position))
            target = latest[partition]
            while offset < target:
                try:
                    records, _high = self.client.fetch(
                        self.topic, partition, offset,
                        max_bytes=self.max_fetch_bytes)
                except KafkaProtocolError as exc:
                    if exc.code == OFFSET_OUT_OF_RANGE:
                        # refresh the floor first: retention may have
                        # truncated DURING this pass, making the snapshot
                        # taken at pass start stale
                        earliest[partition] = self.client.list_offsets(
                            self.topic, [partition], EARLIEST)[partition]
                        if earliest[partition] > offset:
                            # retention truncated past the checkpoint:
                            # resume at the earliest retained offset (the
                            # records in between are gone —
                            # auto.offset.reset=earliest semantics; the
                            # checkpoint jump is the honest record of loss)
                            offset = earliest[partition]
                            continue
                    raise
                records = [(off, v) for off, v in records if off < target]
                if not records:
                    break
                docs = []
                for _off, value in records[:batch_num_docs]:
                    try:
                        docs.append(_json.loads(value))
                    except (ValueError, UnicodeDecodeError):
                        docs.append({"_malformed":
                                     value.decode("utf-8", "replace")})
                taken = records[:batch_num_docs]
                next_offset = taken[-1][0] + 1
                # the delta always starts at the STORED position — after a
                # retention reset it spans the truncated hole, keeping the
                # exactly-once chain contiguous
                delta = CheckpointDelta.from_range(
                    partition_id, position, offset_position(next_offset))
                yield SourceBatch(docs, delta)
                position = offset_position(next_offset)
                offset = next_offset
