"""Merge policies + merge execution.

Role of the reference's merge side (`merge_planner.rs`, `merge_policy/
stable_log_merge_policy.rs`, `merge_executor.rs`): decide which published
splits to merge and replace N splits by one, through the same atomic
stage/upload/publish(replace) protocol so no document is ever lost or
duplicated (`no_split_loss`/`rows_conserved` invariants of quickwit-dst).

The executor merges at the ARRAY level (index/merge_arrays.py: term-dict
k-way merge, postings offset-concat, compressed docstore blocks reused) in
the common case; when delete tasks newer than the inputs' delete_opstamp
are pending, it falls back to a doc-level rewrite that applies them — like
the reference's delete-task pipeline applies deletes at merge time.
"""

from __future__ import annotations

import logging
import zlib
from dataclasses import dataclass
from typing import Optional

from ..common.clock import wall_time
from ..index.reader import SplitReader
from ..index.writer import SplitWriter
from ..metastore.base import ListSplitsQuery, Metastore
from ..models.doc_mapper import DocMapper
from ..models.split_metadata import Split, SplitMetadata, SplitState, new_split_id
from ..storage.base import Storage
from .pipeline import split_file_path

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class MergeOperation:
    splits: tuple[Split, ...]

    @property
    def split_ids(self) -> list[str]:
        return [s.metadata.split_id for s in self.splits]


class MergePolicy:
    def operations(self, splits: list[Split]) -> list[MergeOperation]:
        raise NotImplementedError


class NopMergePolicy(MergePolicy):
    def operations(self, splits: list[Split]) -> list[MergeOperation]:
        return []


class StableLogMergePolicy(MergePolicy):
    """Size-tiered merging (reference `stable_log_merge_policy.rs`): splits
    bucket into levels by doc-count magnitude; a level reaching
    `merge_factor` members merges its oldest members into one split.
    Splits at or above `split_num_docs_target` are mature and never merge.
    """

    def __init__(self, merge_factor: int = 10, max_merge_factor: int = 12,
                 split_num_docs_target: int = 10_000_000,
                 min_level_num_docs: int = 100_000):
        self.merge_factor = merge_factor
        self.max_merge_factor = max_merge_factor
        self.split_num_docs_target = split_num_docs_target
        self.min_level_num_docs = min_level_num_docs

    def _level(self, num_docs: int) -> int:
        level = 0
        threshold = self.min_level_num_docs
        while num_docs >= threshold:
            level += 1
            threshold *= self.merge_factor
        return level

    def operations(self, splits: list[Split]) -> list[MergeOperation]:
        candidates = [
            s for s in splits
            if s.state is SplitState.PUBLISHED
            and s.metadata.num_docs < self.split_num_docs_target
        ]
        # partitioned splits only merge within their partition (reference
        # split_metadata.rs:75-78: merging across partition_id defeats
        # routing-based pruning), so the level buckets key on both
        by_level: dict[tuple[int, int], list[Split]] = {}
        for split in candidates:
            key = (split.metadata.partition_id,
                   self._level(split.metadata.num_docs))
            by_level.setdefault(key, []).append(split)
        operations = []
        for level_splits in by_level.values():
            level_splits.sort(key=lambda s: s.metadata.split_id)  # ULIDs: time order
            while len(level_splits) >= self.merge_factor:
                group = level_splits[: self.max_merge_factor]
                level_splits = level_splits[len(group):]
                operations.append(MergeOperation(tuple(group)))
        return operations


def merge_policy_from_config(config: dict) -> MergePolicy:
    kind = config.get("type", "stable_log")
    if kind == "stable_log":
        return StableLogMergePolicy(
            merge_factor=config.get("merge_factor", 10),
            max_merge_factor=config.get("max_merge_factor", 12),
            split_num_docs_target=config.get("split_num_docs_target", 10_000_000),
            min_level_num_docs=config.get("min_level_num_docs", 100_000),
        )
    if kind in ("no_merge", "nop", "none"):
        return NopMergePolicy()
    raise ValueError(f"unknown merge policy {kind!r}")


def _merge_column_bounds(splits) -> dict:
    """Zonemap union over merge inputs: min of mins / max of maxes. A
    field is kept only when EVERY input carries bounds for it — a split
    without the entry might be a pre-zonemap split that still holds
    values, so dropping the field is the only sound choice."""
    if not splits:
        return {}
    common = set(splits[0].metadata.column_bounds)
    for split in splits[1:]:
        common &= set(split.metadata.column_bounds)
    out = {}
    for name in common:
        bounds = [s.metadata.column_bounds[name] for s in splits]
        out[name] = (min(b[0] for b in bounds), max(b[1] for b in bounds))
    return out


def _iter_all_docs(reader: SplitReader):
    """Stream every stored document of a split in doc-id order."""
    import json
    block_first = reader.array("store.block_first_doc")
    block_offsets = reader.array("store.block_offsets")
    for block in range(len(block_first) - 1):
        raw = reader.array_slice(
            "store.data", int(block_offsets[block]),
            int(block_offsets[block + 1] - block_offsets[block]))
        for line in zlib.decompress(raw.tobytes()).split(b"\n"):
            if line:
                yield json.loads(line)


class MergeExecutor:
    """Reference `merge_executor.rs`: N published splits → 1, atomically."""

    def __init__(self, index_uid: str, doc_mapper: DocMapper,
                 metastore: Metastore, split_storage: Storage,
                 node_id: str = "node-0", fault_injector=None):
        self.index_uid = index_uid
        self.doc_mapper = doc_mapper
        self.metastore = metastore
        self.split_storage = split_storage
        self.node_id = node_id
        # chaos hook (common/faults.FaultInjector): "merge.execute" perturbs
        # the read/merge phase, "merge.publish" the atomic replace — a fault
        # at either point must leave every input split PUBLISHED and
        # searchable (no_split_loss), and a retry must conserve rows.
        # "merge.reorder" perturbs only the cluster-aware doc reordering:
        # the merge must then degrade to append order, never fail or corrupt
        self.fault_injector = fault_injector

    def execute(self, operation: MergeOperation,
                delete_tasks: Optional[list[dict]] = None) -> Optional[str]:
        """`delete_tasks`: metastore task dicts ({"opstamp", "query_ast"}).
        Only tasks NEWER than every input split's delete_opstamp still need
        applying — already-applied tasks must not push merges onto the slow
        doc-level path forever."""
        if self.fault_injector is not None:
            self.fault_injector.perturb("merge.execute")
        max_delete_opstamp = self.metastore.last_delete_opstamp(self.index_uid)
        min_applied = min(s.metadata.delete_opstamp for s in operation.splits)
        applicable = [t for t in (delete_tasks or [])
                      if t["opstamp"] > min_applied]
        from ..query.ast import ast_from_dict
        delete_matchers = self._delete_matchers(
            [ast_from_dict(t["query_ast"]) for t in applicable])
        readers = [SplitReader(self.split_storage,
                               split_file_path(s.metadata.split_id))
                   for s in operation.splits]
        if not delete_matchers:
            # fast path: array-level segment merge, no re-tokenization;
            # stats come from the authoritative split metadata. The merged
            # split clusters doc ids by timestamp so zonemaps tighten;
            # "merge.reorder" chaos faults (and any other reorder failure)
            # degrade to the plain append-order merge inside merge_splits
            from ..index.merge_arrays import merge_splits
            reorder_hook = None
            if self.fault_injector is not None:
                reorder_hook = (
                    lambda: self.fault_injector.perturb("merge.reorder"))
            data = merge_splits(readers,
                                reorder_field=self.doc_mapper.timestamp_field,
                                fault_hook=reorder_hook)
            num_docs = sum(s.metadata.num_docs for s in operation.splits)
            uncompressed = sum(s.metadata.uncompressed_docs_size_bytes
                               for s in operation.splits)
            time_min = min((s.metadata.time_range_start
                            for s in operation.splits
                            if s.metadata.time_range_start is not None),
                           default=None)
            time_max = max((s.metadata.time_range_end
                            for s in operation.splits
                            if s.metadata.time_range_end is not None),
                           default=None)
            tags = frozenset().union(*(s.metadata.tags for s in operation.splits))
            return self._publish_merged(
                operation, data, num_docs, uncompressed, time_min, time_max,
                tags, max_delete_opstamp,
                _merge_column_bounds(operation.splits))
        # delete tasks pending: doc-level rewrite applies them
        writer = SplitWriter(self.doc_mapper)
        for reader in readers:
            for doc in _iter_all_docs(reader):
                if any(matcher(doc) for matcher in delete_matchers):
                    continue
                writer.add_json_doc(doc)
        if writer.num_docs == 0:
            # all docs deleted: publish the replacement as a pure removal
            self.metastore.publish_splits(
                self.index_uid, [], replaced_split_ids=operation.split_ids)
            return None
        data = writer.finish()
        return self._publish_merged(
            operation, data, writer.num_docs, writer._uncompressed_docs_size,
            writer._time_min, writer._time_max, frozenset(writer.tags),
            max_delete_opstamp,
            dict(writer.column_bounds))

    def _publish_merged(self, operation, data, num_docs, uncompressed,
                        time_min, time_max, tags, max_delete_opstamp,
                        column_bounds=None):
        merged_id = new_split_id()
        metadata = SplitMetadata(
            split_id=merged_id,
            index_uid=self.index_uid,
            source_id=operation.splits[0].metadata.source_id,
            node_id=self.node_id,
            num_docs=num_docs,
            uncompressed_docs_size_bytes=uncompressed,
            footprint_bytes=len(data),
            time_range_start=time_min,
            time_range_end=time_max,
            tags=tags,
            create_timestamp=int(wall_time()),
            num_merge_ops=1 + max(s.metadata.num_merge_ops for s in operation.splits),
            delete_opstamp=max_delete_opstamp,
            doc_mapping_uid=operation.splits[0].metadata.doc_mapping_uid,
            partition_id=operation.splits[0].metadata.partition_id,
            column_bounds=column_bounds or {},
        )
        self.metastore.stage_splits(self.index_uid, [metadata])
        self.split_storage.put(split_file_path(merged_id), data)
        if self.fault_injector is not None:
            # pre-publish crash: the merged split stays STAGED (GC fodder)
            # and every input stays PUBLISHED — the replace is all-or-nothing
            self.fault_injector.perturb("merge.publish")
        self.metastore.publish_splits(
            self.index_uid, [merged_id],
            replaced_split_ids=operation.split_ids)
        logger.info("merged %d splits -> %s (%d docs)",
                    len(operation.splits), merged_id, num_docs)
        return merged_id

    def _delete_matchers(self, delete_query_asts: list):
        """Host-side doc matchers for delete tasks. Round-1 subset: term and
        bool-of-terms queries on mapped fields evaluated against the raw doc;
        complex deletes are applied by search-based planners later."""
        from ..query import ast as Q

        def matcher_for(ast):
            if isinstance(ast, Q.Term):
                field_path = ast.field.split(".")

                def match(doc, path=field_path, value=ast.value):
                    node = doc
                    for key in path:
                        if not isinstance(node, dict) or key not in node:
                            return False
                        node = node[key]
                    return str(node) == value
                return match
            if isinstance(ast, Q.Bool):
                subs = [matcher_for(c) for c in ast.must + ast.filter]
                nots = [matcher_for(c) for c in ast.must_not]
                shoulds = [matcher_for(c) for c in ast.should]

                def match(doc):
                    if subs and not all(m(doc) for m in subs):
                        return False
                    if nots and any(m(doc) for m in nots):
                        return False
                    if shoulds and not (subs or any(m(doc) for m in shoulds)):
                        return False
                    return bool(subs or shoulds)
                return match
            raise ValueError(
                f"delete query node {type(ast).__name__} not supported")
        return [matcher_for(ast) for ast in delete_query_asts]
