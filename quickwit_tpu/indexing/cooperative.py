"""Cooperative indexing: phase-spread, concurrency-bounded pipeline turns.

Role of the reference's `cooperative_indexing.rs` (CooperativeIndexingCycle
/ CooperativeIndexingPeriod): with many (index, source) pipelines on one
node, letting them all build splits at once maximizes peak memory and
makes every resource spike coincide. Instead:

- a semaphore caps how many pipelines may index concurrently, and
- each pipeline is steered toward a private target PHASE of the shared
  `commit_timeout` cycle (derived from a hash of its pipeline id), so
  work spreads uniformly over the window instead of thundering together.

The sleep after a work period is `commit_timeout - (work duration)`,
nudged by at most NUDGE_TOLERANCE_SECS toward the target phase per cycle
(reference `compute_sleep_duration`). Work periods also yield
PipelineMetrics (throughput + cpu-load fraction of one full pipeline),
which the control-plane scheduler consumes as observed pipeline cost.

The clock is injectable so tests steer phases without real sleeping
(the actor Universe's accelerated clock plugs in directly).
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass
from typing import Callable, Optional

from ..common.clock import monotonic

NUDGE_TOLERANCE_SECS = 5.0

# one pipeline saturating its whole commit window ≙ this many cpu millis
# (reference PIPELINE_FULL_CAPACITY = 4000mcpu)
PIPELINE_FULL_CAPACITY_MCPU = 4000


@dataclass(frozen=True)
class PipelineMetrics:
    """Observed per-cycle pipeline cost (reference PipelineMetrics)."""
    cpu_load_mcpu: int
    throughput_mb_per_sec: int


class CooperativeIndexingCycle:
    """Per-pipeline scheduling state; share one `permits` semaphore across
    every pipeline of the node."""

    def __init__(self, pipeline_id: str, commit_timeout_secs: float,
                 permits: threading.Semaphore,
                 clock: Callable[[], float] = monotonic,
                 origin: Optional[float] = None):
        if commit_timeout_secs <= 0:
            raise ValueError("commit_timeout must be positive")
        self.commit_timeout = float(commit_timeout_secs)
        self.permits = permits
        self.clock = clock
        # shared origin of time: phases of different pipelines must be
        # measured against the same epoch to spread out
        self.origin = 0.0 if origin is None else origin
        digest = hashlib.blake2b(pipeline_id.encode(),
                                 digest_size=8).digest()
        # max(…, 1): sub-millisecond windows must not modulo by zero
        window_millis = max(int(self.commit_timeout * 1000), 1)
        self.target_phase = (int.from_bytes(digest, "little")
                             % window_millis) / 1000.0

    def initial_sleep_duration(self) -> float:
        """Sleep that puts the FIRST period near the target phase."""
        current = (self.clock() - self.origin) % self.commit_timeout
        sleep = (self.commit_timeout + self.target_phase
                 - current) % self.commit_timeout
        if sleep + 2 * NUDGE_TOLERANCE_SECS > self.commit_timeout:
            # close enough — the per-cycle nudge finishes the job
            return 0.0
        return sleep

    def begin_period(self, timeout: Optional[float] = None
                     ) -> Optional["CooperativeIndexingPeriod"]:
        """Acquire an indexing turn (blocks on the shared semaphore, the
        reference's 'waking' phase). None when `timeout` elapses first."""
        t_wake = self.clock()
        acquired = self.permits.acquire(
            timeout=timeout) if timeout is not None \
            else self.permits.acquire()
        if not acquired:
            return None
        return CooperativeIndexingPeriod(self, t_wake, self.clock())


class CooperativeIndexingPeriod:
    def __init__(self, cycle: CooperativeIndexingCycle, t_wake: float,
                 t_work_start: float):
        self.cycle = cycle
        self.t_wake = t_wake
        self.t_work_start = t_work_start
        self._done = False

    def _compute_sleep_duration(self, t_work_end: float) -> float:
        ct = self.cycle.commit_timeout
        phase = (t_work_end - self.cycle.origin) % ct
        delta = phase - self.cycle.target_phase
        # fold into [-ct/2, ct/2): nudge toward the NEAREST occurrence
        if delta >= ct / 2:
            delta -= ct
        elif delta < -ct / 2:
            delta += ct
        nudge = max(-NUDGE_TOLERANCE_SECS,
                    min(NUDGE_TOLERANCE_SECS, delta))
        return max(0.0, ct - (t_work_end - self.t_wake) - nudge)

    def _compute_metrics(self, t_work_end: float,
                         uncompressed_num_bytes: int) -> PipelineMetrics:
        elapsed = max(t_work_end - self.t_work_start, 0.0)
        # bytes per microsecond == MB/s (reference formula)
        throughput = int(uncompressed_num_bytes / (1.0 + elapsed * 1e6))
        fraction = min(elapsed / self.cycle.commit_timeout, 1.0)
        return PipelineMetrics(
            cpu_load_mcpu=int(PIPELINE_FULL_CAPACITY_MCPU * fraction),
            throughput_mb_per_sec=throughput)

    def end_of_work(self, uncompressed_num_bytes: int
                    ) -> tuple[float, PipelineMetrics]:
        """Release the permit; → (sleep_secs until next period, metrics)."""
        if self._done:
            raise RuntimeError("end_of_work called twice")
        self._done = True
        t_work_end = self.cycle.clock()
        self.cycle.permits.release()
        return (self._compute_sleep_duration(t_work_end),
                self._compute_metrics(t_work_end, uncompressed_num_bytes))
