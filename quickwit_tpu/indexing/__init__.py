from .sources import FileSource, Source, SourceBatch, VecSource, VoidSource, make_source
from .pipeline import IndexingPipeline, PipelineParams
from .merge import MergeExecutor, StableLogMergePolicy, NopMergePolicy, merge_policy_from_config

__all__ = [
    "Source", "SourceBatch", "VecSource", "FileSource", "VoidSource", "make_source",
    "IndexingPipeline", "PipelineParams",
    "MergeExecutor", "StableLogMergePolicy", "NopMergePolicy", "merge_policy_from_config",
]
