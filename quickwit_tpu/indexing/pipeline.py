"""Indexing pipeline: source → typed docs → split files → atomic publish.

Role of the reference's actor chain (`quickwit-indexing/src/actors/`:
DocProcessor → Indexer → IndexSerializer → Packager → Uploader → Sequencer →
Publisher, SURVEY.md §3.3), collapsed into a synchronous pipeline object —
the stage boundaries and failure semantics are preserved (stage splits
before upload; upload before publish; publish carries the checkpoint delta
so crash-replays dedupe), while threading/supervision live one level up in
the IndexingService.

A split is cut when `split_num_docs_target` is reached or the source batch
is force-committed (commit_timeout's role for bounded sources).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..common.clock import wall_time
from ..index.writer import SplitWriter
from ..metastore.base import Metastore
from ..metastore.checkpoint import CheckpointDelta, SourceCheckpoint
from ..models.doc_mapper import DocMapper, DocParsingError
from ..models.split_metadata import SplitMetadata, new_split_id
from ..storage.base import Storage
from .sources import Source, SourceBatch
from .transform import TransformRuntimeError

logger = logging.getLogger(__name__)


def split_file_path(split_id: str) -> str:
    return f"{split_id}.split"


@dataclass
class PipelineParams:
    index_uid: str
    source_id: str
    node_id: str = "node-0"
    split_num_docs_target: int = 10_000_000
    batch_num_docs: int = 10_000
    doc_mapping_uid: str = "default"


@dataclass
class PipelineCounters:
    """Observable pipeline state (role of the actors' observable states)."""
    num_docs_processed: int = 0
    num_docs_invalid: int = 0
    num_splits_published: int = 0
    num_published_docs: int = 0
    num_published_bytes: int = 0  # uncompressed (cooperative metrics)


class IndexingPipeline:
    """One (index, source) pipeline (reference `indexing_pipeline.rs:80`)."""

    def __init__(self, params: PipelineParams, doc_mapper: DocMapper,
                 source: Source, metastore: Metastore, split_storage: Storage,
                 transform=None, fault_injector=None):
        self.params = params
        self.doc_mapper = doc_mapper
        self.source = source
        self.metastore = metastore
        self.split_storage = split_storage
        self.transform = transform  # compiled Transform (VRL analogue) or None
        # chaos hook (common/faults.FaultInjector): perturbs the commit's
        # stage/upload/publish boundaries ("indexing.stage",
        # "indexing.upload", "indexing.publish") so the crash-between-stages
        # claims above are test-driven, not asserted
        self.fault_injector = fault_injector
        self.counters = PipelineCounters()
        # one writer per partition id (reference `indexer.rs:146-160`);
        # partition 0 is the unpartitioned default
        self._writers: dict[int, SplitWriter] = {}
        self._pending_delta = CheckpointDelta()

    # ------------------------------------------------------------------
    def run_to_completion(self) -> PipelineCounters:
        """Drain a bounded source fully, publishing splits along the way."""
        checkpoint = self._current_checkpoint()
        # splits cut at batch boundaries, so batches must not exceed the
        # split target (checkpoint deltas stay aligned with published splits)
        batch_num_docs = min(self.params.batch_num_docs,
                             self.params.split_num_docs_target)
        for batch in self.source.batches(checkpoint, batch_num_docs):
            self.process_batch(batch)
        self.commit(force=True)
        return self.counters

    def _current_checkpoint(self) -> SourceCheckpoint:
        return self.metastore.source_checkpoint(  # type: ignore[attr-defined]
            self.params.index_uid, self.params.source_id)

    # ------------------------------------------------------------------
    # overflow partition once max_num_partitions writers exist
    # (reference `indexer.rs:61,157-160` maps excess docs to OTHER)
    OTHER_PARTITION = 2**64 - 1

    def _writer_for(self, partition: int) -> SplitWriter:
        writer = self._writers.get(partition)
        if writer is None:
            if (partition != self.OTHER_PARTITION
                    and len(self._writers)
                    >= self.doc_mapper.max_num_partitions):
                return self._writer_for(self.OTHER_PARTITION)
            writer = self._writers[partition] = SplitWriter(self.doc_mapper)
        return writer

    def process_batch(self, batch: SourceBatch) -> None:
        """DocProcessor + Indexer stages."""
        for doc in batch.docs:
            try:
                if self.transform is not None:
                    doc = self.transform.apply(doc, copy=False)
                    if doc is None:  # drop()ped by the script (filtering)
                        continue
                # parse BEFORE fetching the writer: an invalid doc must
                # not register a phantom partition writer (the partition
                # budget would fill with empties, mis-routing later docs)
                tdoc = self.doc_mapper.doc_from_json(doc)
                partition = self.doc_mapper.partition_id(doc)
                self._writer_for(partition).add_typed_doc(tdoc)
                self.counters.num_docs_processed += 1
            except (DocParsingError, TransformRuntimeError) as exc:
                self.counters.num_docs_invalid += 1
                logger.debug("dropping invalid doc: %s", exc)
        self._pending_delta.extend(batch.checkpoint_delta)
        total = sum(w.num_docs for w in self._writers.values())
        if (total >= self.params.split_num_docs_target
                or batch.force_commit):
            self.commit(force=True)

    def commit(self, force: bool = False) -> Optional[str]:
        """Packager + Uploader + Publisher stages: serialize one split per
        partition, stage them, upload them, publish them TOGETHER with the
        pending checkpoint delta (partitioned docs from one batch window
        must land atomically, like the reference's per-partition
        IndexedSplitBatch)."""
        writers = {p: w for p, w in self._writers.items() if w.num_docs > 0}
        if not writers:
            if not self._pending_delta.is_empty:
                # batches that produced no valid docs still advance the
                # checkpoint (otherwise they would replay forever)
                self.metastore.publish_splits(
                    self.params.index_uid, [],
                    source_id=self.params.source_id,
                    checkpoint_delta=self._pending_delta)
                self._pending_delta = CheckpointDelta()
            return None
        staged: list[tuple[SplitMetadata, bytes]] = []
        for partition in sorted(writers):
            writer = writers[partition]
            data = writer.finish()
            staged.append((SplitMetadata(
                split_id=new_split_id(),
                index_uid=self.params.index_uid,
                source_id=self.params.source_id,
                node_id=self.params.node_id,
                num_docs=writer.num_docs,
                uncompressed_docs_size_bytes=writer._uncompressed_docs_size,
                footprint_bytes=len(data),
                time_range_start=writer._time_min,
                time_range_end=writer._time_max,
                tags=frozenset(writer.tags),
                create_timestamp=int(wall_time()),
                doc_mapping_uid=self.params.doc_mapping_uid,
                partition_id=partition,
                column_bounds=dict(writer.column_bounds),
            ), data))
        # stage → upload → publish: a crash between stages leaves either a
        # staged-but-absent split (GC'd) or an uploaded-but-unpublished file
        # (GC'd); never a published split without its file. Each boundary
        # perturbs BEFORE its mutation so an error-kind fault models a crash
        # that left the previous stage durable and this one not started.
        if self.fault_injector is not None:
            self.fault_injector.perturb("indexing.stage")
        self.metastore.stage_splits(self.params.index_uid,
                                    [m for m, _ in staged])
        if self.fault_injector is not None:
            self.fault_injector.perturb("indexing.upload")
        for metadata, data in staged:
            self.split_storage.put(split_file_path(metadata.split_id), data)
        delta = self._pending_delta if not self._pending_delta.is_empty else None
        split_ids = [m.split_id for m, _ in staged]
        if self.fault_injector is not None:
            self.fault_injector.perturb("indexing.publish")
        self.metastore.publish_splits(
            self.params.index_uid, split_ids,
            source_id=self.params.source_id,
            checkpoint_delta=delta)
        for metadata, _ in staged:
            self.counters.num_splits_published += 1
            self.counters.num_published_docs += metadata.num_docs
            self.counters.num_published_bytes += \
                metadata.uncompressed_docs_size_bytes
            logger.info("published split %s (%d docs, partition %d)",
                        metadata.split_id, metadata.num_docs,
                        metadata.partition_id)
        self._writers = {}
        self._pending_delta = CheckpointDelta()
        return split_ids[0]
