"""Doc transforms: a small, parsed VRL-analogue applied before mapping.

Role of the reference's VRL source transforms
(`quickwit-indexing/src/actors/doc_processor.rs:94` — a per-source
`transform: script` compiled once and run on every ingested doc before the
doc mapper). VRL itself is a Rust DSL; this is a deliberately small,
side-effect-free expression language with the same shape: field paths,
assignments, `del`/`drop`, conditionals, and a fixed function library.
Scripts are parsed once into closures — no Python `eval`, no attribute
access, no IO — so untrusted index configs cannot escape the doc.

Grammar (statements separated by newlines or `;`):

    .path.to.field = <expr>          # assignment (creates nested objects)
    del(.field)                      # remove a field
    drop()                           # discard the whole doc (filtering)
    if <expr> { stmts } [else { stmts }]

Expressions: literals (numbers, "strings", true/false/null), field refs
(`.a.b`), `( )`, unary `-`/`!`, binary `+ - * / %`, comparisons
`== != < <= > >=`, boolean `&& ||`, and function calls. `+` concatenates
when either side is a string.

Functions: string, int, float, bool, lowercase/downcase,
uppercase/upcase, trim, replace(s, from, to), contains(s, sub),
starts_with(s, p), ends_with(s, p), split(s, sep), join(arr, sep),
length(x), exists(.f), now() (epoch seconds), parse_json(s),
encode_json(x), round/floor/ceil/abs, slice(x, lo, hi),
truncate(s, n), push(arr, v), merge(obj, obj), md5/sha1/sha256,
to_unix_timestamp(x), parse_timestamp(s, fmt),
format_timestamp(secs, fmt), parse_regex(s, pattern) (named groups),
parse_key_value(s) (logfmt), parse_common_log(s) (Apache CLF/combined),
parse_syslog(s) (RFC3164), parse_url(s).

Failure semantics match VRL's abort-on-error default: any runtime error
(type mismatch, bad function arg) makes the doc invalid — counted and
dropped by the pipeline, never published half-transformed.
"""

from __future__ import annotations

import datetime as _dt
import functools
import hashlib
import json
import math
import re
import time
from typing import Any, Callable, Optional
from urllib.parse import urlsplit, parse_qsl


class TransformParseError(Exception):
    """Script rejected at compile time."""


class TransformRuntimeError(Exception):
    """Per-doc evaluation failure (doc becomes invalid)."""


class _Drop(Exception):
    """Control-flow: drop() discards the current doc."""


# --------------------------------------------------------------------------
# lexer

_TOKEN_RE = re.compile(r"""
    (?P<ws>[ \t]+)
  | (?P<comment>\#[^\n]*)
  | (?P<newline>[\n;]+)
  | (?P<path>\.[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)*)
  | (?P<number>\d+(?:\.\d+)?)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op>==|!=|<=|>=|&&|\|\||[=<>+\-*/%!(){},])
""", re.VERBOSE)

_KEYWORDS = ("if", "else", "true", "false", "null")


def _tokenize(script: str) -> list[tuple[str, str]]:
    tokens: list[tuple[str, str]] = []
    pos = 0
    while pos < len(script):
        m = _TOKEN_RE.match(script, pos)
        if m is None:
            raise TransformParseError(
                f"unexpected character {script[pos]!r} at offset {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        tokens.append((kind, m.group()))
    tokens.append(("eof", ""))
    return tokens


# --------------------------------------------------------------------------
# runtime helpers (the function library)

def _fn_string(x):
    if x is None:
        return ""
    if isinstance(x, bool):
        return "true" if x else "false"
    if isinstance(x, (dict, list)):
        return json.dumps(x)
    return str(x)


def _fn_int(x):
    try:
        return int(float(x)) if isinstance(x, str) else int(x)
    except (TypeError, ValueError) as exc:
        raise TransformRuntimeError(f"int(): {exc}")


def _fn_float(x):
    try:
        return float(x)
    except (TypeError, ValueError) as exc:
        raise TransformRuntimeError(f"float(): {exc}")


def _str_arg(name: str, x) -> str:
    if not isinstance(x, str):
        raise TransformRuntimeError(f"{name}() requires a string, got "
                                    f"{type(x).__name__}")
    return x


def _fn_parse_json(x):
    try:
        return json.loads(_str_arg("parse_json", x))
    except ValueError as exc:
        raise TransformRuntimeError(f"parse_json(): {exc}")


def _fn_length(x):
    if isinstance(x, (str, list, dict)):
        return len(x)
    raise TransformRuntimeError(
        f"length() requires string/array/object, got {type(x).__name__}")


def _fn_join(arr, sep):
    if not isinstance(arr, list):
        raise TransformRuntimeError("join() requires an array")
    return _str_arg("join", sep).join(_fn_string(v) for v in arr)


def _num_arg(name: str, x) -> float:
    if isinstance(x, bool) or not isinstance(x, (int, float)):
        raise TransformRuntimeError(f"{name}() requires a number, got "
                                    f"{type(x).__name__}")
    return x


@functools.lru_cache(maxsize=256)
def _compiled_regex(pattern: str) -> "re.Pattern":
    try:
        return re.compile(pattern)
    except re.error as exc:
        raise TransformRuntimeError(f"parse_regex(): bad pattern: {exc}")


def _fn_parse_regex(s, pattern):
    """Named capture groups -> object (VRL parse_regex!); no match is a
    per-doc error, like VRL's abort-on-error default."""
    m = _compiled_regex(_str_arg("parse_regex", pattern)).search(
        _str_arg("parse_regex", s))
    if m is None:
        raise TransformRuntimeError("parse_regex(): no match")
    out = {k: v for k, v in m.groupdict().items() if v is not None}
    if not out:  # positional groups fall back to _0.._n
        out = {f"_{i}": g for i, g in enumerate(m.groups(), 1)
               if g is not None}
    return out


_KV_RE = re.compile(r'([A-Za-z0-9_.\-]+)=("(?:[^"\\]|\\.)*"|\S*)')


def _fn_parse_key_value(s):
    """logfmt-style `k=v k2="quoted v"` -> object (VRL
    parse_key_value!)."""
    out = {}
    for key, raw in _KV_RE.findall(_str_arg("parse_key_value", s)):
        if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
            try:
                out[key] = json.loads(raw)
            except ValueError:
                out[key] = raw[1:-1]
        else:
            out[key] = raw
    return out


_CLF_RE = re.compile(
    r'^(?P<host>\S+) (?P<identity>\S+) (?P<user>\S+) '
    r'\[(?P<timestamp>[^\]]+)\] "(?P<method>\S+) (?P<path>\S+)'
    r'(?: (?P<protocol>[^"]+))?" (?P<status>\d{3}) (?P<size>\d+|-)'
    r'(?: "(?P<referrer>[^"]*)" "(?P<user_agent>[^"]*)")?')


def _fn_parse_common_log(s):
    """Apache common/combined log format -> object (VRL
    parse_common_log! / parse_apache_log!)."""
    m = _CLF_RE.match(_str_arg("parse_common_log", s))
    if m is None:
        raise TransformRuntimeError("parse_common_log(): no match")
    out = {k: v for k, v in m.groupdict().items() if v is not None}
    out["status"] = int(out["status"])
    out["size"] = 0 if out["size"] == "-" else int(out["size"])
    return out


_SYSLOG_RE = re.compile(
    r'^<(?P<pri>\d{1,3})>(?P<timestamp>[A-Z][a-z]{2} [ \d]\d '
    r'\d{2}:\d{2}:\d{2}) (?P<hostname>\S+) '
    r'(?P<appname>[^\[:\s]+)(?:\[(?P<procid>\d+)\])?: ?(?P<message>.*)$')


def _fn_parse_syslog(s):
    """RFC3164 syslog line -> object with facility/severity split out
    (VRL parse_syslog!)."""
    m = _SYSLOG_RE.match(_str_arg("parse_syslog", s))
    if m is None:
        raise TransformRuntimeError("parse_syslog(): no match")
    out = {k: v for k, v in m.groupdict().items() if v is not None}
    pri = int(out.pop("pri"))
    out["facility"] = pri // 8
    out["severity"] = pri % 8
    if "procid" in out:
        out["procid"] = int(out["procid"])
    return out


def _fn_parse_url(s):
    parts = urlsplit(_str_arg("parse_url", s))
    out: dict[str, Any] = {"scheme": parts.scheme, "host": parts.hostname,
                           "path": parts.path}
    if parts.port is not None:
        out["port"] = parts.port
    if parts.query:
        out["query"] = dict(parse_qsl(parts.query))
    if parts.fragment:
        out["fragment"] = parts.fragment
    return out


_TS_FORMATS = ("%Y-%m-%dT%H:%M:%S.%f%z", "%Y-%m-%dT%H:%M:%S%z",
               "%Y-%m-%dT%H:%M:%S.%f", "%Y-%m-%dT%H:%M:%S",
               "%Y-%m-%d %H:%M:%S.%f", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d")


def _fn_to_unix_timestamp(x):
    """Epoch seconds from a number (pass-through) or an RFC3339-ish
    string (VRL to_unix_timestamp)."""
    if isinstance(x, (int, float)) and not isinstance(x, bool):
        return int(x)
    text = _str_arg("to_unix_timestamp", x).replace("Z", "+00:00")
    for fmt in _TS_FORMATS:
        try:
            parsed = _dt.datetime.strptime(text, fmt)
        except ValueError:
            continue
        if parsed.tzinfo is None:
            parsed = parsed.replace(tzinfo=_dt.timezone.utc)
        return int(parsed.timestamp())
    raise TransformRuntimeError(
        f"to_unix_timestamp(): unrecognized timestamp {x!r}")


def _fn_parse_timestamp(s, fmt):
    """strptime with an explicit format -> epoch seconds (VRL
    parse_timestamp!)."""
    try:
        parsed = _dt.datetime.strptime(_str_arg("parse_timestamp", s),
                                       _str_arg("parse_timestamp", fmt))
    except ValueError as exc:
        raise TransformRuntimeError(f"parse_timestamp(): {exc}")
    if parsed.tzinfo is None:
        parsed = parsed.replace(tzinfo=_dt.timezone.utc)
    return int(parsed.timestamp())


def _fn_format_timestamp(ts, fmt):
    """Epoch seconds -> string via strftime, UTC (VRL
    format_timestamp!)."""
    try:
        moment = _dt.datetime.fromtimestamp(
            _num_arg("format_timestamp", ts), tz=_dt.timezone.utc)
    except (OverflowError, OSError, ValueError) as exc:
        raise TransformRuntimeError(f"format_timestamp(): {exc}")
    return moment.strftime(_str_arg("format_timestamp", fmt))


def _fn_slice(x, start, end):
    lo = int(_num_arg("slice", start))
    hi = int(_num_arg("slice", end))
    if isinstance(x, (str, list)):
        return x[lo:hi]
    raise TransformRuntimeError(
        f"slice() requires string/array, got {type(x).__name__}")


def _fn_push(arr, value):
    if not isinstance(arr, list):
        raise TransformRuntimeError("push() requires an array")
    return arr + [value]


def _fn_merge(a, b):
    if not isinstance(a, dict) or not isinstance(b, dict):
        raise TransformRuntimeError("merge() requires two objects")
    return {**a, **b}


_FUNCTIONS: dict[str, tuple[int, Callable]] = {
    "string": (1, _fn_string),
    "int": (1, _fn_int),
    "float": (1, _fn_float),
    "bool": (1, lambda x: bool(x)),
    "lowercase": (1, lambda x: _str_arg("lowercase", x).lower()),
    "uppercase": (1, lambda x: _str_arg("uppercase", x).upper()),
    # VRL spells these downcase/upcase — both spellings resolve
    "downcase": (1, lambda x: _str_arg("downcase", x).lower()),
    "upcase": (1, lambda x: _str_arg("upcase", x).upper()),
    "trim": (1, lambda x: _str_arg("trim", x).strip()),
    "replace": (3, lambda s, a, b: _str_arg("replace", s).replace(
        _str_arg("replace", a), _str_arg("replace", b))),
    "contains": (2, lambda s, sub: _str_arg("contains", sub)
                 in _str_arg("contains", s)),
    "starts_with": (2, lambda s, p: _str_arg("starts_with", s).startswith(
        _str_arg("starts_with", p))),
    "ends_with": (2, lambda s, p: _str_arg("ends_with", s).endswith(
        _str_arg("ends_with", p))),
    "split": (2, lambda s, sep: _str_arg("split", s).split(
        _str_arg("split", sep))),
    "join": (2, _fn_join),
    "length": (1, _fn_length),
    "now": (0, lambda: int(time.time())),
    "parse_json": (1, _fn_parse_json),
    "encode_json": (1, lambda x: json.dumps(x)),
    # numeric (round is half-away-from-zero like VRL, not Python's
    # banker's rounding: round(2.5) == 3, round(-2.5) == -3)
    "round": (1, lambda x: math.floor(_num_arg("round", x) + 0.5)
              if _num_arg("round", x) >= 0
              else math.ceil(_num_arg("round", x) - 0.5)),
    "floor": (1, lambda x: math.floor(_num_arg("floor", x))),
    "ceil": (1, lambda x: math.ceil(_num_arg("ceil", x))),
    "abs": (1, lambda x: abs(_num_arg("abs", x))),
    # strings / arrays / objects
    "slice": (3, _fn_slice),
    "truncate": (2, lambda s, n: _str_arg("truncate", s)
                 [: int(_num_arg("truncate", n))]),
    "push": (2, _fn_push),
    "merge": (2, _fn_merge),
    # hashes (hex digests, VRL md5/sha1/sha2)
    "md5": (1, lambda x: hashlib.md5(
        _str_arg("md5", x).encode()).hexdigest()),
    "sha1": (1, lambda x: hashlib.sha1(
        _str_arg("sha1", x).encode()).hexdigest()),
    "sha256": (1, lambda x: hashlib.sha256(
        _str_arg("sha256", x).encode()).hexdigest()),
    # timestamps
    "to_unix_timestamp": (1, _fn_to_unix_timestamp),
    "parse_timestamp": (2, _fn_parse_timestamp),
    "format_timestamp": (2, _fn_format_timestamp),
    # structured parsers
    "parse_regex": (2, _fn_parse_regex),
    "parse_key_value": (1, _fn_parse_key_value),
    "parse_common_log": (1, _fn_parse_common_log),
    "parse_syslog": (1, _fn_parse_syslog),
    "parse_url": (1, _fn_parse_url),
}


def _get_path(doc: dict, parts: tuple[str, ...]):
    cur: Any = doc
    for p in parts:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(p)
    return cur


def _set_path(doc: dict, parts: tuple[str, ...], value) -> None:
    cur = doc
    for p in parts[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[p] = nxt
        cur = nxt
    cur[parts[-1]] = value


def _del_path(doc: dict, parts: tuple[str, ...]) -> None:
    cur: Any = doc
    for p in parts[:-1]:
        if not isinstance(cur, dict):
            return
        cur = cur.get(p)
    if isinstance(cur, dict):
        cur.pop(parts[-1], None)


def _binop(op: str, a, b):
    try:
        if op == "+":
            if isinstance(a, str) or isinstance(b, str):
                return _fn_string(a) + _fn_string(b)
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return a / b
        if op == "%":
            return a % b
        if op == "==":
            return a == b
        if op == "!=":
            return a != b
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
    except (TypeError, ZeroDivisionError) as exc:
        raise TransformRuntimeError(f"{op!r}: {exc}")
    raise TransformRuntimeError(f"unknown operator {op!r}")


# --------------------------------------------------------------------------
# parser: recursive descent → closures over the doc

class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.tokens = tokens
        self.i = 0

    def peek(self) -> tuple[str, str]:
        return self.tokens[self.i]

    def next(self) -> tuple[str, str]:
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def expect(self, value: str) -> None:
        kind, got = self.next()
        if got != value:
            raise TransformParseError(f"expected {value!r}, got {got!r}")

    def skip_newlines(self) -> None:
        while self.peek()[0] == "newline":
            self.next()

    # --- statements -------------------------------------------------------
    def parse_block(self, until: Optional[str]) -> Callable[[dict], None]:
        stmts: list[Callable[[dict], None]] = []
        self.skip_newlines()
        while True:
            kind, value = self.peek()
            if kind == "eof" or (until is not None and value == until):
                break
            stmts.append(self.parse_statement())
            self.skip_newlines()

        def run(doc: dict) -> None:
            for stmt in stmts:
                stmt(doc)
        return run

    def parse_statement(self) -> Callable[[dict], None]:
        kind, value = self.peek()
        if kind == "ident" and value == "if":
            return self.parse_if()
        if kind == "ident" and value == "del":
            self.next()
            self.expect("(")
            pkind, pval = self.next()
            if pkind != "path":
                raise TransformParseError("del() takes a field path")
            self.expect(")")
            parts = tuple(pval[1:].split("."))
            return lambda doc: _del_path(doc, parts)
        if kind == "ident" and value == "drop":
            self.next()
            self.expect("(")
            self.expect(")")
            def do_drop(doc: dict) -> None:
                raise _Drop()
            return do_drop
        if kind == "path":
            self.next()
            parts = tuple(value[1:].split("."))
            self.expect("=")
            expr = self.parse_expr()
            return lambda doc: _set_path(doc, parts, expr(doc))
        raise TransformParseError(f"unexpected token {value!r}")

    def parse_if(self) -> Callable[[dict], None]:
        self.next()  # 'if'
        cond = self.parse_expr()
        self.expect("{")
        then_block = self.parse_block(until="}")
        self.expect("}")
        else_block: Optional[Callable[[dict], None]] = None
        self.skip_newlines()
        if self.peek() == ("ident", "else"):
            self.next()
            self.expect("{")
            else_block = self.parse_block(until="}")
            self.expect("}")

        def run(doc: dict) -> None:
            if cond(doc):
                then_block(doc)
            elif else_block is not None:
                else_block(doc)
        return run

    # --- expressions (precedence climbing) --------------------------------
    def parse_expr(self) -> Callable[[dict], Any]:
        return self.parse_or()

    def parse_or(self) -> Callable[[dict], Any]:
        left = self.parse_and()
        while self.peek()[1] == "||":
            self.next()
            right = self.parse_and()
            prev = left
            left = lambda doc, a=prev, b=right: bool(a(doc)) or bool(b(doc))
        return left

    def parse_and(self) -> Callable[[dict], Any]:
        left = self.parse_cmp()
        while self.peek()[1] == "&&":
            self.next()
            right = self.parse_cmp()
            prev = left
            left = lambda doc, a=prev, b=right: bool(a(doc)) and bool(b(doc))
        return left

    def parse_cmp(self) -> Callable[[dict], Any]:
        left = self.parse_add()
        while self.peek()[1] in ("==", "!=", "<", "<=", ">", ">="):
            op = self.next()[1]
            right = self.parse_add()
            prev = left
            left = lambda doc, a=prev, b=right, o=op: _binop(o, a(doc), b(doc))
        return left

    def parse_add(self) -> Callable[[dict], Any]:
        left = self.parse_mul()
        while self.peek()[1] in ("+", "-"):
            op = self.next()[1]
            right = self.parse_mul()
            prev = left
            left = lambda doc, a=prev, b=right, o=op: _binop(o, a(doc), b(doc))
        return left

    def parse_mul(self) -> Callable[[dict], Any]:
        left = self.parse_unary()
        while self.peek()[1] in ("*", "/", "%"):
            op = self.next()[1]
            right = self.parse_unary()
            prev = left
            left = lambda doc, a=prev, b=right, o=op: _binop(o, a(doc), b(doc))
        return left

    def parse_unary(self) -> Callable[[dict], Any]:
        kind, value = self.peek()
        if value == "!":
            self.next()
            inner = self.parse_unary()
            return lambda doc: not inner(doc)
        if value == "-":
            self.next()
            inner = self.parse_unary()
            return lambda doc: _binop("-", 0, inner(doc))
        return self.parse_primary()

    def parse_primary(self) -> Callable[[dict], Any]:
        kind, value = self.next()
        if kind == "number":
            num = float(value) if "." in value else int(value)
            return lambda doc: num
        if kind == "string":
            try:
                text = json.loads(value)  # handles escapes
            except ValueError as exc:
                raise TransformParseError(f"bad string literal {value}: {exc}")
            return lambda doc: text
        if kind == "path":
            parts = tuple(value[1:].split("."))
            return lambda doc: _get_path(doc, parts)
        if kind == "ident":
            if value == "true":
                return lambda doc: True
            if value == "false":
                return lambda doc: False
            if value == "null":
                return lambda doc: None
            if value in ("if", "else"):
                raise TransformParseError(f"{value!r} is not an expression")
            return self.parse_call(value)
        if value == "(":
            inner = self.parse_expr()
            self.expect(")")
            return inner
        raise TransformParseError(f"unexpected token {value!r} in expression")

    def parse_call(self, name: str) -> Callable[[dict], Any]:
        if name == "exists":
            self.expect("(")
            pkind, pval = self.next()
            if pkind != "path":
                raise TransformParseError("exists() takes a field path")
            self.expect(")")
            parts = tuple(pval[1:].split("."))
            return lambda doc: _get_path(doc, parts) is not None
        if name not in _FUNCTIONS:
            raise TransformParseError(f"unknown function {name!r}")
        arity, fn = _FUNCTIONS[name]
        self.expect("(")
        args: list[Callable[[dict], Any]] = []
        if self.peek()[1] != ")":
            args.append(self.parse_expr())
            while self.peek()[1] == ",":
                self.next()
                args.append(self.parse_expr())
        self.expect(")")
        if len(args) != arity:
            raise TransformParseError(
                f"{name}() takes {arity} argument(s), got {len(args)}")
        return lambda doc: fn(*(a(doc) for a in args))


# --------------------------------------------------------------------------

class Transform:
    """A compiled transform script: `apply(doc)` returns the transformed doc
    (a copy — the input is never mutated) or None when drop()ped."""

    def __init__(self, script: str):
        self.script = script
        parser = _Parser(_tokenize(script))
        self._program = parser.parse_block(until=None)
        if parser.peek()[0] != "eof":
            raise TransformParseError(
                f"trailing tokens at {parser.peek()[1]!r}")

    def apply(self, doc: dict, copy: bool = True) -> Optional[dict]:
        if not isinstance(doc, dict):
            # typed, so the pipeline counts the doc invalid instead of
            # crashing the whole drain pass on one malformed record
            raise TransformRuntimeError(
                f"document must be a JSON object, got {type(doc).__name__}")
        # copy=False lets the ingest hot path skip the deep copy when the
        # caller discards the input anyway (the pipeline does)
        out = (json.loads(json.dumps(doc)) if copy else doc) if doc else {}
        try:
            self._program(out)
        except _Drop:
            return None
        except TransformRuntimeError:
            raise
        except Exception as exc:  # noqa: BLE001 - stdlib leaks (OverflowError,
            # ValueError from split("") etc.) must stay per-doc failures,
            # never abort the whole drain pass
            raise TransformRuntimeError(f"{type(exc).__name__}: {exc}")
        return out


def transform_script_of(params) -> Optional[str]:
    """Extract the raw script from a SourceConfig's params, or None.
    The single source of truth for the `transform` param shape."""
    if not isinstance(params, dict):
        if params:
            raise TransformParseError("source params must be a JSON object")
        return None
    spec = params.get("transform")
    if not spec:
        return None
    script = spec.get("script") if isinstance(spec, dict) else spec
    if not isinstance(script, str) or not script.strip():
        raise TransformParseError("transform requires a script string")
    return script


def transform_from_source_params(params) -> Optional[Transform]:
    """`transform: {script: ...}` in a SourceConfig's params (reference:
    `TransformConfig` on the source, doc_processor.rs:94)."""
    script = transform_script_of(params)
    return Transform(script) if script is not None else None
