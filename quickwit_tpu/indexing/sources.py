"""Document sources.

Role of the reference's `Source` trait + implementations
(`quickwit-indexing/src/source/mod.rs:242`): pull-based batch emitters with
per-partition checkpoint positions. Implemented: `VecSource` (tests),
`FileSource` (ndjson, one partition per file), `VoidSource`, and the
ingest-WAL source lives in `ingest/` (shard fetch streams). Kafka/Kinesis/
Pulsar/SQS are interface-compatible stubs raising a clear error (their SDKs
are not in this image; the queue-source coordinator pattern of the reference
maps onto `Source` one-to-one).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

from ..metastore.checkpoint import (
    BEGINNING, CheckpointDelta, SourceCheckpoint, offset_position,
)


@dataclass
class SourceBatch:
    docs: list[dict]
    checkpoint_delta: CheckpointDelta
    force_commit: bool = False


class Source:
    """Pull-based source: `batches()` yields until exhausted (bounded
    sources) or forever (streaming sources)."""

    def batches(self, checkpoint: SourceCheckpoint,
                batch_num_docs: int = 10_000) -> Iterator[SourceBatch]:
        raise NotImplementedError

    def partition_ids(self) -> list[str]:
        return []


class VecSource(Source):
    """In-memory doc list, single partition (reference `vec_source.rs`)."""

    def __init__(self, docs: list[dict], partition_id: str = "vec"):
        self.docs = docs
        self.partition_id = partition_id

    def batches(self, checkpoint: SourceCheckpoint,
                batch_num_docs: int = 10_000) -> Iterator[SourceBatch]:
        position = checkpoint.position_for(self.partition_id)
        start = int(position) if position != BEGINNING else 0
        for begin in range(start, len(self.docs), batch_num_docs):
            end = min(begin + batch_num_docs, len(self.docs))
            # positions count processed docs: from == previous batch's end
            delta = CheckpointDelta.from_range(
                self.partition_id,
                BEGINNING if begin == 0 else offset_position(begin),
                offset_position(end))
            yield SourceBatch(self.docs[begin:end], delta)

    def partition_ids(self) -> list[str]:
        return [self.partition_id]


class FileSource(Source):
    """One ndjson file = one partition; position = byte offset
    (reference `file_source.rs`)."""

    def __init__(self, path: str):
        self.path = path
        self.partition_id = f"file:{os.path.abspath(path)}"

    def batches(self, checkpoint: SourceCheckpoint,
                batch_num_docs: int = 10_000) -> Iterator[SourceBatch]:
        position = checkpoint.position_for(self.partition_id)
        start_offset = int(position) if position != BEGINNING else 0
        with open(self.path, "rb") as f:
            f.seek(start_offset)
            docs: list[dict] = []
            batch_start = start_offset
            while True:
                line = f.readline()
                if not line:
                    break
                stripped = line.strip()
                if stripped:
                    try:
                        docs.append(json.loads(stripped))
                    except json.JSONDecodeError:
                        docs.append({"_malformed": stripped.decode("utf-8", "replace")})
                if len(docs) >= batch_num_docs:
                    end_offset = f.tell()
                    yield self._batch(docs, batch_start, end_offset)
                    docs, batch_start = [], end_offset
            if docs:
                yield self._batch(docs, batch_start, f.tell())

    def _batch(self, docs: list[dict], start: int, end: int) -> SourceBatch:
        delta = CheckpointDelta.from_range(
            self.partition_id,
            BEGINNING if start == 0 else offset_position(start),
            offset_position(end))
        return SourceBatch(docs, delta)

    def partition_ids(self) -> list[str]:
        return [self.partition_id]


class VoidSource(Source):
    def batches(self, checkpoint: SourceCheckpoint,
                batch_num_docs: int = 10_000) -> Iterator[SourceBatch]:
        return iter(())


_UNSUPPORTED = {"pulsar", "gcp_pubsub"}


def parse_source_config(spec: Any) -> "Any":
    """Validated spec dict -> SourceConfig — the ONE place the REST
    route and the CLI share for defaults + config-time transform-script
    validation (reference: `source_config/mod.rs` deserialization).
    Raises ValueError (HTTP 400 at the REST boundary)."""
    from ..models.index_metadata import SourceConfig
    from .transform import transform_from_source_params
    if not isinstance(spec, dict):
        raise ValueError("source config must be a JSON/YAML object")
    if not isinstance(spec.get("source_id"), str):
        raise ValueError("source requires a string source_id")
    source = SourceConfig(
        source_id=spec["source_id"],
        source_type=spec.get("source_type", "vec"),
        params=spec.get("params", {}),
        enabled=spec.get("enabled", True))
    # reject bad transform scripts at config time, not ingest time
    transform_from_source_params(source.params)
    return source


def make_source(source_type: str, params: dict[str, Any],
                resolver=None) -> Source:
    """`resolver`: storage resolver for sources that FETCH notified
    objects (sqs); ignored by stream sources."""
    if source_type == "vec":
        return VecSource(params.get("docs", []), params.get("partition_id", "vec"))
    if source_type == "file":
        return FileSource(params["filepath"])
    if source_type == "void":
        return VoidSource()
    if source_type == "kafka":
        # reference SourceParams::Kafka shape: topic + librdkafka-style
        # client_params carrying bootstrap.servers
        from .kafka import KafkaSource
        servers = (params.get("client_params", {})
                   .get("bootstrap.servers")
                   or params.get("bootstrap_servers"))
        if not servers:
            raise ValueError(
                "kafka source requires client_params[\"bootstrap.servers\"]")
        if isinstance(servers, str):
            servers = [s.strip() for s in servers.split(",") if s.strip()]
        if "topic" not in params:
            raise ValueError("kafka source requires a topic")
        return KafkaSource(servers, params["topic"])
    if source_type == "kinesis":
        # reference SourceParams::Kinesis shape: stream_name + region;
        # endpoint override for non-AWS deployments (and the wire fake)
        from ..storage.s3 import S3Config
        from .kinesis import KinesisSource
        if "stream_name" not in params:
            raise ValueError("kinesis source requires a stream_name")
        # credentials: environment first (AWS_ACCESS_KEY_ID / ... — the
        # normal deployment shape), explicit params override (tests,
        # non-AWS endpoints)
        import dataclasses
        base = S3Config.from_env()
        region = params.get("region") or base.region or "us-east-1"
        endpoint = (params.get("endpoint")
                    or f"https://kinesis.{region}.amazonaws.com")
        config = dataclasses.replace(
            base, region=region,
            access_key=params.get("access_key", base.access_key),
            secret_key=params.get("secret_key", base.secret_key),
            session_token=params.get("session_token", base.session_token))
        return KinesisSource(endpoint, params["stream_name"], config)
    if source_type == "sqs":
        # reference SourceParams::Sqs shape: queue_url (+ region);
        # notifications carry the files to ingest
        import dataclasses

        from ..storage.s3 import S3Config
        from .sqs import SqsFileSource
        if "queue_url" not in params:
            raise ValueError("sqs source requires a queue_url")
        base = S3Config.from_env()
        region = params.get("region") or base.region or "us-east-1"
        endpoint = (params.get("endpoint")
                    or f"https://sqs.{region}.amazonaws.com")
        config = dataclasses.replace(
            base, region=region,
            access_key=params.get("access_key", base.access_key),
            secret_key=params.get("secret_key", base.secret_key),
            session_token=params.get("session_token", base.session_token))
        return SqsFileSource(endpoint, params["queue_url"], config,
                             resolver=resolver)
    if source_type in _UNSUPPORTED:
        raise NotImplementedError(
            f"source type {source_type!r} requires an external client SDK not "
            "available in this build; use 'file', 'vec', 'kafka', or the "
            "ingest API")
    raise ValueError(f"unknown source type {source_type!r}")


class IngestSource(Source):
    """WAL-shard source: streams records from the local Ingester's shards of
    one (index, source) with per-shard checkpoint positions (reference:
    `quickwit-indexing/src/source/ingest/mod.rs` reading ingester fetch
    streams; partitions == shard queue ids, positions == WAL record
    positions)."""

    def __init__(self, ingester, index_uid: str, source_id: str):
        self.ingester = ingester
        self.index_uid = index_uid
        self.source_id = source_id

    def partition_ids(self) -> list[str]:
        return [s.shard_id for s in self.ingester.list_shards(self.index_uid)
                if s.source_id == self.source_id]

    def batches(self, checkpoint: SourceCheckpoint,
                batch_num_docs: int = 10_000) -> Iterator[SourceBatch]:
        for shard in list(self.ingester.list_shards(self.index_uid)):
            if shard.source_id != self.source_id:
                continue
            current = checkpoint.position_for(shard.shard_id)
            start = 0 if current == BEGINNING else int(current)
            from_pos = current
            while True:
                records = self.ingester.fetch(
                    self.index_uid, self.source_id, shard.shard_id,
                    from_position=start, max_records=batch_num_docs)
                if not records:
                    break
                docs = [doc for _, doc in records]
                last = records[-1][0]
                delta = CheckpointDelta.from_range(
                    shard.shard_id, from_pos, offset_position(last + 1))
                yield SourceBatch(docs, delta)
                start = last + 1
                from_pos = offset_position(start)
