"""Shared AWS JSON-protocol client (Kinesis, SQS) on stdlib HTTP.

One copy of the signed-call machinery: x-amz-json target protocol over a
persistent `http.client` connection, SigV4 via the canonical signer from
storage/s3.py, and the retry envelope (transient 5xx + service throttle
types back off and retry; a dead kept-alive connection re-dials once per
attempt)."""

from __future__ import annotations

import hashlib
import http.client
import json
import time
from typing import Any, Optional
from urllib.parse import urlparse

from ..storage.s3 import S3Config, sigv4_headers


class AwsApiError(RuntimeError):
    def __init__(self, message: str, error_type: Optional[str] = None):
        super().__init__(message)
        self.error_type = error_type


class AwsJsonClient:
    """Subclasses set `service` (SigV4 scope), `target_prefix`
    ("Kinesis_20131202" / "AmazonSQS"), `content_type`,
    `retryable_types` (service throttle __type names), and
    `error_class` (the service-specific AwsApiError subclass every
    failure surfaces as)."""

    service = "aws"
    target_prefix = ""
    content_type = "application/x-amz-json-1.1"
    retryable_types: tuple[str, ...] = ()
    error_class = AwsApiError
    _RETRYABLE_STATUS = (500, 502, 503, 504)
    _MAX_ATTEMPTS = 3

    def __init__(self, endpoint: str, config: S3Config,
                 timeout: float = 30.0):
        parsed = urlparse(endpoint if "//" in endpoint
                          else f"http://{endpoint}")
        self.scheme = parsed.scheme or "http"
        self.host = parsed.hostname or endpoint
        self.port = parsed.port or (443 if self.scheme == "https" else 80)
        self.config = config
        self.timeout = timeout
        self._conn: Optional[http.client.HTTPConnection] = None

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is None:
            cls = (http.client.HTTPSConnection if self.scheme == "https"
                   else http.client.HTTPConnection)
            self._conn = cls(self.host, self.port, timeout=self.timeout)
        return self._conn

    def call(self, action: str, payload: dict[str, Any]) -> dict[str, Any]:
        body = json.dumps(payload).encode()
        host_header = (self.host if self.port in (80, 443)
                       else f"{self.host}:{self.port}")
        headers = sigv4_headers(
            "POST", host_header, "/", [],
            hashlib.sha256(body).hexdigest(), self.config,
            extra_headers={
                "content-type": self.content_type,
                "x-amz-target": f"{self.target_prefix}.{action}",
            },
            service=self.service)
        last_error: Optional[AwsApiError] = None
        for attempt in range(1, self._MAX_ATTEMPTS + 1):
            try:
                conn = self._connection()
                conn.request("POST", "/", body=body, headers=headers)
                response = conn.getresponse()
                raw = response.read()
            except (http.client.HTTPException, OSError) as exc:
                self.close()
                last_error = self.error_class(
                    f"{self.service} transport error: {exc}")
                if attempt == self._MAX_ATTEMPTS:
                    raise last_error
                time.sleep(0.05 * attempt)
                continue
            try:
                decoded = json.loads(raw) if raw else {}
            except ValueError:
                decoded = {}  # proxy HTML error page etc: status rules
            if response.status == 200:
                return decoded
            error_type = (decoded.get("__type") or "").split("#")[-1]
            last_error = self.error_class(
                decoded.get("message") or decoded.get("Message")
                or f"{self.service} call {action} failed: "
                   f"{response.status}",
                error_type=error_type or None)
            if (response.status in self._RETRYABLE_STATUS
                    or error_type in self.retryable_types) \
                    and attempt < self._MAX_ATTEMPTS:
                time.sleep(0.05 * attempt)
                continue
            raise last_error
        raise last_error  # unreachable; keeps the type checker honest
