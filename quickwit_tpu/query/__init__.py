from .ast import (
    Bool,
    Boost,
    FieldPresence,
    FullText,
    MatchAll,
    MatchNone,
    PhrasePrefix,
    QueryAst,
    Range,
    RangeBound,
    Regex,
    Term,
    TermSet,
    Wildcard,
    ast_from_dict,
)
from .parser import parse_query_string
from .tokenizers import get_tokenizer

__all__ = [
    "QueryAst", "Term", "TermSet", "FullText", "PhrasePrefix", "Wildcard",
    "Regex", "Range", "RangeBound", "Bool", "Boost", "MatchAll", "MatchNone",
    "FieldPresence", "ast_from_dict", "parse_query_string", "get_tokenizer",
]
