"""Aggregation request model (ES-compatible subset).

Role of the reference's aggregation proxy types (`quickwit-query/src/
aggregations.rs` + tantivy's aggregation request JSON): parses the ES
`aggs` request dict into typed specs the leaf executor lowers onto columnar
kernels (`ops/aggs.py`).

Supported: date_histogram (fixed_interval), histogram, terms, range,
composite (terms/histogram/date_histogram sources, after-pagination,
missing_bucket), avg/min/max/sum/stats/extended_stats/value_count,
percentiles, cardinality. Sub-aggregations: metrics (percentiles
included) under buckets at ANY depth, with ARBITRARY bucket nesting —
multiple sibling bucket children per level, each chain flattened into a
mixed-radix device bucket space (reference: tantivy's recursive
aggregation tree, collector.rs:523). Composite takes metric sub-aggs
(segment-reduced per run on device); range accepts metrics but no
bucket children.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional

_INTERVAL_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d)$")
_INTERVAL_MICROS = {"ms": 1_000, "s": 1_000_000, "m": 60_000_000,
                    "h": 3_600_000_000, "d": 86_400_000_000}


class AggParseError(ValueError):
    pass


def parse_interval_micros(text: str) -> int:
    m = _INTERVAL_RE.match(text.strip())
    if not m:
        raise AggParseError(f"unsupported interval {text!r} (use e.g. 30s, 5m, 1d)")
    return int(float(m.group(1)) * _INTERVAL_MICROS[m.group(2)])


DEFAULT_PERCENTS = (1, 5, 25, 50, 75, 95, 99)


@dataclass(frozen=True)
class MetricAgg:
    name: str
    kind: str          # avg | min | max | sum | stats | value_count | percentiles
    field: str
    percents: tuple[float, ...] = DEFAULT_PERCENTS
    keyed: bool = True  # percentiles output shape (ES `keyed` param)


@dataclass(frozen=True)
class DateHistogramAgg:
    name: str
    field: str
    interval_micros: int
    min_doc_count: int = 0
    extended_bounds: Optional[tuple[int, int]] = None  # micros
    offset_micros: int = 0  # ES `offset`: shifts bucket boundaries
    sub_metrics: tuple[MetricAgg, ...] = ()
    sub_buckets: tuple["AggSpec", ...] = ()


@dataclass(frozen=True)
class RangeAgg:
    """ES range aggregation: explicit [from, to) buckets, all emitted."""
    name: str
    field: str
    ranges: tuple[tuple[str, Optional[float], Optional[float]], ...]
    sub_metrics: tuple[MetricAgg, ...] = ()


@dataclass(frozen=True)
class HistogramAgg:
    name: str
    field: str
    interval: float
    min_doc_count: int = 0
    sub_metrics: tuple[MetricAgg, ...] = ()
    sub_buckets: tuple["AggSpec", ...] = ()


@dataclass(frozen=True)
class TermsAgg:
    name: str
    field: str
    size: int = 10
    min_doc_count: int = 1
    order_by_count_desc: bool = True
    # ES terms ordering target: "_count" (default), "_key", or the name
    # of a single-value sub-metric ("m" or "m.max" for stats fields)
    order_target: str = "_count"
    # per-split truncation (reference/tantivy `split_size`/`shard_size`):
    # each split forwards only its top-N buckets; the merge reports
    # doc_count_error_upper_bound accordingly. None = exact.
    split_size: Optional[int] = None
    sub_metrics: tuple[MetricAgg, ...] = ()
    sub_buckets: tuple["AggSpec", ...] = ()


@dataclass(frozen=True)
class CompositeSource:
    """One source of a composite aggregation key tuple."""
    name: str
    kind: str                     # "terms" | "histogram" | "date_histogram"
    field: str
    interval: float = 0.0         # histogram
    interval_micros: int = 0      # date_histogram
    missing_bucket: bool = False  # honored on every source kind (as in ES)


@dataclass(frozen=True)
class CompositeAgg:
    """ES composite aggregation: paginated buckets over multi-source key
    tuples in ascending lexicographic key order (`after` resumes strictly
    past a key tuple)."""
    name: str
    sources: tuple[CompositeSource, ...]
    size: int = 10
    after: Optional[tuple[Any, ...]] = None  # decoded per-source values
    sub_metrics: tuple[MetricAgg, ...] = ()
    sub_buckets: tuple["AggSpec", ...] = ()


AggSpec = Any  # union of the dataclasses above


_METRIC_KINDS = ("avg", "min", "max", "sum", "stats", "extended_stats",
                 "value_count", "percentiles", "cardinality")


def _parse_metric(name: str, kind: str, body: dict[str, Any]) -> MetricAgg:
    if not isinstance(body, dict):
        raise AggParseError(
            f"aggregation {name!r}: {kind} body must be an object")
    if "field" not in body:
        raise AggParseError(f"aggregation {name!r}: metric {kind} requires a field")
    if not isinstance(body.get("field"), str):
        raise AggParseError(
            f"aggregation {name!r}: field must be a string")
    raw_percents = body.get("percents", DEFAULT_PERCENTS)
    if not isinstance(raw_percents, (list, tuple)) or not all(
            isinstance(p, (int, float)) and not isinstance(p, bool)
            for p in raw_percents):
        raise AggParseError(
            f"aggregation {name!r}: percents must be a list of numbers")
    return MetricAgg(name=name, kind=kind, field=body["field"],
                     percents=tuple(float(p) for p in raw_percents),
                     keyed=body.get("keyed", True))


_BUCKET_KINDS = ("date_histogram", "histogram", "terms", "range")


def _parse_sub_aggs(name: str, sub: dict[str, Any], depth: int = 0):
    """(metrics, sub_buckets). Bucket children may nest arbitrarily deep
    and have siblings; the product of bucket counts along each chain is
    capped at lowering time (MAX_BUCKETS)."""
    metrics = []
    sub_buckets = []
    for sub_name, sub_body in sub.items():
        sub_kind = _agg_kind(sub_body)
        if sub_kind in _METRIC_KINDS:
            metrics.append(_parse_metric(sub_name, sub_kind, sub_body[sub_kind]))
        elif sub_kind == "range":
            # range buckets may overlap, so they have no single per-doc
            # bucket index to extend the mixed-radix space with
            raise AggParseError(
                f"aggregation {name!r}: range cannot nest under bucket "
                "aggregations")
        elif sub_kind in _BUCKET_KINDS:
            sub_buckets.append(_parse_one(sub_name, sub_body, depth=depth + 1))
        else:
            raise AggParseError(
                f"aggregation {name!r}: unsupported sub-aggregation {sub_kind}")
    return tuple(metrics), tuple(sub_buckets)


def _agg_kind(body: dict[str, Any]) -> str:
    kinds = [k for k in body if k not in ("aggs", "aggregations", "meta")]
    if len(kinds) != 1:
        raise AggParseError(f"aggregation body must have exactly one kind, got {kinds}")
    return kinds[0]


def _parse_one(name: str, body: dict[str, Any], depth: int = 0) -> AggSpec:
    if not isinstance(body, dict):
        raise AggParseError(
            f"aggregation {name!r} must be an object")
    kind = _agg_kind(body)
    params = body[kind]
    if kind not in _METRIC_KINDS and not isinstance(params, dict):
        # metric bodies are validated in _parse_metric; bucket bodies
        # must be objects too (ES rejects {"terms": 7} the same way)
        raise AggParseError(
            f"aggregation {name!r}: {kind} body must be an object")
    sub = body.get("aggs") or body.get("aggregations") or {}
    if not isinstance(sub, dict):
        raise AggParseError(
            f"aggregation {name!r}: nested aggs must be an object")
    sub_metrics, sub_buckets = _parse_sub_aggs(name, sub, depth)
    if kind == "date_histogram":
        interval = params.get("fixed_interval") or params.get("interval")
        if interval is None:
            raise AggParseError(f"date_histogram {name!r} requires fixed_interval")
        bounds = None
        if "extended_bounds" in params:
            # ES extended_bounds for date_histogram are epoch MILLISECONDS;
            # bounds_unit="micros" is the internal escape hatch
            b = params["extended_bounds"]
            scale = 1 if params.get("bounds_unit") == "micros" else 1000
            bounds = (int(b["min"]) * scale, int(b["max"]) * scale)
        offset = 0
        if params.get("offset"):
            text = str(params["offset"]).strip()
            sign = -1 if text.startswith("-") else 1
            offset = sign * parse_interval_micros(text.lstrip("+-"))
        return DateHistogramAgg(
            name=name, field=params["field"],
            interval_micros=parse_interval_micros(interval),
            min_doc_count=params.get("min_doc_count", 0),
            extended_bounds=bounds, offset_micros=offset,
            sub_metrics=sub_metrics, sub_buckets=sub_buckets)
    if kind == "histogram":
        return HistogramAgg(
            name=name, field=params["field"], interval=float(params["interval"]),
            min_doc_count=params.get("min_doc_count", 0),
            sub_metrics=sub_metrics, sub_buckets=sub_buckets)
    if kind == "terms":
        order = params.get("order", {"_count": "desc"})
        if not isinstance(order, dict) or len(order) != 1:
            raise AggParseError(
                f"terms aggregation {name!r}: order must be a single-entry "
                "map like {\"_count\": \"desc\"}")
        order_target, order_dir = next(iter(order.items()))
        if order_dir not in ("asc", "desc"):
            raise AggParseError(
                f"terms aggregation {name!r}: order direction must be "
                "asc or desc")
        if order_target not in ("_count", "_key"):
            # the target must resolve to ONE value (ES rejects anything
            # else with a 400; degrading silently would reorder wrong)
            metric_root, _, sub_field = order_target.partition(".")
            metric = next((m for m in sub_metrics
                           if m.name == metric_root), None)
            if metric is None:
                raise AggParseError(
                    f"terms aggregation {name!r}: order target "
                    f"{order_target!r} is not a sub-aggregation")
            single_value = ("avg", "min", "max", "sum", "value_count",
                            "cardinality")
            stats_fields = ("min", "max", "avg", "sum", "count",
                            "sum_of_squares", "variance", "std_deviation")
            if sub_field:
                if metric.kind not in ("stats", "extended_stats") \
                        or sub_field not in stats_fields:
                    raise AggParseError(
                        f"terms aggregation {name!r}: order target "
                        f"{order_target!r} does not resolve to a single "
                        "value")
            elif metric.kind not in single_value:
                raise AggParseError(
                    f"terms aggregation {name!r}: ordering by "
                    f"{metric.kind} requires a field path like "
                    f"\"{metric_root}.max\"")
        split_size = params.get("split_size", params.get(
            "shard_size", params.get("segment_size")))
        return TermsAgg(
            name=name, field=params["field"], size=params.get("size", 10),
            min_doc_count=params.get("min_doc_count", 1),
            order_by_count_desc=order_dir == "desc",
            order_target=order_target,
            split_size=int(split_size) if split_size is not None else None,
            sub_metrics=sub_metrics, sub_buckets=sub_buckets)
    if kind == "range":
        ranges = []
        for r in params.get("ranges", ()):
            lo = float(r["from"]) if "from" in r else None
            hi = float(r["to"]) if "to" in r else None
            key = r.get("key")
            if key is None:  # ES auto key: "from-to" with * for open ends
                key = f"{lo if lo is not None else '*'}-" \
                      f"{hi if hi is not None else '*'}"
            ranges.append((str(key), lo, hi))
        if not ranges:
            raise AggParseError(f"range aggregation {name!r} needs ranges")
        if sub_buckets:
            raise AggParseError(
                f"range aggregation {name!r}: nested bucket aggs under "
                "range are not supported yet")
        return RangeAgg(name=name, field=params["field"],
                        ranges=tuple(ranges), sub_metrics=sub_metrics)
    if kind == "composite":
        if depth > 0:
            raise AggParseError(
                f"composite aggregation {name!r} must be top-level")
        for metric in sub_metrics:
            if metric.kind in ("percentiles", "cardinality"):
                raise AggParseError(
                    f"composite aggregation {name!r}: {metric.kind} under "
                    "composite is not supported yet")
        return _parse_composite(name, params, sub_metrics, sub_buckets)
    if kind in _METRIC_KINDS:
        if sub_metrics or sub_buckets:
            raise AggParseError(f"metric aggregation {name!r} cannot have sub-aggs")
        return _parse_metric(name, kind, params)
    raise AggParseError(f"unsupported aggregation kind {kind!r}")


def _decode_after_value(value: Any, source_kind: str) -> Any:
    """Accept both plain ES after values and tantivy's type-prefixed form
    (`str:x`, `f64:1`, `i64:1`, `u64:1`) emitted by the reference.

    Decoding is source-kind-aware so a plain value is never misread:
    histogram sources take numbers (a bare string must be the typed form);
    terms sources keep strings as-is except the unambiguous prefixes —
    a term field legitimately holding "i64:42" still pages correctly
    because the numeric coercion is re-checked against the dictionary
    type at lowering (plan.py)."""
    if not isinstance(value, str):
        return value
    if source_kind in ("histogram", "date_histogram"):
        for prefix in ("f64:", "i64:", "u64:"):
            if value.startswith(prefix):
                return float(value[len(prefix):])
        try:
            return float(value)
        except ValueError:
            raise AggParseError(
                f"composite after value {value!r} is not numeric for a "
                f"{source_kind} source")
    if value.startswith("str:"):
        return value[4:]
    for prefix in ("f64:",):
        if value.startswith(prefix):
            return float(value[len(prefix):])
    for prefix in ("i64:", "u64:"):
        if value.startswith(prefix):
            return int(value[len(prefix):])
    return value


def _parse_composite(name: str, params: dict[str, Any],
                     sub_metrics: tuple = (),
                     sub_buckets: tuple = ()) -> "CompositeAgg":
    raw_sources = params.get("sources")
    if not raw_sources or not isinstance(raw_sources, list):
        raise AggParseError(
            f"composite aggregation {name!r} requires a sources list")
    sources = []
    for entry in raw_sources:
        if not isinstance(entry, dict) or len(entry) != 1:
            raise AggParseError(
                f"composite {name!r}: each source must be "
                "{name: {kind: {...}}}")
        src_name, src_body = next(iter(entry.items()))
        src_kind = _agg_kind(src_body)
        src_params = src_body[src_kind]
        if src_kind not in ("terms", "histogram", "date_histogram"):
            raise AggParseError(
                f"composite {name!r}: unsupported source kind {src_kind!r}")
        order = src_params.get("order", "asc")
        if order != "asc":
            raise AggParseError(
                f"composite {name!r}: descending source order is not "
                "supported yet")
        if "field" not in src_params:
            raise AggParseError(
                f"composite {name!r}: source {src_name!r} requires a field")
        interval = 0.0
        interval_micros = 0
        if src_kind == "histogram":
            interval = float(src_params["interval"])
            if interval <= 0:
                raise AggParseError(
                    f"composite {name!r}: histogram interval must be > 0")
        elif src_kind == "date_histogram":
            text = (src_params.get("fixed_interval")
                    or src_params.get("interval"))
            if text is None:
                raise AggParseError(
                    f"composite {name!r}: date_histogram source requires "
                    "fixed_interval")
            interval_micros = parse_interval_micros(text)
        sources.append(CompositeSource(
            name=src_name, kind=src_kind, field=src_params["field"],
            interval=interval, interval_micros=interval_micros,
            missing_bucket=bool(src_params.get("missing_bucket", False))))
    after = None
    if "after" in params:
        raw_after = params["after"]
        if not isinstance(raw_after, dict):
            raise AggParseError(f"composite {name!r}: after must be a map")
        missing = [s.name for s in sources if s.name not in raw_after]
        if missing:
            raise AggParseError(
                f"composite {name!r}: after is missing sources {missing}")
        after = tuple(_decode_after_value(raw_after[s.name], s.kind)
                      for s in sources)
    size = int(params.get("size", 10))
    if size < 1 or size > 4096:
        raise AggParseError(
            f"composite {name!r}: size must be in [1, 4096]")
    return CompositeAgg(name=name, sources=tuple(sources), size=size,
                        after=after, sub_metrics=sub_metrics,
                        sub_buckets=sub_buckets)


def parse_aggs(aggs: dict[str, Any]) -> list[AggSpec]:
    """ES `aggs` dict → typed specs."""
    if not isinstance(aggs, dict):
        raise AggParseError("aggs must be an object")
    return [_parse_one(name, body) for name, body in aggs.items()]
