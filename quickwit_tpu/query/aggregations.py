"""Aggregation request model (ES-compatible subset).

Role of the reference's aggregation proxy types (`quickwit-query/src/
aggregations.rs` + tantivy's aggregation request JSON): parses the ES
`aggs` request dict into typed specs the leaf executor lowers onto columnar
kernels (`ops/aggs.py`).

Supported: date_histogram (fixed_interval), histogram, terms,
avg/min/max/sum/stats/value_count, percentiles. Sub-aggregations: metrics
under buckets, plus ONE nested bucket level (e.g. date_histogram > terms)
with its own metrics; deeper nesting raises.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional

_INTERVAL_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d)$")
_INTERVAL_MICROS = {"ms": 1_000, "s": 1_000_000, "m": 60_000_000,
                    "h": 3_600_000_000, "d": 86_400_000_000}


class AggParseError(ValueError):
    pass


def parse_interval_micros(text: str) -> int:
    m = _INTERVAL_RE.match(text.strip())
    if not m:
        raise AggParseError(f"unsupported interval {text!r} (use e.g. 30s, 5m, 1d)")
    return int(float(m.group(1)) * _INTERVAL_MICROS[m.group(2)])


DEFAULT_PERCENTS = (1, 5, 25, 50, 75, 95, 99)


@dataclass(frozen=True)
class MetricAgg:
    name: str
    kind: str          # avg | min | max | sum | stats | value_count | percentiles
    field: str
    percents: tuple[float, ...] = DEFAULT_PERCENTS
    keyed: bool = True  # percentiles output shape (ES `keyed` param)


@dataclass(frozen=True)
class DateHistogramAgg:
    name: str
    field: str
    interval_micros: int
    min_doc_count: int = 0
    extended_bounds: Optional[tuple[int, int]] = None  # micros
    offset_micros: int = 0  # ES `offset`: shifts bucket boundaries
    sub_metrics: tuple[MetricAgg, ...] = ()
    sub_bucket: Optional["AggSpec"] = None


@dataclass(frozen=True)
class RangeAgg:
    """ES range aggregation: explicit [from, to) buckets, all emitted."""
    name: str
    field: str
    ranges: tuple[tuple[str, Optional[float], Optional[float]], ...]
    sub_metrics: tuple[MetricAgg, ...] = ()
    sub_bucket: Optional["AggSpec"] = None


@dataclass(frozen=True)
class HistogramAgg:
    name: str
    field: str
    interval: float
    min_doc_count: int = 0
    sub_metrics: tuple[MetricAgg, ...] = ()
    sub_bucket: Optional["AggSpec"] = None


@dataclass(frozen=True)
class TermsAgg:
    name: str
    field: str
    size: int = 10
    min_doc_count: int = 1
    order_by_count_desc: bool = True
    # per-split truncation (reference/tantivy `split_size`/`shard_size`):
    # each split forwards only its top-N buckets; the merge reports
    # doc_count_error_upper_bound accordingly. None = exact.
    split_size: Optional[int] = None
    sub_metrics: tuple[MetricAgg, ...] = ()
    sub_bucket: Optional["AggSpec"] = None


AggSpec = Any  # union of the four dataclasses above


_METRIC_KINDS = ("avg", "min", "max", "sum", "stats", "extended_stats",
                 "value_count", "percentiles", "cardinality")


def _parse_metric(name: str, kind: str, body: dict[str, Any]) -> MetricAgg:
    if "field" not in body:
        raise AggParseError(f"aggregation {name!r}: metric {kind} requires a field")
    percents = tuple(body.get("percents", DEFAULT_PERCENTS))
    return MetricAgg(name=name, kind=kind, field=body["field"],
                     percents=percents, keyed=body.get("keyed", True))


_BUCKET_KINDS = ("date_histogram", "histogram", "terms", "range")


def _parse_sub_aggs(name: str, sub: dict[str, Any], depth: int = 0):
    """(metrics, sub_bucket|None). One nested bucket level allowed."""
    metrics = []
    sub_bucket = None
    for sub_name, sub_body in sub.items():
        sub_kind = _agg_kind(sub_body)
        if sub_kind == "cardinality":
            raise AggParseError(
                f"aggregation {name!r}: cardinality under bucket "
                "aggregations is not supported yet")
        if sub_kind in _METRIC_KINDS:
            metrics.append(_parse_metric(sub_name, sub_kind, sub_body[sub_kind]))
        elif sub_kind in _BUCKET_KINDS:
            if depth >= 1:
                raise AggParseError(
                    f"aggregation {name!r}: bucket nesting deeper than one "
                    "level is not supported")
            if sub_bucket is not None:
                raise AggParseError(
                    f"aggregation {name!r}: at most one nested bucket "
                    "aggregation is supported")
            sub_bucket = _parse_one(sub_name, sub_body, depth=depth + 1)
        else:
            raise AggParseError(
                f"aggregation {name!r}: unsupported sub-aggregation {sub_kind}")
    return tuple(metrics), sub_bucket


def _agg_kind(body: dict[str, Any]) -> str:
    kinds = [k for k in body if k not in ("aggs", "aggregations", "meta")]
    if len(kinds) != 1:
        raise AggParseError(f"aggregation body must have exactly one kind, got {kinds}")
    return kinds[0]


def _parse_one(name: str, body: dict[str, Any], depth: int = 0) -> AggSpec:
    kind = _agg_kind(body)
    params = body[kind]
    sub = body.get("aggs") or body.get("aggregations") or {}
    sub_metrics, sub_bucket = _parse_sub_aggs(name, sub, depth)
    if kind == "date_histogram":
        interval = params.get("fixed_interval") or params.get("interval")
        if interval is None:
            raise AggParseError(f"date_histogram {name!r} requires fixed_interval")
        bounds = None
        if "extended_bounds" in params:
            # ES extended_bounds for date_histogram are epoch MILLISECONDS;
            # bounds_unit="micros" is the internal escape hatch
            b = params["extended_bounds"]
            scale = 1 if params.get("bounds_unit") == "micros" else 1000
            bounds = (int(b["min"]) * scale, int(b["max"]) * scale)
        offset = 0
        if params.get("offset"):
            text = str(params["offset"]).strip()
            sign = -1 if text.startswith("-") else 1
            offset = sign * parse_interval_micros(text.lstrip("+-"))
        return DateHistogramAgg(
            name=name, field=params["field"],
            interval_micros=parse_interval_micros(interval),
            min_doc_count=params.get("min_doc_count", 0),
            extended_bounds=bounds, offset_micros=offset,
            sub_metrics=sub_metrics, sub_bucket=sub_bucket)
    if kind == "histogram":
        return HistogramAgg(
            name=name, field=params["field"], interval=float(params["interval"]),
            min_doc_count=params.get("min_doc_count", 0),
            sub_metrics=sub_metrics, sub_bucket=sub_bucket)
    if kind == "terms":
        order = params.get("order", {"_count": "desc"})
        split_size = params.get("split_size", params.get(
            "shard_size", params.get("segment_size")))
        return TermsAgg(
            name=name, field=params["field"], size=params.get("size", 10),
            min_doc_count=params.get("min_doc_count", 1),
            order_by_count_desc=order.get("_count", "desc") == "desc",
            split_size=int(split_size) if split_size is not None else None,
            sub_metrics=sub_metrics, sub_bucket=sub_bucket)
    if kind == "range":
        ranges = []
        for r in params.get("ranges", ()):
            lo = float(r["from"]) if "from" in r else None
            hi = float(r["to"]) if "to" in r else None
            key = r.get("key")
            if key is None:  # ES auto key: "from-to" with * for open ends
                key = f"{lo if lo is not None else '*'}-" \
                      f"{hi if hi is not None else '*'}"
            ranges.append((str(key), lo, hi))
        if not ranges:
            raise AggParseError(f"range aggregation {name!r} needs ranges")
        if sub_bucket is not None:
            raise AggParseError(
                f"range aggregation {name!r}: nested bucket aggs under "
                "range are not supported yet")
        return RangeAgg(name=name, field=params["field"],
                        ranges=tuple(ranges), sub_metrics=sub_metrics)
    if kind in _METRIC_KINDS:
        if sub_metrics or sub_bucket:
            raise AggParseError(f"metric aggregation {name!r} cannot have sub-aggs")
        return _parse_metric(name, kind, params)
    raise AggParseError(f"unsupported aggregation kind {kind!r}")


def parse_aggs(aggs: dict[str, Any]) -> list[AggSpec]:
    """ES `aggs` dict → typed specs."""
    return [_parse_one(name, body) for name, body in aggs.items()]
