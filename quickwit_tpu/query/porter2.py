"""Porter2 (English Snowball) stemmer — the algorithm behind tantivy's
`en_stem` (rust-stemmers "english"), implemented faithfully so index
terms are byte-compatible with the reference's.

Spec: snowballstem.org/algorithms/english/stemmer.html. Every rule
below mirrors a clause of the published algorithm; tested against the
standard sample vocabulary pairs.
"""

from __future__ import annotations

_VOWELS = frozenset("aeiouy")
_DOUBLES = ("bb", "dd", "ff", "gg", "mm", "nn", "pp", "rr", "tt")
_LI_ENDINGS = frozenset("cdeghkmnrt")

_EXCEPTIONS = {
    "skis": "ski", "skies": "sky", "dying": "die", "lying": "lie",
    "tying": "tie", "idly": "idl", "gently": "gentl", "ugly": "ugli",
    "early": "earli", "only": "onli", "singly": "singl",
    "sky": "sky", "news": "news", "howe": "howe", "atlas": "atlas",
    "cosmos": "cosmos", "bias": "bias", "andes": "andes",
}
_EXCEPTIONS_1A = frozenset((
    "inning", "outing", "canning", "herring", "earring",
    "proceed", "exceed", "succeed",
))

_STEP2 = (
    ("ization", "ize"), ("ational", "ate"), ("ousness", "ous"),
    ("iveness", "ive"), ("fulness", "ful"), ("biliti", "ble"),
    ("lessli", "less"), ("tional", "tion"), ("ation", "ate"),
    ("alism", "al"), ("aliti", "al"), ("ousli", "ous"),
    ("entli", "ent"), ("fulli", "ful"), ("iviti", "ive"),
    ("enci", "ence"),
    ("anci", "ance"), ("abli", "able"), ("izer", "ize"),
    ("ator", "ate"), ("alli", "al"), ("bli", "ble"),
)
_STEP3 = (
    ("ational", "ate"), ("tional", "tion"), ("alize", "al"),
    ("icate", "ic"), ("iciti", "ic"), ("ical", "ic"),
    ("ful", ""), ("ness", ""),
)
_STEP4 = ("ement", "ance", "ence", "able", "ible", "ment",
          "ant", "ent", "ism", "ate", "iti", "ous", "ive", "ize",
          "al", "er", "ic")


def _is_vowel(word: str, i: int) -> bool:
    return word[i] in _VOWELS


def _regions(word: str) -> tuple[int, int]:
    """(r1, r2) start indexes per the spec (with the gener-/commun-/
    arsen- special cases for R1)."""
    n = len(word)
    r1 = n
    for prefix in ("gener", "commun", "arsen"):
        if word.startswith(prefix):
            r1 = len(prefix)
            break
    else:
        for i in range(1, n):
            if not _is_vowel(word, i) and _is_vowel(word, i - 1):
                r1 = i + 1
                break
    r2 = n
    for i in range(r1 + 1, n):
        if not _is_vowel(word, i) and _is_vowel(word, i - 1):
            r2 = i + 1
            break
    return r1, r2


def _ends_short_syllable(word: str) -> bool:
    """A short syllable at the END of the word: either (a) vowel +
    non-vowel other than w/x/Y preceded by a non-vowel, or (b) a vowel at
    the beginning followed by a non-vowel."""
    n = len(word)
    if n == 2:
        return _is_vowel(word, 0) and not _is_vowel(word, 1)
    if n >= 3:
        return (not _is_vowel(word, n - 3) and _is_vowel(word, n - 2)
                and word[n - 1] not in _VOWELS
                and word[n - 1] not in "wxY")
    return False


def _is_short(word: str, r1: int) -> bool:
    return r1 >= len(word) and _ends_short_syllable(word)


def _has_vowel(word: str, end: int) -> bool:
    return any(_is_vowel(word, i) for i in range(end))


def stem(word: str) -> str:
    if len(word) <= 2:
        return word
    word = word.lower()
    if word in _EXCEPTIONS:
        return _EXCEPTIONS[word]
    if word[0] == "'":
        word = word[1:]
    # mark consonant-y as Y
    if word.startswith("y"):
        word = "Y" + word[1:]
    chars = list(word)
    for i in range(1, len(chars)):
        if chars[i] == "y" and chars[i - 1] in _VOWELS:
            chars[i] = "Y"
    word = "".join(chars)

    r1, r2 = _regions(word)

    # step 0
    for suffix in ("'s'", "'s", "'"):
        if word.endswith(suffix):
            word = word[: -len(suffix)]
            break

    # step 1a
    if word.endswith("sses"):
        word = word[:-2]
    elif word.endswith(("ied", "ies")):
        word = word[:-3] + ("i" if len(word) > 4 else "ie")
    elif word.endswith(("us", "ss")):
        pass
    elif word.endswith("s"):
        if _has_vowel(word, len(word) - 2):
            word = word[:-1]

    if word in _EXCEPTIONS_1A:
        return word

    # step 1b
    if word.endswith(("eedly", "eed")):
        suffix_len = 5 if word.endswith("eedly") else 3
        if len(word) - suffix_len >= r1:  # suffix lies within R1
            word = word[: len(word) - suffix_len] + "ee"
    elif word.endswith(("ingly", "edly", "ing", "ed")):
        for suffix in ("ingly", "edly", "ing", "ed"):
            if word.endswith(suffix):
                stem_part = word[: -len(suffix)]
                if _has_vowel(stem_part, len(stem_part)):
                    word = stem_part
                    if word.endswith(("at", "bl", "iz")):
                        word += "e"
                    elif word.endswith(_DOUBLES):
                        word = word[:-1]
                    elif _is_short(word, r1):
                        word += "e"
                break

    # step 1c
    if (len(word) > 2 and word[-1] in "yY"
            and word[-2] not in _VOWELS):
        word = word[:-1] + "i"

    # step 2 (suffix must be in R1)
    for suffix, repl in _STEP2:
        if word.endswith(suffix):
            if len(word) - len(suffix) >= r1:
                word = word[: -len(suffix)] + repl
            break
    else:
        if word.endswith("ogi"):
            if len(word) - 3 >= r1 and len(word) > 3 and word[-4] == "l":
                word = word[:-1]
        elif word.endswith("li"):
            if len(word) - 2 >= r1 and word[-3] in _LI_ENDINGS:
                word = word[:-2]

    # step 3
    for suffix, repl in _STEP3:
        if word.endswith(suffix):
            if len(word) - len(suffix) >= r1:
                word = word[: -len(suffix)] + repl
            break
    else:
        if word.endswith("ative") and len(word) - 5 >= r2:
            word = word[:-5]

    # step 4 (suffix must be in R2)
    for suffix in _STEP4:
        if word.endswith(suffix):
            if len(word) - len(suffix) >= r2:
                word = word[: -len(suffix)]
            break
    else:
        if word.endswith("ion") and len(word) - 3 >= r2 \
                and len(word) > 3 and word[-4] in "st":
            word = word[:-3]

    # step 5
    if word.endswith("e"):
        if len(word) - 1 >= r2:
            word = word[:-1]
        elif len(word) - 1 >= r1 and not _ends_short_syllable(word[:-1]):
            word = word[:-1]
    elif word.endswith("l") and len(word) - 1 >= r2 and len(word) > 1 \
            and word[-2] == "l":
        word = word[:-1]

    return word.replace("Y", "y")
