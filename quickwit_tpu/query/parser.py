"""Query-string language parser.

Role of the reference's query-language parser (quickwit-query's
`query_string_query` path, itself a mini-Lucene grammar): turns strings like

    severity_text:ERROR AND resource.service:web
    (foo OR bar) -baz tenant_id:[10 TO 20} timestamp:>=2021-01-01T00:00:00Z
    body:"connection refused" field:IN [a b c] *

into a `QueryAst`. Subset implemented: field:term, quoted phrases, AND/OR/NOT,
+/- prefixes, parentheses, range syntax `[a TO b]` / `{a TO b}` and
comparison shorthands (>=, >, <=, <), `IN [..]` term sets, `*` match-all,
`field:*` presence. Bare terms search `default_search_fields`.
"""

from __future__ import annotations

import re
from typing import Optional, Sequence

from .ast import (
    Bool, FieldPresence, FullText, MatchAll, PhrasePrefix, QueryAst, Range,
    RangeBound, Term, TermSet, Wildcard,
)

_TOKEN_RE = re.compile(
    r"""
    \s*(
        \(|\)|                                # parens
        \[|\]|\{|\}|                          # range brackets
        "(?:[^"\\]|\\.)*"|                    # quoted phrase
        AND\b|OR\b|NOT\b|TO\b|IN\b|           # keywords
        [+\-]|                                # occur prefixes
        [^\s()\[\]{}"]+                       # bare word (may contain field:)
    )""",
    re.VERBOSE,
)


class QueryParseError(ValueError):
    pass


def _tokenize(text: str) -> list[str]:
    tokens: list[str] = []
    idx = 0
    while idx < len(text):
        m = _TOKEN_RE.match(text, idx)
        if not m:
            if text[idx:].strip():
                raise QueryParseError(f"cannot tokenize query at: {text[idx:]!r}")
            break
        tokens.append(m.group(1))
        idx = m.end()
    return tokens


class _Parser:
    def __init__(self, tokens: list[str], default_fields: Sequence[str]):
        self.tokens = tokens
        self.pos = 0
        self.default_fields = list(default_fields)

    def peek(self) -> Optional[str]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> str:
        tok = self.peek()
        if tok is None:
            raise QueryParseError("unexpected end of query")
        self.pos += 1
        return tok

    # Grammar: or_expr := and_expr (OR and_expr)*
    #          and_expr := unary (AND? unary)*   (implicit AND on adjacency... like
    #          quickwit, adjacent clauses without operator are `should` clauses)
    def parse(self) -> QueryAst:
        ast = self.parse_or()
        if self.peek() is not None:
            raise QueryParseError(f"trailing tokens: {self.tokens[self.pos:]}")
        return ast

    def parse_or(self) -> QueryAst:
        clauses = [self.parse_and()]
        while self.peek() == "OR":
            self.next()
            clauses.append(self.parse_and())
        if len(clauses) == 1:
            return clauses[0]
        return Bool(should=tuple(clauses))

    def parse_and(self) -> QueryAst:
        # AND promotes only the clauses immediately adjacent to it (Lucene
        # classic semantics): `a:1 b:2 AND c:3` keeps a:1 optional.
        items: list[tuple[str, QueryAst]] = []  # (occur, clause)
        pending_and = False
        while True:
            tok = self.peek()
            if tok is None or tok in (")", "OR"):
                break
            if tok == "AND":
                self.next()
                if items and items[-1][0] == "should":
                    items[-1] = ("must", items[-1][1])
                pending_and = True
                continue
            occur = None
            if tok in ("+", "-"):
                occur = self.next()
                tok = self.peek()
            if tok == "NOT":
                self.next()
                items.append(("must_not", self.parse_unary()))
                pending_and = False
                continue
            clause = self.parse_unary()
            if occur == "+":
                items.append(("must", clause))
            elif occur == "-":
                items.append(("must_not", clause))
            elif pending_and:
                items.append(("must", clause))
            else:
                items.append(("should", clause))
            pending_and = False
        if not items:
            raise QueryParseError("empty clause")
        if len(items) == 1 and items[0][0] in ("must", "should"):
            return items[0][1]
        return Bool(
            must=tuple(c for o, c in items if o == "must"),
            must_not=tuple(c for o, c in items if o == "must_not"),
            should=tuple(c for o, c in items if o == "should"),
        )

    def parse_unary(self) -> QueryAst:
        tok = self.next()
        if tok == "(":
            inner = self.parse_or()
            if self.next() != ")":
                raise QueryParseError("expected ')'")
            return inner
        if tok == "*":
            return MatchAll()
        if tok.startswith('"'):
            return self._phrase(None, tok)
        # field:value?
        field, value = self._split_field(tok)
        if value == "" and field is not None:
            # `field:` followed by complex value token (range, quoted, IN)
            nxt = self.peek()
            if nxt in ("[", "{"):
                return self._range(field)
            if nxt is not None and nxt.startswith('"'):
                return self._phrase(field, self.next())
            if nxt == "IN":
                self.next()
                return self._term_set(field)
            raise QueryParseError(f"missing value for field {field!r}")
        if field is not None:
            if value == "*":
                return FieldPresence(field)
            if value == "IN" and self.peek() == "[":
                return self._term_set(field)
            for op, incl in ((">=", True), ("<=", True), (">", False), ("<", False)):
                if value.startswith(op):
                    bound = RangeBound(value[len(op):], incl)
                    if op.startswith(">"):
                        return Range(field, lower=bound)
                    return Range(field, upper=bound)
            if value.startswith('"'):
                return self._phrase(field, value)
            if value.startswith("'"):
                # single-quoted phrase: `field:'AB CD'` — quotes ride
                # inside bare tokens, so join tokens to the closing quote
                parts = [value]
                while not (parts[-1].endswith("'")
                           and (len(parts) > 1 or len(parts[0]) > 1)):
                    nxt = self.peek()
                    if nxt is None:
                        raise QueryParseError("unclosed ' phrase")
                    parts.append(self.next())
                return self._phrase(field, '"' + " ".join(parts)[1:-1] + '"')
            unescaped = value.replace("\\*", "\x00").replace("\\?", "\x01")
            if "*" in unescaped or "?" in unescaped:
                # escaped wildcards match literally (fnmatch classes)
                return Wildcard(field, unescaped.replace("\x00", "[*]")
                                .replace("\x01", "[?]"))
            return Term(field, unescaped.replace("\x00", "*")
                        .replace("\x01", "?"))
        # bare term → full-text over default fields
        return self._default_field_query(tok)

    def _default_field_query(self, text: str) -> QueryAst:
        if not self.default_fields:
            raise QueryParseError(
                f"bare term {text!r} requires default_search_fields")
        # bare comparison shorthand applies as a range on the default
        # field(s): `default_field: x, query: ">=10"` (ES query_string)
        for op, incl in ((">=", True), ("<=", True), (">", False),
                         ("<", False)):
            if text.startswith(op):
                bound = RangeBound(text[len(op):], incl)
                ranges = [Range(f, lower=bound) if op.startswith(">")
                          else Range(f, upper=bound)
                          for f in self.default_fields]
                return ranges[0] if len(ranges) == 1 else \
                    Bool(should=tuple(ranges))
        unescaped = text.replace("\\*", "\x00").replace("\\?", "\x01")
        if ("*" in unescaped or "?" in unescaped) and text != "*":
            # bare wildcard over the default fields (ES query_string);
            # ESCAPED wildcards become fnmatch character classes so they
            # match literally
            pattern = (unescaped.replace("\x00", "[*]")
                       .replace("\x01", "[?]"))
            wilds = [Wildcard(f, pattern) for f in self.default_fields]
            return wilds[0] if len(wilds) == 1 else Bool(should=tuple(wilds))
        # escaped wildcards are literal characters, not operators
        text = unescaped.replace("\x00", "*").replace("\x01", "?")
        clauses = [FullText(f, text, "or") for f in self.default_fields]
        if len(clauses) == 1:
            return clauses[0]
        return Bool(should=tuple(clauses))

    @staticmethod
    def _split_field(tok: str) -> tuple[Optional[str], str]:
        # field names may contain dots; split at the first colon not in the value
        if ":" in tok:
            field, value = tok.split(":", 1)
            if field:
                return field, value
        return None, tok

    def _phrase(self, field: Optional[str], tok: str) -> QueryAst:
        text = re.sub(r"\\(.)", r"\1", tok[1:-1])
        prefix = False
        if self.peek() == "*":
            self.next()
            prefix = True
        if field is None:
            if not self.default_fields:
                raise QueryParseError("phrase requires a field or default_search_fields")
            fields = self.default_fields
        else:
            fields = [field]
        if prefix:
            clauses: list[QueryAst] = [PhrasePrefix(f, text) for f in fields]
        else:
            clauses = [FullText(f, text, "phrase") for f in fields]
        return clauses[0] if len(clauses) == 1 else Bool(should=tuple(clauses))

    def _range_value(self) -> str:
        # numbers may tokenize as a sign token followed by digits
        tok = self.next()
        if tok in ("+", "-"):
            tok = tok + self.next()
        if tok.startswith('"'):
            # reference parity: the query language has no quoted (or
            # whitespace-escaped) range bounds — use the ES API instead
            raise QueryParseError("range bounds do not support quoted values")
        return tok

    def _range(self, field: str) -> QueryAst:
        open_tok = self.next()
        lower_incl = open_tok == "["
        lo = self._range_value()
        if self.next() != "TO":
            raise QueryParseError("expected TO in range")
        hi = self._range_value()
        close_tok = self.next()
        if close_tok not in ("]", "}"):
            raise QueryParseError("expected ] or } closing range")
        upper_incl = close_tok == "]"
        lower = None if lo == "*" else RangeBound(lo, lower_incl)
        upper = None if hi == "*" else RangeBound(hi, upper_incl)
        return Range(field, lower=lower, upper=upper)

    def _term_set(self, field: str) -> QueryAst:
        if self.next() != "[":
            raise QueryParseError("expected [ after IN")
        terms: list[str] = []
        while True:
            tok = self.next()
            if tok == "]":
                break
            terms.append(tok)
        return TermSet({field: tuple(terms)})


def parse_query_string(query: str, default_search_fields: Sequence[str] = ()) -> QueryAst:
    query = query.strip()
    if not query or query == "*":
        return MatchAll()
    return _Parser(_tokenize(query), default_search_fields).parse()
