"""Elasticsearch query DSL → QueryAst.

Role of the reference's `quickwit-query/src/elastic_query_dsl/`
(`mod.rs:169` et al.): translate the ES `query` body subset into the
engine's QueryAst. Supported: term, terms, match, match_phrase,
match_phrase_prefix, multi_match, match_all/match_none, bool, range,
exists, wildcard, regexp, prefix, query_string, simple_query_string.
"""

from __future__ import annotations

from typing import Any, Sequence

from .ast import (
    Bool, Boost, FieldPresence, FullText, MatchAll, MatchNone, PhrasePrefix,
    QueryAst, Range, RangeBound, Regex, Term, TermSet, Wildcard,
)
from .parser import parse_query_string


class EsDslParseError(ValueError):
    pass


def _single_kv(body: dict[str, Any], kind: str) -> tuple[str, Any]:
    if len(body) != 1:
        raise EsDslParseError(f"{kind} expects exactly one field, got {list(body)}")
    return next(iter(body.items()))


def _as_clause_list(value) -> list:
    if value is None:
        return []
    return value if isinstance(value, list) else [value]


def es_query_to_ast(query: dict[str, Any],
                    default_search_fields: Sequence[str] = ()) -> QueryAst:
    if not isinstance(query, dict) or len(query) != 1:
        raise EsDslParseError(f"query must have exactly one root clause, got {query!r}")
    kind, body = next(iter(query.items()))

    if kind == "match_all":
        return MatchAll()
    if kind == "match_none":
        return MatchNone()
    if kind == "term":
        field, spec = _single_kv(body, "term")
        if isinstance(spec, dict):
            ast: QueryAst = Term(field, str(spec["value"]))
            if "boost" in spec:
                ast = Boost(ast, float(spec["boost"]))
            return ast
        return Term(field, _scalar_str(spec))
    if kind == "terms":
        entries = {f: v for f, v in body.items() if f != "boost"}
        field, values = _single_kv(entries, "terms")
        return TermSet({field: tuple(_scalar_str(v) for v in values)})
    if kind == "match":
        field, spec = _single_kv(body, "match")
        if isinstance(spec, dict):
            text = str(spec["query"])
            operator = spec.get("operator", "or").lower()
            ast = FullText(field, text, operator)
            if "boost" in spec:
                ast = Boost(ast, float(spec["boost"]))
            return ast
        return FullText(field, _scalar_str(spec), "or")
    if kind == "match_phrase":
        field, spec = _single_kv(body, "match_phrase")
        if isinstance(spec, dict):
            return FullText(field, str(spec["query"]), "phrase",
                            slop=spec.get("slop", 0))
        return FullText(field, _scalar_str(spec), "phrase")
    if kind == "match_phrase_prefix":
        field, spec = _single_kv(body, "match_phrase_prefix")
        if isinstance(spec, dict):
            return PhrasePrefix(field, str(spec["query"]),
                                max_expansions=spec.get("max_expansions", 50))
        return PhrasePrefix(field, _scalar_str(spec))
    if kind == "multi_match":
        fields = body.get("fields") or list(default_search_fields)
        if not fields:
            raise EsDslParseError("multi_match requires fields")
        text = str(body["query"])
        mode = "phrase" if body.get("type") == "phrase" else \
            body.get("operator", "or").lower()
        clauses = tuple(FullText(f, text, mode) for f in fields)
        return clauses[0] if len(clauses) == 1 else Bool(should=clauses)
    if kind == "bool":
        msm = body.get("minimum_should_match")
        return Bool(
            must=tuple(es_query_to_ast(c, default_search_fields)
                       for c in _as_clause_list(body.get("must"))),
            must_not=tuple(es_query_to_ast(c, default_search_fields)
                           for c in _as_clause_list(body.get("must_not"))),
            should=tuple(es_query_to_ast(c, default_search_fields)
                         for c in _as_clause_list(body.get("should"))),
            filter=tuple(es_query_to_ast(c, default_search_fields)
                         for c in _as_clause_list(body.get("filter"))),
            minimum_should_match=int(msm) if msm is not None else None,
        )
    if kind == "range":
        field, spec = _single_kv(body, "range")
        lower = upper = None
        if "gte" in spec:
            lower = RangeBound(spec["gte"], True)
        elif "gt" in spec:
            lower = RangeBound(spec["gt"], False)
        if "lte" in spec:
            upper = RangeBound(spec["lte"], True)
        elif "lt" in spec:
            upper = RangeBound(spec["lt"], False)
        return Range(field, lower=lower, upper=upper)
    if kind == "exists":
        return FieldPresence(body["field"])
    if kind == "wildcard":
        field, spec = _single_kv(body, "wildcard")
        pattern = spec["value"] if isinstance(spec, dict) else spec
        return Wildcard(field, str(pattern))
    if kind == "regexp":
        field, spec = _single_kv(body, "regexp")
        pattern = spec["value"] if isinstance(spec, dict) else spec
        return Regex(field, str(pattern))
    if kind == "prefix":
        field, spec = _single_kv(body, "prefix")
        value = spec["value"] if isinstance(spec, dict) else spec
        return Wildcard(field, f"{value}*")
    if kind in ("query_string", "simple_query_string"):
        fields = body.get("fields") or body.get("default_field") or \
            list(default_search_fields)
        if isinstance(fields, str):
            fields = [fields]
        return parse_query_string(body["query"], fields)
    raise EsDslParseError(f"unsupported query kind {kind!r}")


def _scalar_str(value: Any) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)
