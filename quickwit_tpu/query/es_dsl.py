"""Elasticsearch query DSL → QueryAst.

Role of the reference's `quickwit-query/src/elastic_query_dsl/`
(`mod.rs:169` et al.): translate the ES `query` body subset into the
engine's QueryAst. Supported: term, terms, match, match_phrase,
match_phrase_prefix, multi_match, match_all/match_none, bool, range,
exists, wildcard, regexp, prefix, query_string, simple_query_string.
"""

from __future__ import annotations

from typing import Any, Sequence

from .ast import (
    Bool, Boost, FieldPresence, FullText, MatchAll, MatchNone, PhrasePrefix,
    QueryAst, Range, RangeBound, Regex, Term, TermSet, Wildcard,
)
from .parser import parse_query_string


class EsDslParseError(ValueError):
    pass


def _single_kv(body: dict[str, Any], kind: str) -> tuple[str, Any]:
    if len(body) != 1:
        raise EsDslParseError(f"{kind} expects exactly one field, got {list(body)}")
    return next(iter(body.items()))


def _as_clause_list(value) -> list:
    if value is None:
        return []
    return value if isinstance(value, list) else [value]


def es_query_to_ast(query: dict[str, Any],
                    default_search_fields: Sequence[str] = (),
                    lenient_validator=None) -> QueryAst:
    if not isinstance(query, dict) or len(query) != 1:
        raise EsDslParseError(f"query must have exactly one root clause, got {query!r}")
    kind, body = next(iter(query.items()))

    if kind == "match_all":
        return MatchAll()
    if kind == "match_none":
        return MatchNone()
    if kind == "term":
        # ES term queries are NOT analyzed: the value must equal the
        # post-tokenization indexed form (verbatim=True)
        field, spec = _single_kv(body, "term")
        if isinstance(spec, dict):
            # _scalar_str, not str(): JSON true must canonicalize to
            # "true" exactly like the scalar shorthand form
            value = _scalar_str(spec["value"])
            if spec.get("case_insensitive"):
                value = value.lower()
            ast: QueryAst = Term(field, value, verbatim=True)
            if "boost" in spec:
                ast = Boost(ast, float(spec["boost"]))
            return ast
        return Term(field, _scalar_str(spec), verbatim=True)
    if kind == "terms":
        entries = {f: v for f, v in body.items() if f != "boost"}
        field, values = _single_kv(entries, "terms")
        return TermSet({field: tuple(_scalar_str(v) for v in values)})
    if kind == "match":
        field, spec = _single_kv(body, "match")
        if isinstance(spec, dict):
            text = str(spec["query"])
            operator = spec.get("operator", "or").lower()
            zero_terms = str(spec.get("zero_terms_query", "none")).lower()
            ast = FullText(field, text, operator, zero_terms=zero_terms)
            if "boost" in spec:
                ast = Boost(ast, float(spec["boost"]))
            return ast
        return FullText(field, _scalar_str(spec), "or")
    if kind == "match_phrase":
        field, spec = _single_kv(body, "match_phrase")
        if isinstance(spec, dict):
            return FullText(field, str(spec["query"]), "phrase",
                            slop=spec.get("slop", 0))
        return FullText(field, _scalar_str(spec), "phrase")
    if kind == "match_phrase_prefix":
        field, spec = _single_kv(body, "match_phrase_prefix")
        if isinstance(spec, dict):
            analyzer = spec.get("analyzer")
            if analyzer is not None:
                from .tokenizers import known_tokenizer
                if not known_tokenizer(analyzer):
                    raise EsDslParseError(
                        f"unknown analyzer {analyzer!r}")
            return PhrasePrefix(field, str(spec["query"]),
                                max_expansions=spec.get("max_expansions", 50),
                                analyzer=analyzer)
        return PhrasePrefix(field, _scalar_str(spec))
    if kind == "multi_match":
        if body.get("fields") == []:
            raise EsDslParseError("multi_match `fields` must not be empty")
        fields = body.get("fields") or list(default_search_fields)
        if isinstance(fields, str):
            fields = [fields]  # multi_match accepts a single string
        if not fields:
            raise EsDslParseError("multi_match requires fields")
        # ES `field^boost` syntax
        boosts = {}
        parsed_fields = []
        for f in fields:
            name, _, boost = str(f).partition("^")
            parsed_fields.append(name)
            if boost:
                boosts[name] = float(boost)
        fields = parsed_fields
        if lenient_validator is not None:
            # ES drops unknown fields from multi_match regardless of the
            # `lenient` flag (field leniency vs value leniency)
            known = [f for f in fields if lenient_validator(f, None)]
            if not known:
                return MatchNone()
            fields = known
        text = str(body["query"])
        mm_type = body.get("type")
        def boosted(node, f):
            return Boost(node, boosts[f]) if f in boosts else node

        if mm_type == "phrase_prefix":
            max_exp = int(body.get("max_expansions", 50))
            clauses: tuple = tuple(
                boosted(PhrasePrefix(f, text, max_expansions=max_exp), f)
                for f in fields)
        else:
            mode = "phrase" if mm_type == "phrase" else \
                body.get("operator", "or").lower()
            clauses = tuple(
                boosted(FullText(f, text, mode,
                                 slop=int(body.get("slop", 0))), f)
                for f in fields)
        ast = clauses[0] if len(clauses) == 1 else Bool(should=clauses)
        if body.get("lenient") and lenient_validator is not None:
            ast = rewrite_lenient(ast, lenient_validator)
        return ast
    if kind == "match_bool_prefix":
        # every token matches as a term except the last, which matches as
        # a prefix (ES match_bool_prefix)
        field, spec = _single_kv(body, "match_bool_prefix")
        text = str(spec["query"]) if isinstance(spec, dict) else \
            _scalar_str(spec)
        operator = (str(spec.get("operator", "or")).lower()
                    if isinstance(spec, dict) else "or")
        # analysis happens at lowering with the FIELD's tokenizer (the
        # last TOKEN becomes a prefix, not the last space-separated word)
        mode = "bool_prefix_and" if operator == "and" else "bool_prefix_or"
        return FullText(field, text, mode)
    if kind == "bool":
        msm = body.get("minimum_should_match")
        num_should = len(_as_clause_list(body.get("should")))
        return Bool(
            must=tuple(es_query_to_ast(c, default_search_fields, lenient_validator)
                       for c in _as_clause_list(body.get("must"))),
            must_not=tuple(es_query_to_ast(c, default_search_fields, lenient_validator)
                           for c in _as_clause_list(body.get("must_not"))),
            should=tuple(es_query_to_ast(c, default_search_fields, lenient_validator)
                         for c in _as_clause_list(body.get("should"))),
            filter=tuple(es_query_to_ast(c, default_search_fields, lenient_validator)
                         for c in _as_clause_list(body.get("filter"))),
            minimum_should_match=(None if msm is None
                                  else _parse_msm(msm, num_should)),
        )
    if kind == "range":
        field, spec = _single_kv(body, "range")
        lower = upper = None
        if "gte" in spec:
            lower = RangeBound(spec["gte"], True)
        elif "gt" in spec:
            lower = RangeBound(spec["gt"], False)
        if "lte" in spec:
            upper = RangeBound(spec["lte"], True)
        elif "lt" in spec:
            upper = RangeBound(spec["lt"], False)
        return Range(field, lower=lower, upper=upper,
                     format=spec.get("format"))
    if kind == "exists":
        if not isinstance(body, dict) or not isinstance(body.get("field"),
                                                        str):
            raise EsDslParseError("exists expects {\"field\": \"<name>\"}")
        return FieldPresence(body["field"])
    if kind == "wildcard":
        field, spec = _single_kv(body, "wildcard")
        pattern = spec["value"] if isinstance(spec, dict) else spec
        ci = isinstance(spec, dict) and bool(spec.get("case_insensitive"))
        return Wildcard(field, str(pattern), case_insensitive=ci)
    if kind == "regexp":
        field, spec = _single_kv(body, "regexp")
        pattern = spec["value"] if isinstance(spec, dict) else spec
        ci = isinstance(spec, dict) and bool(spec.get("case_insensitive"))
        return Regex(field, str(pattern), case_insensitive=ci)
    if kind == "prefix":
        field, spec = _single_kv(body, "prefix")
        value = spec["value"] if isinstance(spec, dict) else spec
        ci = isinstance(spec, dict) and bool(spec.get("case_insensitive"))
        return Wildcard(field, f"{value}*", case_insensitive=ci)
    if kind in ("query_string", "simple_query_string"):
        if "fields" in body and not isinstance(body["fields"], list):
            # ES rejects a bare-string `fields` (400); only `default_field`
            # takes a single string
            raise EsDslParseError("query_string `fields` must be an array")
        if body.get("fields") and body.get("default_field"):
            raise EsDslParseError(
                "query_string cannot set both `fields` and `default_field`")
        fields = body.get("fields") or body.get("default_field") or \
            list(default_search_fields)
        if isinstance(fields, str):
            fields = [fields]
        ast = parse_query_string(body["query"], fields)
        if body.get("lenient") and lenient_validator is not None:
            ast = rewrite_lenient(ast, lenient_validator)
        return ast
    raise EsDslParseError(f"unsupported query kind {kind!r}")


def rewrite_lenient(ast: QueryAst, valid) -> QueryAst:
    """ES `lenient: true`: clauses referencing unknown fields or carrying
    values the field type cannot parse become match-none instead of
    erroring. `valid(field, value_or_None) -> bool` is supplied by the
    serve layer, which owns the doc mapper."""
    if isinstance(ast, Bool):
        return Bool(
            must=tuple(rewrite_lenient(c, valid) for c in ast.must),
            must_not=tuple(rewrite_lenient(c, valid) for c in ast.must_not),
            should=tuple(rewrite_lenient(c, valid) for c in ast.should),
            filter=tuple(rewrite_lenient(c, valid) for c in ast.filter),
            minimum_should_match=ast.minimum_should_match)
    if isinstance(ast, Boost):
        return Boost(rewrite_lenient(ast.underlying, valid), ast.boost)
    if isinstance(ast, Term):
        return ast if valid(ast.field, ast.value) else MatchNone()
    if isinstance(ast, FullText):
        return ast if valid(ast.field, ast.text) else MatchNone()
    if isinstance(ast, Range):
        ok = all(valid(ast.field, b.value)
                 for b in (ast.lower, ast.upper) if b is not None)
        return ast if ok and valid(ast.field, None) else MatchNone()
    if isinstance(ast, (Wildcard, Regex, PhrasePrefix, FieldPresence)):
        field = ast.field
        return ast if valid(field, None) else MatchNone()
    if isinstance(ast, TermSet):
        ok = all(valid(f, t) for f, ts in ast.terms_per_field.items()
                 for t in ts)
        return ast if ok else MatchNone()
    return ast


def _parse_msm(msm: Any, num_should: int) -> int:
    """ES minimum_should_match: integer, negative integer (n - |value|),
    or percentage ("50%" / "-25%") of the number of should clauses."""
    if isinstance(msm, str) and msm.strip().endswith("%"):
        pct = float(msm.strip()[:-1])
        if pct < 0:
            return num_should - int(num_should * (-pct) / 100.0)
        return int(num_should * pct / 100.0)
    value = int(msm)
    if value < 0:
        return max(num_should + value, 0)
    return value


def _scalar_str(value: Any) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)
