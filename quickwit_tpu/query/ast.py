"""Serializable query AST.

Role of the reference's `quickwit-query/src/query_ast/mod.rs`: a typed,
JSON-serializable query tree that travels between root and leaf searchers and
is lowered — against a concrete doc mapping — into an executable plan.  In the
TPU build the lowering target is a tensor plan (`search/plan.py`) instead of a
tantivy `Query`.

Every node serializes as ``{"type": "<tag>", ...fields}`` so leaf requests are
wire-stable, mirroring the reference's internally-tagged serde representation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Union

JsonLiteral = Union[str, int, float, bool, None]


@dataclass(frozen=True)
class QueryAst:
    """Base class; use the concrete subclasses below."""

    def to_dict(self) -> dict[str, Any]:
        raise NotImplementedError

    # --- combinators -------------------------------------------------------
    def boost(self, factor: float) -> "QueryAst":
        return Boost(underlying=self, boost=factor)


@dataclass(frozen=True)
class MatchAll(QueryAst):
    def to_dict(self) -> dict[str, Any]:
        return {"type": "match_all"}


@dataclass(frozen=True)
class MatchNone(QueryAst):
    def to_dict(self) -> dict[str, Any]:
        return {"type": "match_none"}


@dataclass(frozen=True)
class Term(QueryAst):
    """Exact term on a field; `value` is the raw (pre-normalization) token.

    `verbatim` distinguishes ES `term` queries (no analysis: the value
    must equal the post-tokenization indexed form — reference:
    `elastic_query_dsl/term_query.rs`) from query-string terms, which
    tokenize on text fields like a conjunctive full-text match."""
    field: str
    value: str
    verbatim: bool = False

    def to_dict(self) -> dict[str, Any]:
        return {"type": "term", "field": self.field, "value": self.value,
                "verbatim": self.verbatim}


@dataclass(frozen=True)
class TermSet(QueryAst):
    """Matches docs containing any of the terms (per field)."""
    terms_per_field: dict[str, tuple[str, ...]] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "type": "term_set",
            "terms_per_field": {f: list(ts) for f, ts in self.terms_per_field.items()},
        }


@dataclass(frozen=True)
class FullText(QueryAst):
    """Tokenized match query. `mode` is 'or' | 'and' | 'phrase'.

    The reference's FullTextQuery (`full_text_query.rs`) with its
    operator/phrase modes; slop supported for phrase.
    """
    field: str
    text: str
    mode: str = "or"
    slop: int = 0
    # ES `zero_terms_query`: what a match whose text tokenizes to nothing
    # matches — "none" (default) or "all"
    zero_terms: str = "none"

    def to_dict(self) -> dict[str, Any]:
        return {"type": "full_text", "field": self.field, "text": self.text,
                "mode": self.mode, "slop": self.slop,
                "zero_terms": self.zero_terms}


@dataclass(frozen=True)
class PhrasePrefix(QueryAst):
    field: str
    phrase: str
    max_expansions: int = 50
    analyzer: Optional[str] = None  # ES per-query analyzer override

    def to_dict(self) -> dict[str, Any]:
        return {"type": "phrase_prefix", "field": self.field, "phrase": self.phrase,
                "max_expansions": self.max_expansions,
                "analyzer": self.analyzer}


@dataclass(frozen=True)
class Wildcard(QueryAst):
    field: str
    pattern: str  # `*` and `?` wildcards
    case_insensitive: bool = False

    def to_dict(self) -> dict[str, Any]:
        return {"type": "wildcard", "field": self.field,
                "pattern": self.pattern,
                "case_insensitive": self.case_insensitive}


@dataclass(frozen=True)
class Regex(QueryAst):
    field: str
    pattern: str
    case_insensitive: bool = False

    def to_dict(self) -> dict[str, Any]:
        return {"type": "regex", "field": self.field,
                "pattern": self.pattern,
                "case_insensitive": self.case_insensitive}


@dataclass(frozen=True)
class FieldPresence(QueryAst):
    field: str

    def to_dict(self) -> dict[str, Any]:
        return {"type": "field_presence", "field": self.field}


@dataclass(frozen=True)
class RangeBound:
    value: JsonLiteral
    inclusive: bool = True

    def to_dict(self) -> dict[str, Any]:
        return {"value": self.value, "inclusive": self.inclusive}

    @staticmethod
    def from_dict(d: Optional[dict[str, Any]]) -> "Optional[RangeBound]":
        if d is None:
            return None
        return RangeBound(d["value"], d.get("inclusive", True))


@dataclass(frozen=True)
class Range(QueryAst):
    field: str
    lower: Optional[RangeBound] = None
    upper: Optional[RangeBound] = None
    # ES range `format` param: a java-time pattern the bounds are parsed
    # with instead of the field's input_formats
    format: Optional[str] = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "type": "range",
            "field": self.field,
            "lower": self.lower.to_dict() if self.lower else None,
            "upper": self.upper.to_dict() if self.upper else None,
            "format": self.format,
        }


@dataclass(frozen=True)
class Bool(QueryAst):
    """Boolean combination (reference: `bool_query.rs`).

    Semantics match ES/tantivy: `must`/`filter` are conjunctive, `should`
    disjunctive (scoring only if there are no `must` clauses, unless
    minimum_should_match forces it), `must_not` is an exclusion filter and
    never scores.
    """
    must: tuple[QueryAst, ...] = ()
    must_not: tuple[QueryAst, ...] = ()
    should: tuple[QueryAst, ...] = ()
    filter: tuple[QueryAst, ...] = ()
    minimum_should_match: Optional[int] = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "type": "bool",
            "must": [q.to_dict() for q in self.must],
            "must_not": [q.to_dict() for q in self.must_not],
            "should": [q.to_dict() for q in self.should],
            "filter": [q.to_dict() for q in self.filter],
            "minimum_should_match": self.minimum_should_match,
        }


@dataclass(frozen=True)
class Boost(QueryAst):
    underlying: QueryAst
    boost: float

    def to_dict(self) -> dict[str, Any]:
        return {"type": "boost", "underlying": self.underlying.to_dict(), "boost": self.boost}


def _seq(dicts: Sequence[dict[str, Any]]) -> tuple[QueryAst, ...]:
    return tuple(ast_from_dict(d) for d in dicts)


def ast_from_dict(d: dict[str, Any]) -> QueryAst:
    tag = d["type"]
    if tag == "match_all":
        return MatchAll()
    if tag == "match_none":
        return MatchNone()
    if tag == "term":
        return Term(d["field"], d["value"], d.get("verbatim", False))
    if tag == "term_set":
        return TermSet({f: tuple(ts) for f, ts in d["terms_per_field"].items()})
    if tag == "full_text":
        return FullText(d["field"], d["text"], d.get("mode", "or"),
                        d.get("slop", 0), d.get("zero_terms", "none"))
    if tag == "phrase_prefix":
        return PhrasePrefix(d["field"], d["phrase"], d.get("max_expansions", 50),
                            d.get("analyzer"))
    if tag == "wildcard":
        return Wildcard(d["field"], d["pattern"], d.get("case_insensitive", False))
    if tag == "regex":
        return Regex(d["field"], d["pattern"], d.get("case_insensitive", False))
    if tag == "field_presence":
        return FieldPresence(d["field"])
    if tag == "range":
        return Range(d["field"], RangeBound.from_dict(d.get("lower")),
                     RangeBound.from_dict(d.get("upper")), d.get("format"))
    if tag == "bool":
        return Bool(
            must=_seq(d.get("must", [])),
            must_not=_seq(d.get("must_not", [])),
            should=_seq(d.get("should", [])),
            filter=_seq(d.get("filter", [])),
            minimum_should_match=d.get("minimum_should_match"),
        )
    if tag == "boost":
        return Boost(ast_from_dict(d["underlying"]), d["boost"])
    raise ValueError(f"unknown query ast node type: {tag!r}")
