"""Text tokenizers.

Role of the reference's `quickwit-query/src/tokenizers/` (and tantivy's
tokenizer API): turn field text into index tokens. Parity-critical because the
same tokenizer must run at indexing and query time.

Registry mirrors the reference's named tokenizers:
- ``raw``: whole value as a single token (no lowercasing), capped length
- ``default``: split on non-alphanumeric, lowercase, drop tokens > 255 chars
- ``en_stem``: default + Porter-lite stemming
- ``whitespace``: split on whitespace, no lowercasing
- ``lowercase``: single token, lowercased (reference's raw+lowercase)
- ``chinese_compatible``: CJK codepoints as single tokens, latin runs as words
- ``source_code_default``: splits identifiers on case/underscore boundaries
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Iterator

MAX_TOKEN_LEN = 255


@dataclass(frozen=True)
class Token:
    text: str
    position: int  # token position (for phrase queries)


Tokenizer = Callable[[str], list[Token]]

_WORD_RE = re.compile(r"[0-9A-Za-zÀ-ɏЀ-ӿ]+")
_CJK_RE = re.compile(
    r"([一-鿿㐀-䶿぀-ヿ가-힯])|([0-9A-Za-z]+)"
)
_CODE_RE = re.compile(
    r"(?:[A-Z]+(?![a-z]))|(?:[A-Z][a-z]+)|(?:[a-z]+)|(?:[0-9]+)"
)


def _raw(text: str) -> list[Token]:
    text = text[:MAX_TOKEN_LEN]
    return [Token(text, 0)] if text else []


def _lowercase(text: str) -> list[Token]:
    text = text[:MAX_TOKEN_LEN].lower()
    return [Token(text, 0)] if text else []


def _default(text: str) -> list[Token]:
    return [
        Token(m.group(0).lower(), pos)
        for pos, m in enumerate(_WORD_RE.finditer(text))
        if len(m.group(0)) <= MAX_TOKEN_LEN
    ]


def _whitespace(text: str) -> list[Token]:
    return [Token(tok, pos) for pos, tok in enumerate(text.split()) if len(tok) <= MAX_TOKEN_LEN]


def _en_stem(text: str) -> list[Token]:
    """Default tokenization + Porter2 (English Snowball) stemming —
    byte-compatible with tantivy's rust-stemmers "english" output
    (`porter2.py`), so `en_stem` index terms match the reference's."""
    from .porter2 import stem
    return [Token(stem(t.text), t.position) for t in _default(text)]


def _chinese_compatible(text: str) -> list[Token]:
    out: list[Token] = []
    pos = 0
    for m in _CJK_RE.finditer(text):
        tok = m.group(0)
        out.append(Token(tok.lower(), pos))
        pos += 1
    return out


def _source_code(text: str) -> list[Token]:
    out: list[Token] = []
    pos = 0
    for m in _CODE_RE.finditer(text):
        out.append(Token(m.group(0).lower(), pos))
        pos += 1
    return out


_REGISTRY: dict[str, Tokenizer] = {
    "raw": _raw,
    "lowercase": _lowercase,
    "default": _default,
    "en_stem": _en_stem,
    "whitespace": _whitespace,
    "chinese_compatible": _chinese_compatible,
    "source_code_default": _source_code,
}


def get_tokenizer(name: str) -> Tokenizer:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown tokenizer {name!r}; known: {sorted(_REGISTRY)}")


def tokenizer_names() -> list[str]:
    return sorted(_REGISTRY)


def known_tokenizer(name: str) -> bool:
    return name in _REGISTRY
