"""Multi-split, multi-chip query execution over a device mesh.

Role of the reference's query fan-out + scatter-gather merge tree
(SURVEY.md §2.3: rendezvous job placement → per-node leaf batches → per-split
tasks → `IncrementalCollector` merges → root `merge_fruits`), re-designed for
TPU: the split dimension becomes a **mesh axis**, the merge tree becomes XLA
collectives over ICI (the pmap'd merge of BASELINE.json):

    mesh = Mesh(devices, ("splits", "docs"))
    arrays: postings stacked [n_splits, plen]       → P("splits")
            columns stacked  [n_splits, padded]     → P("splits", "docs")
    shard_map: each device searches its split shard over its doc shard
      - per-split kernel vmapped over the local split batch
      - doc-axis partials merged by psum (counts/aggs) and
        all_gather + re-top-k (hits) over ICI
      - split-axis partials likewise

The doc axis is the long-dimension ("sequence parallel") analogue: one huge
split's dense doc arrays are sharded across chips, with the same
collective-merge pattern (SURVEY.md §5.7).

Batch restrictions (checked at build): all splits share one doc-mapping and
the query must lower to a split-independent structure — wildcard/regex/
phrase-prefix expand differently per split and fall back to per-split
sequential leaf search in the search service.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..common.clock import monotonic as _clock_monotonic
from ..index.format import ZONEMAP_BLOCK
from ..index.reader import SplitReader
from ..models.doc_mapper import DocMapper
from ..observability import flight
from ..observability.profile import (
    PHASE_COMPILE, PHASE_EXECUTE, PHASE_PLAN_BUILD, PHASE_STAGING_CACHE_HIT,
    PHASE_STAGING_UPLOAD, PHASE_TOPK_MERGE, current_profile, profile_add,
    profiled_phase,
)
from ..query.aggregations import DateHistogramAgg, HistogramAgg, TermsAgg, parse_aggs
from ..search.models import LeafSearchResponse, PartialHit, SearchRequest
from ..search.plan import BucketAggExec, LoweredPlan, MetricAggExec, lower_request
from ..search import executor as executor_mod
from ..search.leaf import (
    _intermediate_aggs, _sort_values_are_int, decode_sort_value_exact,
)


def make_mesh(axis_splits: int, axis_docs: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    devs = np.asarray(devices if devices is not None else jax.devices())
    need = axis_splits * axis_docs
    if devs.size < need:
        raise ValueError(f"need {need} devices, have {devs.size}")
    return Mesh(devs[:need].reshape(axis_splits, axis_docs), ("splits", "docs"))


# --------------------------------------------------------------------------

@dataclass
class SplitBatch:
    """Same-structure plans for one query over many splits, stacked."""
    template: LoweredPlan                 # structure donor (slots/signature)
    arrays: list[np.ndarray]              # slot-indexed, stacked [n, ...]
    scalars: list[np.ndarray]             # slot-indexed, stacked [n]
    num_docs: np.ndarray                  # [n] int32
    split_ids: list[str]                  # n entries ("" = padding split)
    num_docs_padded: int                  # uniform padded doc count
    doc_mapper: DocMapper
    sort_field: str
    sort_order: str
    sort2_field: Optional[str] = None     # secondary sort key (2-key sorts)
    sort2_order: str = "desc"
    readers: list[SplitReader] = None  # for exact int sort-value re-reads

    @property
    def n_splits(self) -> int:
        return len(self.split_ids)


def _global_agg_overrides(agg_specs, readers: list[SplitReader],
                          doc_mapper: DocMapper) -> dict:
    """Compute batch-global bucket spaces so per-split states merge on device."""
    histograms: dict[str, tuple[int, int]] = {}
    terms_dicts: dict[str, dict] = {}
    terms_cards: dict[str, int] = {}
    terms_keys: dict[str, list] = {}
    from ..search.plan import MAX_BUCKETS, PlanError
    # nested child buckets need batch-global spaces too (their per-split
    # ordinal/origin spaces would otherwise be summed incoherently on
    # device); children key under "parent>child" since ES names are only
    # unique per level
    expanded: list = []

    from ..query.aggregations import CompositeAgg

    def _expand(spec, path):
        if isinstance(spec, CompositeAgg):
            # composite is per-split by design (split-local key
            # encodings) — lowering raises before any override is read,
            # so computing cross-reader dictionaries here is pure waste
            return
        expanded.append((spec, path))
        for sub in getattr(spec, "sub_buckets", ()):
            _expand(sub, f"{path}>{sub.name}")

    for spec in agg_specs:
        _expand(spec, spec.name)
    for spec, override_key in expanded:
        if isinstance(spec, (DateHistogramAgg, HistogramAgg)):
            vmins, vmaxs = [], []
            for r in readers:
                meta = r.field_meta(spec.field)
                if meta.get("min_value") is not None:
                    vmins.append(meta["min_value"])
                    vmaxs.append(meta["max_value"])
            if isinstance(spec, DateHistogramAgg) and spec.extended_bounds:
                vmins.append(spec.extended_bounds[0])
                vmaxs.append(spec.extended_bounds[1])
            if not vmins:
                histograms[override_key] = (0, 1)
                continue
            interval = spec.interval_micros if isinstance(spec, DateHistogramAgg) \
                else spec.interval
            if isinstance(spec, DateHistogramAgg):
                offset = getattr(spec, "offset_micros", 0)
                origin = ((min(vmins) - offset) // interval) * interval \
                    + offset
            else:
                origin = float(np.floor(min(vmins) / interval) * interval)
            num_buckets = int((max(vmaxs) - origin) // interval) + 1
            if num_buckets > MAX_BUCKETS:
                raise PlanError(
                    f"aggregation {spec.name!r} would create {num_buckets} "
                    f"buckets over the batch (max {MAX_BUCKETS})")
            histograms[override_key] = (origin if isinstance(spec, HistogramAgg)
                                        else int(origin), num_buckets)
        elif isinstance(spec, TermsAgg):
            union: set = set()
            for r in readers:
                meta = r.field_meta(spec.field)
                if meta.get("column_kind") == "ordinal":
                    union.update(r.column_dict(spec.field))
                else:
                    from ..search.plan import ordinalize_numeric_column
                    _, keys = ordinalize_numeric_column(r, spec.field)
                    union.update(keys)
            keys_sorted = sorted(union, key=lambda v: (str(type(v)), v))
            terms_dicts[spec.field] = {k: i for i, k in enumerate(keys_sorted)}
            terms_cards[spec.field] = len(keys_sorted)
            terms_keys[spec.field] = keys_sorted
    return {"histograms": histograms, "terms_dicts": terms_dicts,
            "terms_cards": terms_cards, "terms_keys": terms_keys}


def _pad_fill(key: str, num_docs_padded: int, dtype=None):
    if key.startswith("post.") and key.endswith(".ids"):
        return num_docs_padded        # OOB scatter sentinel
    if key.startswith("pre.") and key.endswith(".ids"):
        return num_docs_padded
    if "ordinals" in key:
        return -1
    if key.endswith(".zmin"):
        # inverted envelope: pad blocks never qualify (harmless either way —
        # their doc lanes carry present=0 — but keep the zonemaps honest)
        return np.inf if dtype.kind == "f" else np.iinfo(dtype).max
    if key.endswith(".zmax"):
        return -np.inf if dtype.kind == "f" else np.iinfo(dtype).min
    return 0


def build_batch(request: SearchRequest, doc_mapper: DocMapper,
                readers: list[SplitReader], split_ids: list[str],
                pad_to_splits: Optional[int] = None,
                absence_sink=None,
                sort_value_threshold: Optional[float] = None) -> SplitBatch:
    """`absence_sink(split_id, field, term)`: term-dictionary misses found
    during lowering, fed to the predicate/negative cache.

    `sort_value_threshold` is the batch-wide dynamic top-K threshold
    (internal encoding): the same value is lowered into every lane's plan,
    so slot layouts stay uniform and the pushdown rides the existing
    stacked-scalar machinery."""
    # plan_build covers per-split lowering (storage byte-range IO surfaces
    # as storage_read_* counters) plus the host-side lane stacking
    with profiled_phase(PHASE_PLAN_BUILD) as rec:
        if rec is not None:
            rec["splits"] = len(split_ids)
            rec["stage"] = "batch"
        return _build_batch(request, doc_mapper, readers, split_ids,
                            pad_to_splits, absence_sink, sort_value_threshold)


def _build_batch(request: SearchRequest, doc_mapper: DocMapper,
                 readers: list[SplitReader], split_ids: list[str],
                 pad_to_splits: Optional[int],
                 absence_sink,
                 sort_value_threshold: Optional[float]) -> SplitBatch:
    agg_specs = parse_aggs(request.aggs) if request.aggs else []
    overrides = _global_agg_overrides(agg_specs, readers, doc_mapper)
    # a term absent from one split lowers to the uniform empty stand-in,
    # whose impact_ordered flag is part of the plan sig since format v3:
    # the stand-in must agree with the splits that DO hold the field, so
    # the lowering needs cross-reader visibility (an empty posting list is
    # vacuously sound under either storage-order claim)
    overrides["batch_readers"] = readers
    sort = request.sort_fields[0] if request.sort_fields else None
    sort_field = sort.field if sort else "_score"
    sort_order = sort.order if sort else "desc"
    sort2 = request.sort_fields[1] if len(request.sort_fields) > 1 else None

    num_docs_padded = max(r.num_docs_padded for r in readers)
    plans: list[LoweredPlan] = []
    for reader, split_id in zip(readers, split_ids, strict=True):
        plan = lower_request(
            request.query_ast, doc_mapper, reader, agg_specs,
            sort_field=sort_field, sort_order=sort_order,
            sort2_field=sort2.field if sort2 else None,
            sort2_order=sort2.order if sort2 else "desc",
            start_timestamp=request.start_timestamp,
            end_timestamp=request.end_timestamp,
            batch_overrides=overrides,
            absence_sink=(None if absence_sink is None else
                          lambda f, t, s=split_id: absence_sink(s, f, t)),
            sort_value_threshold=sort_value_threshold,
        )
        plans.append(plan)
    sigs = {p.root.sig() + p.sort.sig() + ",".join(a.sig() for a in p.aggs)
            for p in plans}
    if len(sigs) != 1:
        raise ValueError(
            "query does not lower to a uniform structure across splits "
            "(wildcard/regex/phrase-prefix queries need per-split execution)")

    template = plans[0]
    n = len(plans)
    total = pad_to_splits or n
    if total < n:
        raise ValueError(
            f"pad_to_splits={pad_to_splits} is smaller than the number of "
            f"splits ({n})")
    num_slots = len(template.arrays)

    stacked_arrays: list[np.ndarray] = []
    for slot in range(num_slots):
        key = template.array_keys[slot]
        per_split = [p.arrays[slot] for p in plans]
        dtype = per_split[0].dtype
        if any(a.dtype != dtype for a in per_split[1:]):
            # e.g. FOR-packed lanes of different widths (u8 vs u16), or a
            # packed/raw mix whose slot layout happened to coincide —
            # numpy slice assignment would truncate silently, so refuse
            # and let the service fall back to per-split execution
            raise ValueError(
                f"array slot {key!r} has non-uniform dtypes across splits "
                "(mixed column packings need per-split execution)")
        fill = _pad_fill(key, num_docs_padded, dtype)
        # uniform last-dim length: postings pad to max, doc-dim pad to padded
        max_len = max(a.shape[0] for a in per_split)
        if key.endswith((".zmin", ".zmax")):
            max_len = num_docs_padded // ZONEMAP_BLOCK
        elif key.startswith(("col.", "norm.")):
            max_len = num_docs_padded
        out = np.full((total, max_len), fill, dtype=dtype)
        for i, a in enumerate(per_split):
            out[i, : a.shape[0]] = a
        stacked_arrays.append(out)

    stacked_scalars: list[np.ndarray] = []
    for slot in range(len(template.scalars)):
        vals = [np.asarray(p.scalars[slot]) for p in plans]
        if any(v.dtype != vals[0].dtype for v in vals[1:]):
            raise ValueError(
                f"scalar slot {slot} has non-uniform dtypes across splits "
                "(mixed column packings need per-split execution)")
        out = np.zeros(total, dtype=vals[0].dtype)
        for i, v in enumerate(vals):
            out[i] = v
        stacked_scalars.append(out)

    num_docs = np.zeros(total, dtype=np.int32)
    num_docs[:n] = [p.num_docs for p in plans]
    ids = list(split_ids) + [""] * (total - n)

    # retarget the template's padded size to the batch-uniform one
    template.num_docs_padded = num_docs_padded
    return SplitBatch(
        template=template, arrays=stacked_arrays, scalars=stacked_scalars,
        num_docs=num_docs, split_ids=ids, num_docs_padded=num_docs_padded,
        doc_mapper=doc_mapper, sort_field=sort_field, sort_order=sort_order,
        sort2_field=sort2.field if sort2 else None,
        sort2_order=sort2.order if sort2 else "desc",
        readers=list(readers),
    )


# --------------------------------------------------------------------------
# merged execution

_BATCH_JIT_CACHE: dict[tuple, Any] = {}


def _merge_agg_stack(agg_out):
    """agg_out leaves carry a leading split axis [n, ...] → reduce axis 0
    (counts/sums add, min/max combine by leaf name)."""
    def red(path, leaf):
        name = None
        for p in reversed(path):
            if hasattr(p, "key"):
                name = p.key
                break
        if name == "min":
            return jnp.min(leaf, axis=0)
        if name in ("max", "hll"):  # HLL registers merge by max too
            return jnp.max(leaf, axis=0)
        if name == "stats":
            # state vector [count, sum, sum_sq, min, max]: first three add
            return jnp.concatenate([
                jnp.sum(leaf[:, :3], axis=0),
                jnp.min(leaf[:, 3:4], axis=0),
                jnp.max(leaf[:, 4:5], axis=0),
            ])
        return jnp.sum(leaf, axis=0)
    return jax.tree_util.tree_map_with_path(red, agg_out)


def batch_shardings(batch: SplitBatch, mesh: Mesh):
    """NamedShardings for the stacked inputs: every slot is sharded over the
    'splits' axis; dense per-doc slots (columns, fieldnorms) additionally
    shard their doc dimension over the 'docs' axis (the long-dimension /
    sequence-parallel analogue). XLA GSPMD inserts the ICI collectives for
    the cross-shard reductions and top-k merges."""
    from jax.sharding import NamedSharding
    array_shardings = []
    for key in batch.template.array_keys:
        if key.startswith(("col.", "norm.")) \
                and not key.endswith((".zmin", ".zmax")):
            array_shardings.append(NamedSharding(mesh, P("splits", "docs")))
        else:
            # zonemaps are per-BLOCK (padded/512), not per-doc: replicate
            # along the doc axis so block gating never crosses shards
            array_shardings.append(NamedSharding(mesh, P("splits", None)))
    scalar_shardings = [NamedSharding(mesh, P("splits"))] * len(batch.template.scalars)
    nd_sharding = NamedSharding(mesh, P("splits"))
    return tuple(array_shardings), tuple(scalar_shardings), nd_sharding


def batch_fn(batch: SplitBatch, k: int, exact: bool = False):
    """The unjitted merged-batch closure (arrays, scalars, num_docs) →
    result tree — exposed so measurement harnesses can wrap it (e.g. in a
    device-side repeat loop) before jitting."""
    template = batch.template
    single_fn = executor_mod._build(template, k, exact)

    def fn(arrays, scalars, num_docs):
        results = jax.vmap(single_fn)(arrays, scalars, num_docs)
        sort_vals, sort_vals2, doc_ids, hit_scores, counts, topk_safe, \
            agg_out = results
        total = jnp.sum(counts)
        # one certificate for the whole batch: any unsafe split taints the
        # cross-split merge, so the host re-runs the batch exactly
        safe = jnp.min(topk_safe)
        if k == 0:  # count/agg-only: no cross-split hit merge
            empty_i = jnp.zeros((0,), jnp.int32)
            return (jnp.zeros((0,), sort_vals.dtype), None, empty_i, empty_i,
                    jnp.zeros((0,), hit_scores.dtype), total, safe,
                    _merge_agg_stack(agg_out))
        # flatten [n, k] → [n*k]; split-major order keeps the
        # (key desc, split asc, doc asc) tie-break of the collector
        if sort_vals2 is None:
            top_vals, pos = jax.lax.top_k(sort_vals.reshape(-1), k)
            top_vals2 = None
        else:
            # 2-key sorts: lexicographic cross-split re-top-k (the same
            # kernel the per-split path uses, over the flattened winners)
            from ..ops import topk as topk_ops
            top_vals, top_vals2, pos = topk_ops.exact_topk_2key(
                sort_vals.reshape(-1), sort_vals2.reshape(-1), k)
        split_idx = (pos // k).astype(jnp.int32)
        flat_ids = doc_ids.reshape(-1)[pos]
        flat_scores = hit_scores.reshape(-1)[pos]
        return top_vals, top_vals2, split_idx, flat_ids, flat_scores, \
            total, safe, _merge_agg_stack(agg_out)

    return fn


def _mesh_axes(mesh: Mesh) -> tuple[str, Optional[str]]:
    """(split_axis_name, doc_axis_name) of a fanout mesh. Axis names come
    from the mesh itself (not hard-coded literals) so qwir's R4 planted-
    defect fixtures can trace the SAME program builder over a mis-named
    mesh and watch the rule fire."""
    names = mesh.axis_names
    return names[0], (names[1] if len(names) > 1 else None)


def _usable_mesh(batch: SplitBatch, mesh: Optional[Mesh]) -> Optional[Mesh]:
    """A mesh the batch can actually shard over, else None (single-device
    host-merge degenerate). NamedSharding refuses ragged dimension-0
    shards outright, so a split axis that does not divide the batch has
    no partial fallback — the service's `device_mesh` only hands out
    dividing axes; this guards direct `execute_batch`/staging callers."""
    if mesh is None:
        return None
    split_ax, _doc_ax = _mesh_axes(mesh)
    return mesh if batch.n_splits % mesh.shape[split_ax] == 0 else None


def _merge_agg_collective(agg_out, split_ax: str):
    """`_merge_agg_stack`'s collective twin: the local [local_n, ...] stack
    reduces over axis 0 on each device, then the SAME per-leaf combiner
    runs once more across the split mesh axis (psum / pmin / pmax), so the
    merged states land replicated on every device — no host merge.

    Exactness: counts, bucket tallies, and HLL registers are integral-
    valued, so f64 reduction re-association cannot change them; float
    metric sums reassociate across the device tree exactly like the host
    `jnp.sum` already could across lanes (docs/multichip.md spells out the
    contract the equivalence suite pins with integral fixtures)."""
    from jax import lax

    def red(path, leaf):
        name = None
        for p in reversed(path):
            if hasattr(p, "key"):
                name = p.key
                break
        if name == "min":
            return lax.pmin(jnp.min(leaf, axis=0), split_ax)
        if name in ("max", "hll"):  # HLL registers merge by max too
            return lax.pmax(jnp.max(leaf, axis=0), split_ax)
        if name == "stats":
            # state vector [count, sum, sum_sq, min, max]: first three add
            return jnp.concatenate([
                lax.psum(jnp.sum(leaf[:, :3], axis=0), split_ax),
                lax.pmin(jnp.min(leaf[:, 3:4], axis=0), split_ax),
                lax.pmax(jnp.max(leaf[:, 4:5], axis=0), split_ax),
            ])
        return lax.psum(jnp.sum(leaf, axis=0), split_ax)
    return jax.tree_util.tree_map_with_path(red, agg_out)


def mesh_batch_fn(batch: SplitBatch, k: int, mesh: Mesh, exact: bool = False):
    """The whole query as ONE explicitly-collective SPMD program
    (shard_map): each device scores its split shard with the vmapped
    per-split kernel, then the root merge — formerly host Python in
    search/collector.py — runs on-mesh:

      1. threshold exchange: each device's k-th best primary sort value is
         all-reduce-max'd (`pmax`) across the split axis. The max of the
         per-device k-th values lower-bounds the global k-th value (the
         winning device already holds k candidates at or above it), so
         every candidate STRICTLY below it is provably outside the global
         top-K and is masked to -inf — the cross-device analogue of
         ops/topk.apply_threshold_mask's `>=`-keeps-ties rule, composing
         with the cross-chunk threshold the collector threads between
         dispatches.
      2. top-K merge: surviving candidates `all_gather` along the split
         axis — device order equals split order under the P("splits")
         input sharding, so the concatenation is split-major and
         `lax.top_k`'s lowest-index tie-break reproduces the collector's
         (key desc, split_id asc, doc asc) total order bit-for-bit, the
         same argument as the host `batch_fn` merge (2-key sorts ride
         `exact_topk_2key` over the gathered pairs).
      3. agg + count reduce: mergeable agg states, hit counts, and the
         guided-top-k certificate reduce via psum/pmin/pmax.

    The doc mesh axis shards dense column storage at rest
    (`batch_shardings`); compute replicates along it here, so collectives
    bind only the split axis and every docs replica holds identical
    results — out_specs are fully replicated. One dispatch, one packed
    scalar readback."""
    from jax import lax
    from jax.experimental.shard_map import shard_map

    template = batch.template
    single_fn = executor_mod._build(template, k, exact)
    split_ax, _doc_ax = _mesh_axes(mesh)
    axis_splits = mesh.shape[split_ax]
    if batch.n_splits % axis_splits:
        raise ValueError(
            f"n_splits={batch.n_splits} does not shard over the "
            f"{axis_splits}-way {split_ax!r} mesh axis (pad the batch)")

    def shard_body(arrays, scalars, num_docs):
        results = jax.vmap(single_fn)(arrays, scalars, num_docs)
        sort_vals, sort_vals2, doc_ids, hit_scores, counts, topk_safe, \
            agg_out = results
        total = lax.psum(jnp.sum(counts), split_ax)
        # one certificate for the whole batch (see batch_fn): pmin is the
        # cross-device jnp.min
        safe = lax.pmin(jnp.min(topk_safe), split_ax)
        merged = _merge_agg_collective(agg_out, split_ax)
        if k == 0:  # count/agg-only: no candidates to exchange or gather
            empty_i = jnp.zeros((0,), jnp.int32)
            return (jnp.zeros((0,), sort_vals.dtype), None, empty_i, empty_i,
                    jnp.zeros((0,), hit_scores.dtype), total, safe, merged)
        flat = sort_vals.reshape(-1)          # [local_n * k], split-major
        neg_inf = jnp.asarray(-jnp.inf, flat.dtype)
        # -- threshold exchange (one pmax round per dispatch) ------------
        local_kth = lax.top_k(flat, k)[0][k - 1]
        threshold = lax.pmax(local_kth, split_ax)
        keep = flat >= threshold              # >= keeps threshold ties
        flat = jnp.where(keep, flat, neg_inf)
        # -- split-axis gather + re-top-k --------------------------------
        g_vals = lax.all_gather(flat, split_ax, axis=0, tiled=True)
        g_ids = lax.all_gather(doc_ids.reshape(-1), split_ax,
                               axis=0, tiled=True)
        g_scores = lax.all_gather(hit_scores.reshape(-1), split_ax,
                                  axis=0, tiled=True)
        if sort_vals2 is None:
            top_vals, pos = lax.top_k(g_vals, k)
            top_vals2 = None
        else:
            flat2 = jnp.where(keep, sort_vals2.reshape(-1), neg_inf)
            g_vals2 = lax.all_gather(flat2, split_ax, axis=0, tiled=True)
            from ..ops import topk as topk_ops
            top_vals, top_vals2, pos = topk_ops.exact_topk_2key(
                g_vals, g_vals2, k)
        split_idx = (pos // k).astype(jnp.int32)
        return (top_vals, top_vals2, split_idx, g_ids[pos], g_scores[pos],
                total, safe, merged)

    in_arrays = tuple(P(split_ax) for _ in batch.arrays)
    in_scalars = tuple(P(split_ax) for _ in batch.scalars)
    return shard_map(shard_body, mesh=mesh,
                     in_specs=(in_arrays, in_scalars, P(split_ax)),
                     out_specs=P(), check_rep=False)


def batch_cache_key(batch: SplitBatch, k: int, mesh: Optional[Mesh],
                    exact: bool = False) -> tuple:
    """The `_BATCH_JIT_CACHE` key `dispatch_batch` uses, post k-clamp —
    mirrored here for tools/qwir's compile-cache closure certificate (must
    stay in lockstep with the key expression in `dispatch_batch`)."""
    k = min(k, batch.num_docs_padded)
    return (batch.template.signature(k), batch.n_splits,
            batch.num_docs_padded, mesh, exact)


def abstract_batch_program(batch: SplitBatch, k: int, exact: bool = False):
    """ClosedJaxpr of the fused merged-batch program (`batch_fn`'s closure,
    minus the packed f64 readback concat) — abstract-traced over
    ShapeDtypeStructs, never compiled or executed, no mesh required.

    The mesh dispatch path no longer relies on GSPMD inference — it jits
    the explicitly-collective `mesh_batch_fn`; use
    `abstract_mesh_batch_program` to audit that one."""
    k = min(max(0, k), batch.num_docs_padded)
    fn = batch_fn(batch, k, exact)
    arrays = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                   for a in batch.arrays)
    scalars = tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                    for s in batch.scalars)
    nd = jax.ShapeDtypeStruct(batch.num_docs.shape, batch.num_docs.dtype)
    return jax.make_jaxpr(fn)(arrays, scalars, nd)


def abstract_mesh_batch_program(batch: SplitBatch, k: int, mesh: Mesh,
                                exact: bool = False):
    """ClosedJaxpr of the collective whole-query program (`mesh_batch_fn`,
    minus the packed f64 readback concat) — abstract-traced, never
    compiled or executed. Unlike `abstract_batch_program`, the collectives
    here are EXPLICIT eqns (shard_map + psum/pmax/pmin/all_gather), which
    is what makes qwir R4's mesh-axis rule load-bearing: every collective
    must bind axes declared by the program's ProgramSpec."""
    k = min(max(0, k), batch.num_docs_padded)
    fn = mesh_batch_fn(batch, k, mesh, exact)
    arrays = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                   for a in batch.arrays)
    scalars = tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                    for s in batch.scalars)
    nd = jax.ShapeDtypeStruct(batch.num_docs.shape, batch.num_docs.dtype)
    return jax.make_jaxpr(fn)(arrays, scalars, nd)


# qwir R2 certification registry (see executor.py's for semantics): the
# cross-split merge re-top-k's the flattened per-split winners — an f64
# sort over n_splits*k lanes, O(fan-out × page size), NOT corpus-scale.
# The corpus-scale sorts it consumes already ran under the certified
# ops/topk.py kernels inside the vmapped per-split programs.
QWIR_CERTIFIED_F64 = {
    "fn": (
        "batch_fn's cross-split merge: lax.top_k / exact_topk_2key over "
        "the flattened [n_splits*k] per-split winners — bounded by fan-out "
        "times page size, never by corpus size."),
    "shard_body": (
        "mesh_batch_fn's on-mesh root merge: the same cross-split "
        "re-top-k as batch_fn over the all_gather'd [n_splits*k] "
        "threshold-surviving winners, plus the k-element threshold "
        "exchange sort — bounded by fan-out times page size."),
}


def _donate_batch_inputs(mesh: Optional[Mesh] = None) -> bool:
    """Donate the stacked batch arrays to the executor so XLA reuses their
    HBM as scratch: the stacks are per-request copies of the column data
    (the resident per-split arrays are NOT what is donated) and are
    invalidated after the dispatch that consumed them. CPU PJRT does not
    implement donation and warns per compile, so gate on backend. Mesh
    dispatches never donate: their staged tuples may alias mesh-resident
    column stacks (`_stage_resident_stack`) that must survive the query —
    and the decision is baked into the cached jit, which is keyed only on
    (signature, n_splits, padded, mesh, exact), not on residency."""
    return mesh is None and jax.default_backend() != "cpu"


def _collective_payload_bytes(shaped, k: int, n_splits: int) -> int:
    """Logical bytes the mesh program's collectives carry per dispatch
    (`qw_mesh_collective_bytes_total` semantics): all_gather candidates +
    the reduced agg/count/certificate leaves + the 8-byte threshold
    exchange. `shaped` is the eval_shape output tree of `mesh_batch_fn`."""
    has2 = shaped[1] is not None
    gather = 0 if k == 0 else n_splits * k * (8 + (8 if has2 else 0) + 4 + 4)
    reduced = sum(int(np.prod(leaf.shape)) * leaf.dtype.itemsize
                  for leaf in jax.tree_util.tree_leaves(shaped[-1]))
    reduced += 4 + 8                        # total count + safe certificate
    exchange = 0 if k == 0 else 8           # one pmax'd f64 scalar
    return gather + reduced + exchange


def _batch_executor(batch: SplitBatch, k: int, mesh: Optional[Mesh],
                    example_args, exact: bool = False):
    """(jitted_packed_fn, treedef, spec, meta): the merged result tree
    rides ONE f64 device array so the readback is a single transfer (see
    executor.py packed-readback rationale; exactness argument identical).

    With a mesh, the jitted program is the explicitly-collective
    `mesh_batch_fn` (the whole root merge on-device); without one it is
    the host-degenerate `batch_fn`. Callers never reach here with a mesh
    whose split axis does not divide the batch: `_usable_mesh` drops such
    meshes to the single-device path at dispatch time (NamedSharding
    rejects ragged dimension-0 shards at staging, so there is no partial
    fallback to salvage)."""
    collective = mesh is not None
    fn = (mesh_batch_fn(batch, k, mesh, exact) if collective
          else batch_fn(batch, k, exact))
    shaped = jax.eval_shape(fn, *example_args)
    treedef = jax.tree_util.tree_structure(shaped)
    spec = [(leaf.shape, leaf.dtype)
            for leaf in jax.tree_util.tree_leaves(shaped)]
    meta = {"collective_bytes": _collective_payload_bytes(
        shaped, k, batch.n_splits)} if collective else None

    def packed(arrays, scalars, num_docs):
        out = fn(arrays, scalars, num_docs)
        flat = [leaf.reshape(-1).astype(jnp.float64)
                for leaf in jax.tree_util.tree_leaves(out)]
        return jnp.concatenate(flat) if flat else jnp.zeros((0,))

    donate = (0,) if _donate_batch_inputs(mesh) else ()
    if mesh is None:
        return jax.jit(packed, donate_argnums=donate), treedef, spec, meta
    arrays_sh, scalars_sh, nd_sh = batch_shardings(batch, mesh)
    return (jax.jit(packed, in_shardings=(arrays_sh, scalars_sh, nd_sh),
                    donate_argnums=donate),
            treedef, spec, meta)


# Column-family slots are query-independent given the split set: packed
# fast-field values, fieldnorms, and their zonemaps derive only from the
# readers and the batch-uniform padded size (even batch-global ordinal
# spaces: the terms dictionary union is over the SPLIT SET, not the
# query). Postings ("pre."/"post.") and masks are query-shaped — for
# format v3 threshold pushdown even re-sliced per threshold — so they
# stream per request and are never stack-resident.
_STACK_RESIDENT_PREFIXES = ("col.", "norm.")


def stack_resident_slots(batch: SplitBatch) -> list[int]:
    """Array slots eligible for the cross-query mesh-resident stack."""
    return [slot for slot, key in enumerate(batch.template.array_keys)
            if key.startswith(_STACK_RESIDENT_PREFIXES)]


def per_device_bytes(batch: SplitBatch, mesh: Optional[Mesh],
                     exclude_stack_resident: bool = False) -> int:
    """The PER-DEVICE HBM footprint of the staged batch — what tenant-DRR
    admission should pin when the stacks shard over a mesh. Dense column
    slots divide across both axes (P("splits", "docs")); everything else
    divides across the split axis only (`batch_shardings`). Without a
    mesh this is the full single-device byte count the seed admitted.

    `exclude_stack_resident` drops the column-family slots: when the
    mesh-resident stack store is active those bytes are admitted under
    the stack owner by `stage_device_inputs` (and stay resident after the
    query), so admitting them under the per-request batch owner too would
    double-pin warm queries."""
    if mesh is None:
        return sum(a.nbytes for a in batch.arrays)
    split_ax, doc_ax = _mesh_axes(mesh)
    n_sp = mesh.shape[split_ax]
    n_doc = mesh.shape.get(doc_ax, 1) if doc_ax else 1
    resident = set(stack_resident_slots(batch)) if exclude_stack_resident \
        else set()
    total = 0
    for slot, (key, a) in enumerate(zip(batch.template.array_keys,
                                        batch.arrays)):
        if slot in resident:
            continue
        if key.startswith(("col.", "norm.")) \
                and not key.endswith((".zmin", ".zmax")):
            total += -(-a.nbytes // (n_sp * n_doc))
        else:
            total += -(-a.nbytes // n_sp)
    total += sum(-(-s.nbytes // n_sp) for s in batch.scalars)
    total += batch.num_docs.nbytes
    return total


def release_stack_pin(batch: SplitBatch, budget) -> None:
    """Release the mesh-resident stack's admission pin taken by
    `stage_device_inputs`. The default `release` leaves the bytes RESIDENT
    (the owner carries `_device_array_cache`), so the stack survives for
    the next warm query; LRU pressure evicts it through HbmBudget's
    existing owner seam."""
    pin = getattr(batch, "_mesh_stack_pin", None)
    if pin is None:
        return
    batch._mesh_stack_pin = None
    owner, admitted = pin
    budget.release(owner, admitted)


def _stage_resident_stack(batch: SplitBatch, mesh: Mesh, arrays_sh,
                          store, budget) -> dict[int, Any]:
    """Serve the column-family slots from (and populate) the cross-query
    mesh-resident stack: slot → committed sharded device array. A warm
    repeat query finds every column slot resident and uploads ZERO column
    bytes to ANY chip; per-device byte accounting rides the existing
    HbmBudget owner seam (admit under the stack owner, release-to-resident
    after the query via `release_stack_pin`)."""
    from ..search.residency import mesh_stack_id
    split_ax, doc_ax = _mesh_axes(mesh)
    n_sp = mesh.shape[split_ax]
    n_doc = mesh.shape.get(doc_ax, 1) if doc_ax else 1
    stack_id = mesh_stack_id(batch.split_ids, batch.num_docs_padded, mesh)
    owner = store.columns_for(stack_id)
    dcache = owner._device_array_cache
    slots = stack_resident_slots(batch)
    entries = []
    for slot in slots:
        key = batch.template.array_keys[slot]
        arr = batch.arrays[slot]
        # shape+dtype in the key: format-version packings (u8/u16 FOR
        # lanes) and padding buckets must never alias
        entries.append((slot, (key, arr.shape, str(arr.dtype))))
    missing = [(slot, ck) for slot, ck in entries if ck not in dcache]
    per_dev = 0
    for slot, _ck in missing:
        key = batch.template.array_keys[slot]
        nbytes = batch.arrays[slot].nbytes
        if key.endswith((".zmin", ".zmax")):
            per_dev += -(-nbytes // n_sp)
        else:
            per_dev += -(-nbytes // (n_sp * n_doc))
    admitted = budget.admit(owner, per_dev) if budget is not None else 0
    try:
        if missing:
            for slot, ck in missing:
                dcache[ck] = jax.device_put(batch.arrays[slot],
                                            arrays_sh[slot])
            store.note_upload(stack_id, per_dev, len(missing))
            store.note_hits(len(slots) - len(missing), full=False)
        elif slots:
            store.note_hits(len(slots), full=True)
        batch._mesh_stack_pin = (owner, admitted)
        return {slot: dcache[ck] for slot, ck in entries}
    except BaseException:
        if budget is not None:
            budget.release(owner, admitted, to_resident=False)
        raise


def stage_device_inputs(batch: SplitBatch, mesh: Optional[Mesh] = None,
                        resident_store=None, budget=None):
    """Start the batch's host→device transfer (async under JAX dispatch)
    and cache the device arrays on the batch for repeat queries — keyed by
    mesh: arrays committed for one sharding must not feed an executor
    compiled for another. Callable from a prefetch thread so the transfer
    overlaps the previous batch's kernel execution.

    With a mesh and a resident store, column-family slots are served from
    the cross-query mesh stack (`_stage_resident_stack`): only the
    query-shaped slots (postings, scalars, doc counts) ride this request's
    upload."""
    mesh = _usable_mesh(batch, mesh)
    cache = getattr(batch, "_device_inputs", None)
    if cache is None:
        cache = batch._device_inputs = {}
    dev = cache.get(mesh)
    if dev is not None:
        # re-dispatch of an already-staged batch (hedged retry, readback
        # replay): record the skip so the waterfall shows where staging
        # would have been
        with profiled_phase(PHASE_STAGING_CACHE_HIT) as rec:
            if rec is not None:
                rec["bytes"] = 0
                rec["stage"] = "batch"
        flight.emit("staging.resident_hit", attrs={"stage": "batch"})
        return dev
    if dev is None:
        arrays_sh = scalars_sh = nd_sh = None
        if mesh is not None:
            arrays_sh, scalars_sh, nd_sh = batch_shardings(batch, mesh)
        resident: dict[int, Any] = {}
        if (mesh is not None and resident_store is not None
                and getattr(resident_store, "enabled", False)):
            resident = _stage_resident_stack(batch, mesh, arrays_sh,
                                             resident_store, budget)
        staging_bytes = (sum(a.nbytes for slot, a in enumerate(batch.arrays)
                             if slot not in resident)
                         + sum(s.nbytes for s in batch.scalars)
                         + batch.num_docs.nbytes)
        # staging times the transfer DISPATCH (device_put is async;
        # completion overlaps into the execute phase by design — same
        # contract as the per-split warmup in search/leaf.py)
        with profiled_phase(PHASE_STAGING_UPLOAD) as rec:
            if rec is not None:
                rec["bytes"] = staging_bytes
                rec["stage"] = "batch"
            if mesh is not None:
                arrays = tuple(
                    resident[slot] if slot in resident
                    else jax.device_put(a, arrays_sh[slot])
                    for slot, a in enumerate(batch.arrays))
                scalars = tuple(jax.device_put(batch.scalars,
                                               list(scalars_sh))) \
                    if batch.scalars else ()
                nd = jax.device_put(batch.num_docs, nd_sh)
            else:
                moved = jax.device_put(
                    batch.arrays + batch.scalars + [batch.num_docs])
                arrays = tuple(moved[: len(batch.arrays)])
                scalars = tuple(moved[len(batch.arrays):-1])
                nd = moved[-1]
        profile_add("staging_bytes", staging_bytes)
        if flight.recording():
            flight.emit("staging.upload",
                        attrs={"bytes": staging_bytes,
                               "resident_slots": len(resident)})
        dev = cache[mesh] = (arrays, scalars, nd)
    return dev


# Mesh programs contain cross-device collectives (the on-mesh root
# merge's psums/all-reduces). Two such programs enqueued concurrently
# from different query threads can interleave their per-device rendezvous
# (thread A first on device 0, thread B first on device 1) and deadlock —
# observed as 5s+ AllReduceParticipantData stalls under the soak suite's
# 8-thread storm on the 8-fake-device CPU host platform. Enqueue is
# therefore serialized; on real hardware the per-device streams then
# execute programs in one consistent order, the enqueue itself is a cheap
# async launch, and the lock releases immediately. The CPU host platform
# has NO ordered streams (a shared thread pool with data-dependency
# ordering only), so there the critical section must span enqueue →
# completion: `_enqueue_batch` returns the still-held lock as a guard and
# the caller releases it AFTER awaiting the program (`readback_batch`'s
# device_get, or `abandon_dispatch` on the deadline-shed path) — the
# blocking wait itself runs OUTSIDE any lexical lock scope, so waiters
# queue on the guard, not on a device round-trip hidden inside a `with`
# block. Single-device dispatches (mesh is None) carry no collectives and
# take no lock.
# qwlint: disable-next-line=QW008 - leaf lock by design: the critical
# section is a jax enqueue (hardware) or enqueue→completion (CPU host
# platform), never a seam primitive, so the gated qwrace scheduler cannot
# preempt inside it and instrumenting it would only serialize jax
# dispatch behind the token
_MESH_DISPATCH_LOCK = threading.Lock()


def _enqueue_batch(ex, arrays, scalars, nd, mesh):
    """Enqueue one batch program; returns (out, guard). `guard` is the
    still-held `_MESH_DISPATCH_LOCK` on the CPU host platform (the caller
    MUST hand it to `_finish_mesh_dispatch` once the program has been
    awaited), None otherwise."""
    if mesh is None:
        return ex(arrays, scalars, nd), None
    _MESH_DISPATCH_LOCK.acquire()
    try:
        out = ex(arrays, scalars, nd)
    except BaseException:
        _MESH_DISPATCH_LOCK.release()
        raise
    if jax.default_backend() != "cpu":
        _MESH_DISPATCH_LOCK.release()
        return out, None
    return out, _MESH_DISPATCH_LOCK


def _finish_mesh_dispatch(guard, out=None) -> None:
    """Complete the cross-procedural mesh-dispatch critical section: await
    the program if the caller has not already (readback's `device_get`
    subsumes the wait, so it passes out=None), then release the guard."""
    if guard is None:
        return
    try:
        if out is not None:
            jax.block_until_ready(out)
    finally:
        guard.release()


def abandon_dispatch(dispatched) -> None:
    """Deadline-shed seam: the dispatch flew but nobody will await its
    readback. The mesh-dispatch guard (CPU host platform) must still see
    the program complete before the next collective program may enqueue;
    device buffers die with their last reference."""
    out, _treedef, _spec, _ctx, guard = dispatched
    _finish_mesh_dispatch(guard, out)


def dispatch_batch(batch: SplitBatch, request: SearchRequest,
                   mesh: Optional[Mesh] = None, exact: bool = False):
    """Async half of the fused batch dispatch: stage (or reuse) the device
    inputs, enqueue ONE XLA program over all splits, start the D2H copy of
    the packed result, and return without blocking. `readback_batch`
    completes it — the seam lets the service shed deadline-expired queries
    before ever paying the readback wait, and overlap the next group's
    dispatch with this one's readback."""
    # cancelled queries stop HERE, before staging device inputs or paying
    # an enqueue nobody will read (the readback seam checks again)
    from ..common.deadline import check_cancelled
    check_cancelled("batch dispatch")
    mesh = _usable_mesh(batch, mesh)
    # k=0 (count/agg-only): per-split executors skip keying/top-k and the
    # batch merge skips the cross-split top_k
    k = min(request.start_offset + request.max_hits, batch.num_docs_padded)
    if batch.template.threshold_slot >= 0 and not exact:
        from ..observability.metrics import SEARCH_KERNEL_THRESHOLD_TOTAL
        # one dispatch, but each real lane's docs are threshold-masked
        SEARCH_KERNEL_THRESHOLD_TOTAL.inc(
            sum(1 for s in batch.split_ids if s))
    arrays, scalars, nd = stage_device_inputs(batch, mesh)
    # Mesh is hashable; id() would go stale if a dead mesh's address is reused
    key = (batch.template.signature(k), batch.n_splits,
           batch.num_docs_padded, mesh, exact)
    profile = current_profile()
    cached = _BATCH_JIT_CACHE.get(key)
    if flight.recording():
        flight.emit("compile.hit" if cached is not None else "compile.miss",
                    attrs={"path": "batch"})
        flight.emit("dispatch.launch",
                    attrs={"path": "batch", "splits": batch.n_splits,
                           "mesh": mesh.size if mesh is not None else 0})
    if profile is None:
        if cached is None:
            cached = _batch_executor(batch, k, mesh, (arrays, scalars, nd),
                                     exact)
            _BATCH_JIT_CACHE[key] = cached
        ex, treedef, spec, meta = cached
        out, guard = _enqueue_batch(ex, arrays, scalars, nd, mesh)
    else:
        # Compile-vs-execute attribution (same lazy-jit approximation as
        # executor.dispatch_plan): on a batch-jit-cache MISS the first call
        # pays trace+XLA-compile; on a HIT the dispatch is a cheap enqueue
        # and the blocking device_get absorbs the device execution time.
        hit = cached is not None
        profile.add("compile_cache_hits" if hit else "compile_cache_misses")
        with profile.phase(PHASE_EXECUTE if hit else PHASE_COMPILE,
                           stage="dispatch_batch"):
            if cached is None:
                cached = _batch_executor(batch, k, mesh,
                                         (arrays, scalars, nd), exact)
                _BATCH_JIT_CACHE[key] = cached
            ex, treedef, spec, meta = cached
            out, guard = _enqueue_batch(ex, arrays, scalars, nd, mesh)
    try:
        if meta is not None:
            from ..observability.metrics import (
                MESH_COLLECTIVE_BYTES_TOTAL, MESH_DEVICES,
                MESH_DISPATCHES_TOTAL, MESH_THRESHOLD_EXCHANGE_ROUNDS_TOTAL,
            )
            MESH_DISPATCHES_TOTAL.inc()
            MESH_DEVICES.set(mesh.size)
            MESH_COLLECTIVE_BYTES_TOTAL.inc(meta["collective_bytes"])
            if k > 0:
                MESH_THRESHOLD_EXCHANGE_ROUNDS_TOTAL.inc()
            if flight.recording():
                flight.emit("mesh.collective",
                            attrs={"devices": mesh.size,
                                   "bytes": meta["collective_bytes"],
                                   "threshold_exchange": int(k > 0)})
        if _donate_batch_inputs(mesh):
            # the stacked inputs were donated into this dispatch — drop the
            # staging-cache entry so nothing touches the dead buffers
            cache = getattr(batch, "_device_inputs", None)
            if cache is not None:
                cache.pop(mesh, None)
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()
    except BaseException:
        _finish_mesh_dispatch(guard, out)
        raise
    return out, treedef, spec, (batch, request, mesh, k), guard


def readback_batch(dispatched) -> LeafSearchResponse:
    """Blocking half of the fused batch dispatch: await the packed scalar
    readback, unpack, host-decode the merged hits/aggs. A `safe == 0`
    guided-top-k certificate triggers one exact re-execution of the whole
    batch (see ops/topk.py:guided_topk)."""
    out, treedef, spec, (batch, request, mesh, k), guard = dispatched
    # the dispatch already flew; a cancel landing in between still saves
    # the device->host transfer wait (the mesh-dispatch guard must still
    # observe completion before releasing — abandon, then re-raise)
    from ..common.deadline import check_cancelled
    try:
        check_cancelled("batch readback")
    except BaseException:
        _finish_mesh_dispatch(guard, out)
        raise
    profile = current_profile()
    t0 = _clock_monotonic() if flight.recording() else 0.0
    try:
        if profile is None:
            packed = jax.device_get(out)
        else:
            with profile.phase(PHASE_EXECUTE, stage="readback"):
                packed = jax.device_get(out)
    except BaseException:
        _finish_mesh_dispatch(guard, out)
        raise
    if flight.recording():
        flight.emit("dispatch.readback", attrs={
            "path": "batch",
            "dur_ms": round((_clock_monotonic() - t0) * 1000.0, 3)})
    # device_get returned only after the program ran to completion — the
    # cross-procedural critical section ends here, BEFORE any exact
    # re-dispatch below re-enters _enqueue_batch (the lock is not
    # re-entrant)
    _finish_mesh_dispatch(guard)
    leaves = []
    offset = 0
    for shape, dtype in spec:
        size = int(np.prod(shape)) if shape else 1
        leaves.append(packed[offset: offset + size]
                      .astype(dtype).reshape(shape))
        offset += size
    top_vals, top_vals2, split_idx, doc_ids, scores, total, topk_safe, \
        merged_aggs = jax.tree_util.tree_unflatten(treedef, leaves)
    if float(topk_safe) < 1.0:
        executor_mod._note_guided_fallback()
        return execute_batch(batch, request, mesh, exact=True)

    return _decode_merged(batch, k, top_vals, top_vals2, split_idx,
                          doc_ids, scores, int(total), merged_aggs)


def _decode_merged(batch: SplitBatch, k: int, top_vals, top_vals2,
                   split_idx, doc_ids, scores, num_hits: int,
                   merged_aggs) -> LeafSearchResponse:
    """Host decode of one merged (cross-split) result into a
    LeafSearchResponse — shared by the single-query batch readback and the
    per-lane unpack of a stacked query-group readback (one lane's slice of
    the [Q, ...] result is exactly one merged batch result)."""
    hits: list[PartialHit] = []
    sort_is_int = _sort_values_are_int(batch.doc_mapper, batch.sort_field)
    sort2_is_int = (_sort_values_are_int(batch.doc_mapper, batch.sort2_field)
                    if batch.sort2_field else False)
    exact_cols: dict[tuple, Any] = {}

    def exact_col(si: int, field: str, is_int: bool):
        if not is_int or batch.readers is None:
            return None
        if (si, field) not in exact_cols:
            exact_cols[(si, field)] = \
                batch.readers[si].column_values(field)[0]
        return exact_cols[(si, field)]

    with profiled_phase(PHASE_TOPK_MERGE) as rec:
        for i in range(min(k, num_hits)):
            internal = float(top_vals[i])
            if internal == float("-inf"):
                break
            si = int(split_idx[i])
            split_id = batch.split_ids[si]
            if split_id == "":
                continue
            doc_id = int(doc_ids[i])
            raw = decode_sort_value_exact(
                internal, batch.sort_field, batch.sort_order, sort_is_int,
                scores[i], doc_id,
                exact_col(si, batch.sort_field, sort_is_int))
            internal2, raw2 = 0.0, None
            if batch.sort2_field is not None and top_vals2 is not None:
                internal2 = float(top_vals2[i])
                raw2 = decode_sort_value_exact(
                    internal2, batch.sort2_field, batch.sort2_order,
                    sort2_is_int, scores[i], doc_id,
                    exact_col(si, batch.sort2_field, sort2_is_int))
            hits.append(PartialHit(sort_value=internal, split_id=split_id,
                                   doc_id=doc_id, raw_sort_value=raw,
                                   sort_value2=internal2,
                                   raw_sort_value2=raw2))
        intermediate = _intermediate_aggs(batch.template, list(merged_aggs))
        if rec is not None:
            rec["hits"] = len(hits)
            rec["stage"] = "batch"
    real_splits = sum(1 for s in batch.split_ids if s)
    return LeafSearchResponse(
        num_hits=num_hits,
        partial_hits=hits,
        num_attempted_splits=real_splits,
        num_successful_splits=real_splits,
        intermediate_aggs=intermediate,
    )


def execute_batch(batch: SplitBatch, request: SearchRequest,
                  mesh: Optional[Mesh] = None,
                  exact: bool = False) -> LeafSearchResponse:
    """Run the batch (optionally mesh-sharded) and emit one merged
    LeafSearchResponse covering all splits."""
    return readback_batch(dispatch_batch(batch, request, mesh, exact))


# --------------------------------------------------------------------------
# query-axis × mesh composition (ROADMAP item 2 over item 6)
#
# N shape-compatible queries over the SAME split set execute as ONE mesh
# program: a leading `queries` axis is vmapped INSIDE each device shard
# (never a mesh axis — chips shard data, lanes share chips), operand slots
# whose cache key agrees across the group broadcast once from the
# mesh-resident column stack, query-shaped slots (postings, masks) gain a
# [Q, n_splits, ...] leading dim sharded P(None, "splits"), and the on-mesh
# root merge becomes per-query-lane collectives: the pmax threshold
# exchange reduces a [Q] vector of per-lane k-th values, the all_gather
# carries [Q, local_n*k] candidate tiles, and mergeable-agg states reduce
# by query-id segments before the cross-device psum. A [Q] validity mask
# rides as an operand, so a rider shed after group formation lane-zeroes
# out of the packed readback without touching the compiled program.

_GROUP_JIT_CACHE: dict[tuple, Any] = {}

# Slot keys that may BROADCAST across query lanes: column families derive
# only from the readers and the padded size, so equal keys over one split
# set mean equal bytes (the same argument as the mesh-resident stack's
# cache key). Posting/mask slots are query-shaped even when their keys
# collide, so they always stack.
_GROUP_SHARED_PREFIXES = ("col.", "norm.")


def group_slot_split(batches: list) -> tuple[tuple[int, ...],
                                             tuple[int, ...]]:
    """(shared_slots, stacked_slots) for a query group: a slot broadcasts
    when every lane carries the same array key AND the key is a
    column-family key (content a pure function of the split set)."""
    t0 = batches[0].template
    shared, stacked = [], []
    for slot, key in enumerate(t0.array_keys):
        if key.startswith(_GROUP_SHARED_PREFIXES) and all(
                b.template.array_keys[slot] == key for b in batches[1:]):
            shared.append(slot)
        else:
            stacked.append(slot)
    return tuple(shared), tuple(stacked)


def _stack_group_operands(batches: list, stacked_slots) -> tuple:
    """Host-side [Q, ...] stacking of the query-shaped operands. Stacked
    slots pad their last dim to the group maximum (two terms' posting
    lists rarely agree in length) using the SAME per-key pad fill the
    split stacking uses, so pad lanes stay inert under every kernel."""
    q = len(batches)
    t0 = batches[0].template
    stacked_arrays = []
    for slot in stacked_slots:
        per_q = [b.arrays[slot] for b in batches]
        dtype = per_q[0].dtype
        if any(a.dtype != dtype for a in per_q[1:]):
            raise ValueError(
                f"group slot {t0.array_keys[slot]!r} has non-uniform "
                "dtypes across queries (incompatible column packings)")
        max_len = max(a.shape[1] for a in per_q)
        fill = _pad_fill(t0.array_keys[slot],
                         batches[0].num_docs_padded, dtype)
        out = np.full((q, per_q[0].shape[0], max_len), fill, dtype=dtype)
        for i, a in enumerate(per_q):
            out[i, :, : a.shape[1]] = a
        stacked_arrays.append(out)
    scalars_b = [np.stack([np.asarray(b.scalars[slot]) for b in batches])
                 for slot in range(len(t0.scalars))]
    return stacked_arrays, scalars_b


def _assemble_group_slots(shared, lane_stacked, shared_slots,
                          stacked_slots, num_slots) -> tuple:
    slots: list = [None] * num_slots
    for i, s in enumerate(shared_slots):
        slots[s] = shared[i]
    for i, s in enumerate(stacked_slots):
        slots[s] = lane_stacked[i]
    return tuple(slots)


def _merge_agg_group_collective(agg_out, split_ax: str, q: int):
    """`_merge_agg_collective`'s query-axis twin: leaves arrive
    [Q, local_n, ...]; the local reduction runs as ONE query-id-segmented
    device op over the flattened [Q*local_n, ...] rows
    (ops/topk.segment_merge_by_query), then the per-leaf combiner crosses
    the split mesh axis per lane (psum/pmin/pmax act elementwise over the
    leading [Q] dim). Exactness: segment_sum accumulates rows in ascending
    index order within each segment — the same left fold over local splits
    the single-query merge performs."""
    from jax import lax

    from ..ops import topk as topk_ops

    def red(path, leaf):
        name = None
        for p in reversed(path):
            if hasattr(p, "key"):
                name = p.key
                break
        local_n = leaf.shape[1]
        flat = leaf.reshape((q * local_n,) + leaf.shape[2:])
        qids = jnp.repeat(jnp.arange(q, dtype=jnp.int32), local_n)
        if name == "min":
            return lax.pmin(topk_ops.segment_merge_by_query(
                flat, qids, q, "min"), split_ax)
        if name in ("max", "hll"):  # HLL registers merge by max too
            return lax.pmax(topk_ops.segment_merge_by_query(
                flat, qids, q, "max"), split_ax)
        if name == "stats":
            # state vector [count, sum, sum_sq, min, max]: first three add
            return jnp.concatenate([
                lax.psum(topk_ops.segment_merge_by_query(
                    flat[:, :3], qids, q, "sum"), split_ax),
                lax.pmin(topk_ops.segment_merge_by_query(
                    flat[:, 3:4], qids, q, "min"), split_ax),
                lax.pmax(topk_ops.segment_merge_by_query(
                    flat[:, 4:5], qids, q, "max"), split_ax),
            ], axis=1)
        # segment_sum keeps the operand dtype, but the solo merge's
        # jnp.sum promotes integer accumulators (int32 counts → int64) —
        # widen first so the stacked readback spec matches bit-for-bit
        flat = flat.astype(jnp.zeros((), leaf.dtype).sum().dtype)
        return lax.psum(topk_ops.segment_merge_by_query(
            flat, qids, q, "sum"), split_ax)
    return jax.tree_util.tree_map_with_path(red, agg_out)


def group_fn(batches: list, k: int, exact: bool = False):
    """Host-degenerate (no-mesh) stacked group closure: the query axis
    vmaps the whole single-query merged-batch program (`batch_fn`), so
    each lane runs bit-identically to its solo batch execution. Signature:
    (shared_arrays, stacked_arrays, scalars_b, num_docs) → per-lane result
    tree with leading [Q] dims."""
    template = batches[0].template
    shared_slots, stacked_slots = group_slot_split(batches)
    num_slots = len(template.arrays)
    base = batch_fn(batches[0], k, exact)

    def fn(shared, stacked, scalars_b, num_docs):
        def lane(lane_stacked, lane_scalars):
            arrays = _assemble_group_slots(
                shared, lane_stacked, shared_slots, stacked_slots,
                num_slots)
            return base(arrays, lane_scalars, num_docs)
        return jax.vmap(lane)(tuple(stacked), tuple(scalars_b))

    return fn


def group_mesh_fn(batches: list, k: int, mesh: Mesh, exact: bool = False):
    """The query group as ONE explicitly-collective SPMD program: the
    stacked twin of `mesh_batch_fn` (same three merge steps, per query
    lane — see that docstring for the exactness arguments; each reduces
    elementwise over the leading [Q] dim, so lane q's merge consumes
    exactly the operands its solo program would):

      1. threshold exchange: [Q] per-lane k-th values, ONE pmax round.
      2. top-K merge: [Q, local_n*k] candidates all_gather along the
         split axis (axis=1, tiled — split-major per lane), then a
         batched top-k; 2-key sorts ride `ops/topk.batched_topk_2key`.
      3. agg + count reduce: query-id-segmented local merges, then
         per-lane psum/pmin/pmax (`_merge_agg_group_collective`).
    """
    from jax import lax
    from jax.experimental.shard_map import shard_map

    template = batches[0].template
    q = len(batches)
    shared_slots, stacked_slots = group_slot_split(batches)
    num_slots = len(template.arrays)
    single_fn = executor_mod._build(template, k, exact)
    split_ax, _doc_ax = _mesh_axes(mesh)
    axis_splits = mesh.shape[split_ax]
    if batches[0].n_splits % axis_splits:
        raise ValueError(
            f"n_splits={batches[0].n_splits} does not shard over the "
            f"{axis_splits}-way {split_ax!r} mesh axis (pad the batch)")

    def shard_body(shared, stacked, scalars_b, num_docs):
        def lane(lane_stacked, lane_scalars):
            arrays = _assemble_group_slots(
                shared, lane_stacked, shared_slots, stacked_slots,
                num_slots)
            return jax.vmap(single_fn)(arrays, lane_scalars, num_docs)
        results = jax.vmap(lane)(tuple(stacked), tuple(scalars_b))
        sort_vals, sort_vals2, doc_ids, hit_scores, counts, topk_safe, \
            agg_out = results
        total = lax.psum(jnp.sum(counts, axis=1), split_ax)        # [Q]
        safe = lax.pmin(jnp.min(topk_safe, axis=1), split_ax)      # [Q]
        merged = _merge_agg_group_collective(agg_out, split_ax, q)
        if k == 0:  # count/agg-only: no candidates to exchange or gather
            empty_i = jnp.zeros((q, 0), jnp.int32)
            return (jnp.zeros((q, 0), sort_vals.dtype), None, empty_i,
                    empty_i, jnp.zeros((q, 0), hit_scores.dtype), total,
                    safe, merged)
        flat = sort_vals.reshape(q, -1)     # [Q, local_n*k], split-major
        neg_inf = jnp.asarray(-jnp.inf, flat.dtype)
        # -- threshold exchange: ONE pmax round carries all Q lanes ------
        local_kth = lax.top_k(flat, k)[0][:, k - 1]
        threshold = lax.pmax(local_kth, split_ax)                  # [Q]
        keep = flat >= threshold[:, None]   # >= keeps threshold ties
        flat = jnp.where(keep, flat, neg_inf)
        # -- split-axis gather + per-lane re-top-k -----------------------
        g_vals = lax.all_gather(flat, split_ax, axis=1, tiled=True)
        g_ids = lax.all_gather(doc_ids.reshape(q, -1), split_ax,
                               axis=1, tiled=True)
        g_scores = lax.all_gather(hit_scores.reshape(q, -1), split_ax,
                                  axis=1, tiled=True)
        if sort_vals2 is None:
            # lax.top_k is batched over leading dims: [Q, n*k] → [Q, k]
            top_vals, pos = lax.top_k(g_vals, k)
            top_vals2 = None
        else:
            flat2 = jnp.where(keep, sort_vals2.reshape(q, -1), neg_inf)
            g_vals2 = lax.all_gather(flat2, split_ax, axis=1, tiled=True)
            from ..ops import topk as topk_ops
            top_vals, top_vals2, pos = topk_ops.batched_topk_2key(
                g_vals, g_vals2, k)
        split_idx = (pos // k).astype(jnp.int32)
        return (top_vals, top_vals2, split_idx,
                jnp.take_along_axis(g_ids, pos, axis=1),
                jnp.take_along_axis(g_scores, pos, axis=1),
                total, safe, merged)

    in_shared = tuple(P(split_ax) for _ in shared_slots)
    in_stacked = tuple(P(None, split_ax) for _ in stacked_slots)
    in_scalars = tuple(P(None, split_ax) for _ in template.scalars)
    return shard_map(shard_body, mesh=mesh,
                     in_specs=(in_shared, in_stacked, in_scalars,
                               P(split_ax)),
                     out_specs=P(), check_rep=False)


def group_cache_key(batches: list, k: int, mesh: Optional[Mesh] = None,
                    exact: bool = False) -> tuple:
    """The `_GROUP_JIT_CACHE` key `dispatch_query_group` uses, post
    k-clamp — mirrored here for tools/qwir's compile-cache closure
    certificate (must stay in lockstep with the key expression in
    `dispatch_query_group`). The [Q] validity mask is an OPERAND, never
    part of the key: shedding a rider does not recompile."""
    b0 = batches[0]
    k = min(k, b0.num_docs_padded)
    _shared, stacked_slots = group_slot_split(batches)
    return (b0.template.signature(k), len(batches), b0.n_splits,
            b0.num_docs_padded, stacked_slots, mesh, exact)


def _group_example_structs(batches: list, stacked_slots):
    """ShapeDtypeStructs for (shared, stacked, scalars, num_docs) of the
    group program — shared by the abstract qwir trace and eval_shape."""
    b0 = batches[0]
    shared_slots, _ = group_slot_split(batches)
    stacked_arrays, scalars_b = _stack_group_operands(batches,
                                                      stacked_slots)
    shared = tuple(jax.ShapeDtypeStruct(b0.arrays[s].shape,
                                        b0.arrays[s].dtype)
                   for s in shared_slots)
    stacked = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                    for a in stacked_arrays)
    scalars = tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                    for s in scalars_b)
    nd = jax.ShapeDtypeStruct(b0.num_docs.shape, b0.num_docs.dtype)
    return shared, stacked, scalars, nd


def abstract_group_mesh_program(batches: list, k: int, mesh: Mesh,
                                exact: bool = False):
    """ClosedJaxpr of the stacked query-group mesh program (`group_mesh_fn`,
    minus the packed readback concat and validity mask) — abstract-traced,
    never compiled or executed. The collectives are explicit eqns binding
    the declared mesh axes, same as `abstract_mesh_batch_program`; the
    query axis shows up as leading [Q] dims, NOT as a mesh axis."""
    b0 = batches[0]
    k = min(max(0, k), b0.num_docs_padded)
    _shared_slots, stacked_slots = group_slot_split(batches)
    fn = group_mesh_fn(batches, k, mesh, exact)
    shared, stacked, scalars, nd = _group_example_structs(batches,
                                                          stacked_slots)
    return jax.make_jaxpr(fn)(shared, stacked, scalars, nd)


def _group_executor(batches: list, k: int, mesh: Optional[Mesh],
                    exact: bool = False):
    """(jitted_packed_fn, treedef, spec): the group's result tree rides
    ONE [Q, total] f64 device array — one transfer for all lanes — with
    the [Q] validity mask zeroing shed lanes' rows (jnp.where, never
    multiply: -inf × 0 is NaN)."""
    q = len(batches)
    _shared_slots, stacked_slots = group_slot_split(batches)
    fn = (group_mesh_fn(batches, k, mesh, exact) if mesh is not None
          else group_fn(batches, k, exact))
    ex_shared, ex_stacked, ex_scalars, ex_nd = _group_example_structs(
        batches, stacked_slots)
    shaped = jax.eval_shape(fn, ex_shared, ex_stacked, ex_scalars, ex_nd)
    treedef = jax.tree_util.tree_structure(shaped)
    spec = [(leaf.shape, leaf.dtype)
            for leaf in jax.tree_util.tree_leaves(shaped)]

    def packed(shared, stacked, scalars_b, num_docs, valid):
        out = fn(shared, stacked, scalars_b, num_docs)
        flat = [leaf.reshape(q, -1).astype(jnp.float64)
                for leaf in jax.tree_util.tree_leaves(out)]
        packed_rows = jnp.concatenate(flat, axis=1) if flat \
            else jnp.zeros((q, 0))
        return jnp.where(valid[:, None], packed_rows, 0.0)

    return jax.jit(packed), treedef, spec


def dispatch_query_group(batches: list, request: SearchRequest,
                         mesh: Optional[Mesh] = None, valid=None,
                         exact: bool = False):
    """Async half of a stacked query-group dispatch: N shape-compatible
    queries (uniform template signature, same split set) enqueue as ONE
    program. `valid` masks lanes shed after group formation; `None` means
    all live. Returns the dispatched tuple for `readback_query_group`."""
    from ..common.deadline import check_cancelled
    check_cancelled("query-group dispatch")
    b0 = batches[0]
    q = len(batches)
    sig0 = b0.template.signature(min(
        request.start_offset + request.max_hits, b0.num_docs_padded))
    for b in batches[1:]:
        if b.split_ids != b0.split_ids:
            raise ValueError("query group spans different split sets")
    mesh = _usable_mesh(b0, mesh)
    k = min(request.start_offset + request.max_hits, b0.num_docs_padded)
    for b in batches[1:]:
        if b.template.signature(k) != sig0:
            raise ValueError(
                "query group is not shape-compatible (template signatures "
                "differ) — group by LoweredPlan.structure_digest upstream")
    if valid is None:
        valid = [True] * q
    shared_slots, stacked_slots = group_slot_split(batches)
    stacked_arrays, scalars_b = _stack_group_operands(batches,
                                                      stacked_slots)
    live = sum(1 for v in valid if v)
    from ..observability.metrics import (
        QBATCH_GROUPS_TOTAL, QBATCH_MASKED_RIDERS_TOTAL,
        QBATCH_QUERIES_PER_DISPATCH, QBATCH_SHARED_BYTES_AVOIDED_TOTAL,
    )
    if live > 1:
        QBATCH_GROUPS_TOTAL.inc()
    QBATCH_QUERIES_PER_DISPATCH.observe(live)
    if q - live:
        QBATCH_MASKED_RIDERS_TOTAL.inc(q - live)
    if live > 1 and shared_slots:
        QBATCH_SHARED_BYTES_AVOIDED_TOTAL.inc(
            sum(b0.arrays[s].nbytes for s in shared_slots) * (live - 1))
    # staging: shared slots ride lane 0's staged batch inputs (and thus
    # the mesh-resident column stack when one is active); stacked slots
    # and scalars are per-group uploads
    if mesh is not None:
        arrays_sh, _scalars_sh, nd_sh = batch_shardings(b0, mesh)
        from jax.sharding import NamedSharding
        split_ax, _doc_ax = _mesh_axes(mesh)
        shared_dev = tuple(jax.device_put(b0.arrays[s], arrays_sh[s])
                           for s in shared_slots)
        stacked_sh = NamedSharding(mesh, P(None, split_ax))
        stacked_dev = tuple(jax.device_put(a, stacked_sh)
                            for a in stacked_arrays)
        scalars_dev = tuple(jax.device_put(s, stacked_sh)
                            for s in scalars_b)
        nd_dev = jax.device_put(b0.num_docs, nd_sh)
    else:
        moved = jax.device_put(
            [b0.arrays[s] for s in shared_slots] + stacked_arrays
            + scalars_b + [b0.num_docs])
        shared_dev = tuple(moved[: len(shared_slots)])
        stacked_dev = tuple(
            moved[len(shared_slots): len(shared_slots) + len(stacked_arrays)])
        scalars_dev = tuple(moved[len(shared_slots) + len(stacked_arrays):-1])
        nd_dev = moved[-1]
    valid_dev = jax.device_put(np.asarray(valid, dtype=bool))
    # mirror: group_cache_key (qwir closure certificate lockstep)
    key = (sig0, q, b0.n_splits, b0.num_docs_padded, stacked_slots, mesh,
           exact)
    cached = _GROUP_JIT_CACHE.get(key)
    if flight.recording():
        flight.emit("compile.hit" if cached is not None else "compile.miss",
                    attrs={"path": "query_group"})
        flight.emit("dispatch.launch",
                    attrs={"path": "query_group", "lanes": q, "live": live,
                           "mesh": mesh.size if mesh is not None else 0})
    profile = current_profile()
    if profile is not None:
        profile.add("compile_cache_hits" if cached is not None
                    else "compile_cache_misses")
    ctx = profile.phase(PHASE_EXECUTE if cached is not None
                        else PHASE_COMPILE, stage="dispatch_query_group") \
        if profile is not None else None
    try:
        if ctx is not None:
            ctx.__enter__()
        if cached is None:
            cached = _group_executor(batches, k, mesh, exact)
            _GROUP_JIT_CACHE[key] = cached
        ex, treedef, spec = cached
        out, guard = _enqueue_batch(
            lambda a, s, n: ex(shared_dev, stacked_dev, s, n, valid_dev),
            None, scalars_dev, nd_dev, mesh)
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)
    try:
        if mesh is not None:
            from ..observability.metrics import (
                MESH_DEVICES, MESH_DISPATCHES_TOTAL,
                MESH_THRESHOLD_EXCHANGE_ROUNDS_TOTAL,
            )
            MESH_DISPATCHES_TOTAL.inc()
            MESH_DEVICES.set(mesh.size)
            if k > 0:
                # one pmax round still carries ALL Q lanes' thresholds
                MESH_THRESHOLD_EXCHANGE_ROUNDS_TOTAL.inc()
            if flight.recording():
                flight.emit("mesh.collective",
                            attrs={"devices": mesh.size,
                                   "path": "query_group",
                                   "threshold_exchange": int(k > 0)})
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()
    except BaseException:
        _finish_mesh_dispatch(guard, out)
        raise
    return out, treedef, spec, (list(batches), request, mesh, k,
                                list(valid)), guard


def readback_query_group(dispatched) -> list:
    """Blocking half: ONE [Q, total] transfer, per-lane unpack + the same
    merged-hit decode the single-query readback uses. Masked lanes return
    None. A lane whose guided-top-k certificate reads unsafe re-runs as a
    solo exact batch (per lane — an unsafe lane must not tax its
    groupmates with a stacked re-dispatch)."""
    out, treedef, spec, (batches, request, mesh, k, valid), guard = \
        dispatched
    from ..common.deadline import check_cancelled
    t0 = _clock_monotonic() if flight.recording() else 0.0
    try:
        check_cancelled("query-group readback")
        profile = current_profile()
        if profile is None:
            packed = jax.device_get(out)
        else:
            with profile.phase(PHASE_EXECUTE, stage="readback"):
                packed = jax.device_get(out)
    except BaseException:
        _finish_mesh_dispatch(guard, out)
        raise
    _finish_mesh_dispatch(guard)
    if flight.recording():
        flight.emit("dispatch.readback", attrs={
            "path": "query_group",
            "dur_ms": round((_clock_monotonic() - t0) * 1000.0, 3)})
    results: list = []
    for lane, batch in enumerate(batches):
        if not valid[lane]:
            results.append(None)
            continue
        row = packed[lane]
        leaves, offset = [], 0
        for shape, dtype in spec:
            lane_shape = shape[1:]
            size = int(np.prod(lane_shape)) if lane_shape else 1
            leaves.append(row[offset: offset + size]
                          .astype(dtype).reshape(lane_shape))
            offset += size
        top_vals, top_vals2, split_idx, doc_ids, scores, total, safe, \
            merged_aggs = jax.tree_util.tree_unflatten(treedef, leaves)
        if float(safe) < 1.0:
            executor_mod._note_guided_fallback()
            results.append(execute_batch(batch, request, mesh, exact=True))
            continue
        results.append(_decode_merged(
            batch, k, top_vals, top_vals2, split_idx, doc_ids, scores,
            int(total), merged_aggs))
    return results


def execute_query_group(batches: list, request: SearchRequest,
                        mesh: Optional[Mesh] = None,
                        valid=None) -> list:
    """Run N shape-compatible queries over one split set as ONE (optionally
    mesh-collective) dispatch; returns one LeafSearchResponse per lane
    (None for lanes masked by `valid`)."""
    return readback_query_group(
        dispatch_query_group(batches, request, mesh, valid=valid))
