from .fanout import SplitBatch, build_batch, execute_batch, make_mesh

__all__ = ["SplitBatch", "build_batch", "execute_batch", "make_mesh"]
