"""File-backed metastore: per-index JSON state on object storage.

Role of the reference's `FileBackedMetastore`
(`quickwit-metastore/src/metastore/file_backed/mod.rs:154`): each index's
full state (metadata, sources, splits, checkpoints, delete tasks) serializes
to one JSON object at `{index_id}/metastore.json`; writes go through an
in-process per-index lock and land with a version counter for
lost-update detection; an `indexes.json` manifest lists live indexes
(reference `manifest.rs`).

Suited to a single metastore node per cluster (like the reference's
file-backed mode); the write-proxying via the control plane keeps other
nodes' views coherent (`control_plane_metastore.rs`).
"""

from __future__ import annotations

import json
import threading
from typing import Iterable, Optional

from ..common.clock import monotonic, wall_time
from ..models.index_metadata import IndexMetadata, SourceConfig
from ..models.split_metadata import Split, SplitMetadata, SplitState
from ..storage.base import Storage, StorageError
from .base import ListSplitsQuery, Metastore, MetastoreError
from .checkpoint import CheckpointDelta, IncompatibleCheckpointDelta, SourceCheckpoint

MANIFEST_PATH = "indexes.json"
TEMPLATES_PATH = "templates.json"


def _state_path(index_id: str) -> str:
    return f"{index_id}/metastore.json"


class _IndexState:
    """In-memory image of one index's metastore file."""

    def __init__(self, metadata: IndexMetadata):
        self.loaded_at = monotonic()
        self.metadata = metadata
        self.splits: dict[str, Split] = {}
        self.checkpoints: dict[str, SourceCheckpoint] = {}
        # source_id -> shard_id -> {"leader": node, "follower": node|None}
        self.shard_chains: dict[str, dict[str, dict]] = {}
        self.delete_tasks: list[dict] = []
        self.last_delete_opstamp = 0
        self.version = 0
        self.discarded = False

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "metadata": self.metadata.to_dict(),
            "splits": [s.to_dict() for s in self.splits.values()],
            "checkpoints": {sid: cp.to_dict() for sid, cp in self.checkpoints.items()},
            "shard_chains": self.shard_chains,
            "delete_tasks": self.delete_tasks,
            "last_delete_opstamp": self.last_delete_opstamp,
        }

    @staticmethod
    def from_dict(d: dict) -> "_IndexState":
        state = _IndexState(IndexMetadata.from_dict(d["metadata"]))
        state.version = d.get("version", 0)
        for split_dict in d.get("splits", []):
            split = Split.from_dict(split_dict)
            state.splits[split.metadata.split_id] = split
        state.checkpoints = {
            sid: SourceCheckpoint.from_dict(cp)
            for sid, cp in d.get("checkpoints", {}).items()
        }
        state.shard_chains = d.get("shard_chains", {})
        state.delete_tasks = d.get("delete_tasks", [])
        state.last_delete_opstamp = d.get("last_delete_opstamp", 0)
        return state


class FileBackedMetastore(Metastore):
    def __init__(self, storage: Storage, polling_interval_secs: Optional[float] = 30.0):
        """`polling_interval_secs`: cached per-index state older than this is
        re-read from storage before serving reads, so other nodes' writes
        become visible (the reference's file-backed polling). Writes always
        persist immediately, so a reload never loses local mutations; like
        the reference, concurrent WRITERS on one index are not supported
        (single metastore-writer deployment)."""
        self.storage = storage
        # qwlint: disable-next-line=QW008 - metastore leaf lock; pure dict/file
        # ops inside its critical sections
        self._lock = threading.RLock()
        self._states: dict[str, _IndexState] = {}  # index_id -> state
        self._manifest: Optional[dict[str, str]] = None  # index_id -> index_uid
        self._manifest_loaded_at = 0.0
        self.polling_interval_secs = polling_interval_secs

    def refresh(self) -> None:
        """Invalidate the polling cache: the next read of the manifest or
        any index state re-fetches from storage, making other nodes'
        committed writes visible NOW (the GC orphan scan depends on this
        to never treat a just-staged split as an orphan). The cache is
        DROPPED, not just aged, so the contract also holds with
        polling_interval_secs=None (whose freshness check would otherwise
        serve any cached state forever)."""
        with self._lock:
            self._manifest = None
            self._manifest_loaded_at = 0.0
            self._states.clear()

    # --- manifest ----------------------------------------------------------
    def _load_manifest(self) -> dict[str, str]:
        stale = (self._manifest is not None
                 and self.polling_interval_secs is not None
                 and monotonic() - self._manifest_loaded_at
                 > self.polling_interval_secs)
        if self._manifest is None or stale:
            try:
                self._manifest = json.loads(self.storage.get_all(MANIFEST_PATH))
            except StorageError:
                if self._manifest is None:
                    self._manifest = {}
            self._manifest_loaded_at = monotonic()
        return self._manifest

    def _save_manifest(self) -> None:
        self.storage.put(MANIFEST_PATH,
                         json.dumps(self._manifest, indent=1).encode())

    # --- state io ----------------------------------------------------------
    def _load_state(self, index_id: str) -> _IndexState:
        state = self._states.get(index_id)
        fresh = (state is not None and not state.discarded
                 and (self.polling_interval_secs is None
                      or monotonic() - state.loaded_at
                      < self.polling_interval_secs))
        if fresh:
            return state
        try:
            raw = self.storage.get_all(_state_path(index_id))
        except StorageError:
            if state is not None and not state.discarded:
                # Distinguish "another node deleted the index" from a
                # transient storage blip: a fresh manifest read that no
                # longer lists the index means deleted — drop the cache.
                try:
                    manifest = json.loads(self.storage.get_all(MANIFEST_PATH))
                except StorageError:
                    return state  # storage blip: keep serving the cache
                self._manifest = manifest
                self._manifest_loaded_at = monotonic()
                if index_id in manifest:
                    return state  # index exists, state read blipped
                self._states.pop(index_id, None)
            raise MetastoreError(f"index {index_id!r} not found", kind="not_found")
        state = _IndexState.from_dict(json.loads(raw))
        self._states[index_id] = state
        return state

    def _save_state(self, state: _IndexState) -> None:
        # Optimistic lost-update detection (reference keeps a version in the
        # per-index file for the same purpose): if the stored version moved
        # past the one we loaded, or the stored file belongs to a different
        # incarnation (deleted + recreated under the same id), another
        # writer raced us — fail the write instead of silently overwriting
        # their splits/checkpoints. Not a true CAS (storage has no
        # conditional put) but catches the common race; background writers
        # are additionally partitioned per index by rendezvous ownership
        # (serve/node.py). Skipped in explicit single-writer mode
        # (polling_interval_secs=None) to keep mutations one storage op.
        if self.polling_interval_secs is not None:
            index_id = state.metadata.index_id
            try:
                stored = json.loads(self.storage.get_all(_state_path(index_id)))
                stored_version = stored.get("version", 0)
                stored_uid = stored.get("metadata", {}).get("index_uid")
            except StorageError:
                stored_version, stored_uid = 0, None  # first write
            conflict = (stored_version > state.version
                        or (stored_uid is not None
                            and stored_uid != state.metadata.index_uid))
            if conflict:
                self._states.pop(index_id, None)  # force reload
                raise MetastoreError(
                    f"concurrent modification of index {index_id!r} detected "
                    f"(stored version {stored_version}, uid {stored_uid!r} vs "
                    f"loaded {state.version}, {state.metadata.index_uid!r}); "
                    f"retry", kind="failed_precondition")
        state.loaded_at = monotonic()  # our write IS the latest state
        state.version += 1
        self.storage.put(_state_path(state.metadata.index_id),
                         json.dumps(state.to_dict()).encode())

    def _state_by_uid(self, index_uid: str) -> _IndexState:
        index_id = index_uid.split(":", 1)[0]
        state = self._load_state(index_id)
        if state.metadata.index_uid != index_uid:
            raise MetastoreError(
                f"index uid mismatch: {index_uid!r} (current incarnation: "
                f"{state.metadata.index_uid!r})", kind="not_found")
        return state

    # --- index templates ---------------------------------------------------
    # (reference: quickwit-config/src/index_template/mod.rs — templates match
    # index-id patterns and seed auto-created indexes)
    def _load_templates(self) -> list[dict]:
        try:
            return json.loads(self.storage.get_all(TEMPLATES_PATH))
        except StorageError:
            return []

    def create_index_template(self, template: dict) -> None:
        self.validate_template(template)
        with self._lock:
            templates = [t for t in self._load_templates()
                         if t["template_id"] != template["template_id"]]
            templates.append(template)
            self.storage.put(TEMPLATES_PATH, json.dumps(templates).encode())

    def list_index_templates(self) -> list[dict]:
        with self._lock:
            return self._load_templates()

    def delete_index_template(self, template_id: str) -> None:
        with self._lock:
            templates = self._load_templates()
            kept = [t for t in templates if t["template_id"] != template_id]
            if len(kept) == len(templates):
                raise MetastoreError(f"template {template_id!r} not found",
                                     kind="not_found")
            self.storage.put(TEMPLATES_PATH, json.dumps(kept).encode())


    # --- index lifecycle ---------------------------------------------------
    def create_index(self, index_metadata: IndexMetadata) -> None:
        with self._lock:
            manifest = self._load_manifest()
            index_id = index_metadata.index_id
            if index_id in manifest:
                raise MetastoreError(f"index {index_id!r} already exists",
                                     kind="already_exists")
            state = _IndexState(index_metadata)
            for source_id in index_metadata.sources:
                state.checkpoints[source_id] = SourceCheckpoint()
            self._states[index_id] = state
            self._save_state(state)
            manifest[index_id] = index_metadata.index_uid
            self._save_manifest()

    def delete_index(self, index_uid: str) -> None:
        with self._lock:
            state = self._state_by_uid(index_uid)
            index_id = state.metadata.index_id
            manifest = self._load_manifest()
            manifest.pop(index_id, None)
            self._save_manifest()
            state.discarded = True
            self._states.pop(index_id, None)
            try:
                self.storage.delete(_state_path(index_id))
            except StorageError:
                pass

    def index_metadata(self, index_id: str) -> IndexMetadata:
        with self._lock:
            return self._load_state(index_id).metadata

    def index_metadata_by_uid(self, index_uid: str) -> IndexMetadata:
        with self._lock:
            return self._state_by_uid(index_uid).metadata

    def list_indexes(self) -> list[IndexMetadata]:
        with self._lock:
            manifest = self._load_manifest()
            out = []
            for index_id in sorted(manifest):
                try:
                    out.append(self._load_state(index_id).metadata)
                except MetastoreError:
                    continue
            return out

    # --- sources -----------------------------------------------------------
    def add_source(self, index_uid: str, source: SourceConfig) -> None:
        with self._lock:
            state = self._state_by_uid(index_uid)
            if source.source_id in state.metadata.sources:
                raise MetastoreError(f"source {source.source_id!r} already exists",
                                     kind="already_exists")
            state.metadata.sources[source.source_id] = source
            state.checkpoints.setdefault(source.source_id, SourceCheckpoint())
            self._save_state(state)

    def delete_source(self, index_uid: str, source_id: str) -> None:
        with self._lock:
            state = self._state_by_uid(index_uid)
            if state.metadata.sources.pop(source_id, None) is None:
                raise MetastoreError(f"source {source_id!r} not found", kind="not_found")
            state.checkpoints.pop(source_id, None)
            self._save_state(state)

    def update_retention_policy(self, index_uid: str, retention) -> None:
        with self._lock:
            state = self._state_by_uid(index_uid)
            state.metadata.index_config.retention = retention
            self._save_state(state)

    def update_index_config(self, index_uid: str, index_config) -> None:
        with self._lock:
            state = self._state_by_uid(index_uid)
            state.metadata.index_config = index_config
            self._save_state(state)

    def toggle_source(self, index_uid: str, source_id: str, enable: bool) -> None:
        with self._lock:
            state = self._state_by_uid(index_uid)
            source = state.metadata.sources.get(source_id)
            if source is None:
                raise MetastoreError(f"source {source_id!r} not found", kind="not_found")
            source.enabled = enable
            self._save_state(state)

    def reset_source_checkpoint(self, index_uid: str, source_id: str) -> None:
        with self._lock:
            state = self._state_by_uid(index_uid)
            if source_id not in state.metadata.sources:
                raise MetastoreError(f"source {source_id!r} not found",
                                     kind="not_found")
            state.checkpoints[source_id] = SourceCheckpoint()
            self._save_state(state)

    def source_checkpoint(self, index_uid: str, source_id: str) -> SourceCheckpoint:
        with self._lock:
            state = self._state_by_uid(index_uid)
            return SourceCheckpoint.from_dict(
                state.checkpoints.get(source_id, SourceCheckpoint()).to_dict())

    # --- replication chain registry ------------------------------------------
    def record_shard_chain(self, index_uid: str, source_id: str,
                           shard_id: str, leader: str,
                           follower: Optional[str]) -> None:
        # Chain changes are rare (follower re-pick, promotion) but must win
        # against a concurrently-drained checkpoint CAS: retry once through
        # a cache drop, like a node's next poll tick would.
        record = {"leader": leader, "follower": follower}
        for attempt in (0, 1):
            with self._lock:
                try:
                    state = self._state_by_uid(index_uid)
                    state.shard_chains.setdefault(source_id, {})[shard_id] = \
                        dict(record)
                    self._save_state(state)
                    return
                except MetastoreError as exc:
                    if attempt or exc.kind != "failed_precondition":
                        raise
                    self.refresh()

    def shard_chain(self, index_uid: str, source_id: str,
                    shard_id: str) -> Optional[dict]:
        with self._lock:
            state = self._state_by_uid(index_uid)
            record = state.shard_chains.get(source_id, {}).get(shard_id)
            return dict(record) if record is not None else None

    # --- splits --------------------------------------------------------------
    def stage_splits(self, index_uid: str, split_metadatas: list[SplitMetadata]) -> None:
        now = int(wall_time())
        with self._lock:
            state = self._state_by_uid(index_uid)
            for md in split_metadatas:
                existing = state.splits.get(md.split_id)
                if existing is not None and existing.state is not SplitState.STAGED:
                    raise MetastoreError(
                        f"split {md.split_id!r} exists in state {existing.state}",
                        kind="failed_precondition")
                state.splits[md.split_id] = Split(
                    metadata=md, state=SplitState.STAGED, update_timestamp=now)
            self._save_state(state)

    def publish_splits(
        self,
        index_uid: str,
        staged_split_ids: list[str],
        replaced_split_ids: Iterable[str] = (),
        source_id: Optional[str] = None,
        checkpoint_delta: Optional[CheckpointDelta] = None,
    ) -> None:
        now = int(wall_time())
        with self._lock:
            state = self._state_by_uid(index_uid)
            # validate everything before mutating anything (atomicity)
            for split_id in staged_split_ids:
                split = state.splits.get(split_id)
                if split is None:
                    raise MetastoreError(f"split {split_id!r} not found",
                                         kind="not_found")
                if split.state is not SplitState.STAGED:
                    raise MetastoreError(
                        f"split {split_id!r} is {split.state}, not staged",
                        kind="failed_precondition")
            replaced = list(replaced_split_ids)
            for split_id in replaced:
                split = state.splits.get(split_id)
                if split is None or split.state is not SplitState.PUBLISHED:
                    raise MetastoreError(
                        f"replaced split {split_id!r} is not published",
                        kind="failed_precondition")
            if checkpoint_delta is not None and not checkpoint_delta.is_empty:
                if source_id is None:
                    raise MetastoreError("checkpoint delta requires source_id")
                checkpoint = state.checkpoints.setdefault(source_id, SourceCheckpoint())
                try:
                    checkpoint.try_apply_delta(checkpoint_delta)
                except IncompatibleCheckpointDelta as exc:
                    raise MetastoreError(str(exc), kind="failed_precondition") from exc
            for split_id in staged_split_ids:
                split = state.splits[split_id]
                split.state = SplitState.PUBLISHED
                split.update_timestamp = now
                split.publish_timestamp = now
            for split_id in replaced:
                split = state.splits[split_id]
                split.state = SplitState.MARKED_FOR_DELETION
                split.update_timestamp = now
            self._save_state(state)

    def list_splits(self, query: ListSplitsQuery) -> list[Split]:
        with self._lock:
            if query.index_uids is not None:
                states = [self._state_by_uid(uid) for uid in query.index_uids]
            else:
                states = [self._load_state(i) for i in self._load_manifest()]
            out = []
            for state in states:
                out.extend(s for s in state.splits.values() if query.matches(s))
            return sorted(out, key=lambda s: s.metadata.split_id)

    def mark_splits_for_deletion(self, index_uid: str, split_ids: Iterable[str]) -> None:
        now = int(wall_time())
        with self._lock:
            state = self._state_by_uid(index_uid)
            for split_id in split_ids:
                split = state.splits.get(split_id)
                if split is None:
                    continue
                if split.state is not SplitState.MARKED_FOR_DELETION:
                    split.state = SplitState.MARKED_FOR_DELETION
                    split.update_timestamp = now
            self._save_state(state)

    def delete_splits(self, index_uid: str, split_ids: Iterable[str]) -> None:
        with self._lock:
            state = self._state_by_uid(index_uid)
            for split_id in split_ids:
                split = state.splits.get(split_id)
                if split is None:
                    continue
                if split.state is SplitState.PUBLISHED:
                    raise MetastoreError(
                        f"cannot delete published split {split_id!r}",
                        kind="failed_precondition")
                del state.splits[split_id]
            self._save_state(state)

    # --- delete tasks --------------------------------------------------------
    def create_delete_task(self, index_uid: str, query_ast_json: dict) -> int:
        with self._lock:
            state = self._state_by_uid(index_uid)
            state.last_delete_opstamp += 1
            opstamp = state.last_delete_opstamp
            state.delete_tasks.append({
                "opstamp": opstamp,
                "create_timestamp": int(wall_time()),
                "query_ast": query_ast_json,
            })
            self._save_state(state)
            return opstamp

    def list_delete_tasks(self, index_uid: str, opstamp_start: int = 0) -> list[dict]:
        with self._lock:
            state = self._state_by_uid(index_uid)
            return [t for t in state.delete_tasks if t["opstamp"] > opstamp_start]

    def last_delete_opstamp(self, index_uid: str) -> int:
        with self._lock:
            return self._state_by_uid(index_uid).last_delete_opstamp

    def update_splits_delete_opstamp(self, index_uid: str,
                                     split_ids: Iterable[str], opstamp: int) -> None:
        with self._lock:
            state = self._state_by_uid(index_uid)
            for split_id in split_ids:
                split = state.splits.get(split_id)
                if split is not None:
                    split.metadata.delete_opstamp = opstamp
            self._save_state(state)
