from .checkpoint import CheckpointDelta, IncompatibleCheckpointDelta, SourceCheckpoint
from .base import Metastore, MetastoreError, ListSplitsQuery
from .file_backed import FileBackedMetastore
from .sql import SqlMetastore

__all__ = [
    "Metastore", "MetastoreError", "ListSplitsQuery", "FileBackedMetastore",
    "SqlMetastore",
    "SourceCheckpoint", "CheckpointDelta", "IncompatibleCheckpointDelta",
]
