from .checkpoint import CheckpointDelta, IncompatibleCheckpointDelta, SourceCheckpoint
from .base import Metastore, MetastoreError, ListSplitsQuery
from .file_backed import FileBackedMetastore

__all__ = [
    "Metastore", "MetastoreError", "ListSplitsQuery", "FileBackedMetastore",
    "SourceCheckpoint", "CheckpointDelta", "IncompatibleCheckpointDelta",
]
