"""SQL metastore backend (sqlite3).

Role of the reference's `PostgresqlMetastore`
(`quickwit-metastore/src/metastore/postgres/metastore.rs:97`): the
second, transactional metastore implementation behind the same
`Metastore` interface — SQL transactions give the atomic
publish-splits/checkpoint cut-over instead of the file-backed
state-machine's compare-and-swap on an object-store file. This image
carries no Postgres server, so the stdlib `sqlite3` plays the SQL
engine; the schema and transaction layout translate to Postgres
directly (the reference's migrations create the same four tables:
indexes / splits / shards|checkpoints / delete_tasks).

Concurrency: one connection guarded by an RLock; every mutation is a
single `BEGIN IMMEDIATE` transaction so multi-process deployments
pointing at one database file serialize through sqlite's file locking,
and readers see only committed state (WAL mode).
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from typing import Iterable, Optional

from ..models.index_metadata import IndexMetadata, SourceConfig
from ..models.split_metadata import Split, SplitState
from .base import ListSplitsQuery, Metastore, MetastoreError
from .checkpoint import (CheckpointDelta, IncompatibleCheckpointDelta,
                         SourceCheckpoint)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS indexes (
    index_id  TEXT PRIMARY KEY,
    index_uid TEXT NOT NULL UNIQUE,
    metadata  TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS splits (
    index_uid TEXT NOT NULL,
    split_id  TEXT NOT NULL,
    state     TEXT NOT NULL,
    split     TEXT NOT NULL,
    PRIMARY KEY (index_uid, split_id)
);
CREATE INDEX IF NOT EXISTS splits_by_state ON splits (index_uid, state);
CREATE TABLE IF NOT EXISTS checkpoints (
    index_uid  TEXT NOT NULL,
    source_id  TEXT NOT NULL,
    checkpoint TEXT NOT NULL,
    PRIMARY KEY (index_uid, source_id)
);
CREATE TABLE IF NOT EXISTS shard_chains (
    index_uid TEXT NOT NULL,
    source_id TEXT NOT NULL,
    shard_id  TEXT NOT NULL,
    leader    TEXT NOT NULL,
    follower  TEXT,
    PRIMARY KEY (index_uid, source_id, shard_id)
);
CREATE TABLE IF NOT EXISTS delete_tasks (
    index_uid TEXT NOT NULL,
    opstamp   INTEGER NOT NULL,
    task      TEXT NOT NULL,
    PRIMARY KEY (index_uid, opstamp)
);
CREATE TABLE IF NOT EXISTS templates (
    template_id TEXT PRIMARY KEY,
    template    TEXT NOT NULL
);
"""


class SqlMetastore(Metastore):
    def __init__(self, db_path: str):
        if db_path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(db_path)),
                        exist_ok=True)
        # isolation_level=None: NO implicit transactions — every mutation
        # runs inside an explicit BEGIN IMMEDIATE (see _txn) so the
        # precondition SELECTs of publish_splits hold the write lock for
        # the whole check-then-act, across PROCESSES sharing the db file
        self._conn = sqlite3.connect(db_path, check_same_thread=False,
                                     isolation_level=None)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA busy_timeout=10000")
        self._conn.executescript(_SCHEMA)
        # qwlint: disable-next-line=QW008 - metastore leaf lock; pure dict/file
        # ops inside its critical sections
        self._lock = threading.RLock()

    # --- helpers ------------------------------------------------------
    def _tx(self):
        return self._lock

    class _Txn:
        def __init__(self, conn):
            self._conn = conn

        def __enter__(self):
            try:
                self._conn.execute("BEGIN IMMEDIATE")
            except sqlite3.OperationalError as exc:
                raise MetastoreError(f"metastore busy: {exc}",
                                     kind="unavailable") from exc
            return self._conn

        def __exit__(self, exc_type, exc, tb):
            if exc_type is None:
                self._conn.execute("COMMIT")
            else:
                self._conn.execute("ROLLBACK")
            return False

    def _txn(self):
        return SqlMetastore._Txn(self._conn)

    def _index_row_by_uid(self, index_uid: str) -> IndexMetadata:
        index_id = index_uid.split(":", 1)[0]
        row = self._conn.execute(
            "SELECT index_uid, metadata FROM indexes WHERE index_id = ?",
            (index_id,)).fetchone()
        if row is None:
            raise MetastoreError(f"index {index_id!r} not found",
                                 kind="not_found")
        if row[0] != index_uid:
            raise MetastoreError(
                f"index uid mismatch: {index_uid!r} (current incarnation: "
                f"{row[0]!r})", kind="not_found")
        return IndexMetadata.from_dict(json.loads(row[1]))

    def _save_metadata(self, metadata: IndexMetadata) -> None:
        self._conn.execute(
            "UPDATE indexes SET metadata = ? WHERE index_uid = ?",
            (json.dumps(metadata.to_dict()), metadata.index_uid))

    # --- index lifecycle ----------------------------------------------
    def create_index(self, index_metadata: IndexMetadata) -> None:
        with self._tx():
            try:
                with self._txn():
                    self._conn.execute(
                        "INSERT INTO indexes (index_id, index_uid, metadata)"
                        " VALUES (?, ?, ?)",
                        (index_metadata.index_id, index_metadata.index_uid,
                         json.dumps(index_metadata.to_dict())))
                    for source_id in index_metadata.sources:
                        self._conn.execute(
                            "INSERT OR IGNORE INTO checkpoints VALUES "
                            "(?, ?, ?)",
                            (index_metadata.index_uid, source_id,
                             json.dumps(SourceCheckpoint().to_dict())))
            except sqlite3.IntegrityError:
                raise MetastoreError(
                    f"index {index_metadata.index_id!r} already exists",
                    kind="already_exists")

    def delete_index(self, index_uid: str) -> None:
        with self._tx(), self._txn():
            # the existence/incarnation check runs INSIDE the transaction:
            # BEGIN IMMEDIATE holds the write lock across the whole
            # check-then-act even between processes
            self._index_row_by_uid(index_uid)
            for table in ("splits", "checkpoints", "shard_chains",
                          "delete_tasks"):
                self._conn.execute(
                    f"DELETE FROM {table} WHERE index_uid = ?",  # noqa: S608
                    (index_uid,))
            self._conn.execute(
                "DELETE FROM indexes WHERE index_uid = ?", (index_uid,))

    def index_metadata(self, index_id: str) -> IndexMetadata:
        with self._tx():
            row = self._conn.execute(
                "SELECT metadata FROM indexes WHERE index_id = ?",
                (index_id,)).fetchone()
            if row is None:
                raise MetastoreError(f"index {index_id!r} not found",
                                     kind="not_found")
            return IndexMetadata.from_dict(json.loads(row[0]))

    def index_metadata_by_uid(self, index_uid: str) -> IndexMetadata:
        with self._tx():
            return self._index_row_by_uid(index_uid)

    def list_indexes(self) -> list[IndexMetadata]:
        with self._tx():
            rows = self._conn.execute(
                "SELECT metadata FROM indexes ORDER BY index_id").fetchall()
            return [IndexMetadata.from_dict(json.loads(r[0])) for r in rows]

    # --- sources ------------------------------------------------------
    def add_source(self, index_uid: str, source: SourceConfig) -> None:
        with self._tx(), self._txn():
            metadata = self._index_row_by_uid(index_uid)
            if source.source_id in metadata.sources:
                raise MetastoreError(
                    f"source {source.source_id!r} already exists",
                    kind="already_exists")
            metadata.sources[source.source_id] = source
            self._save_metadata(metadata)
            self._conn.execute(
                "INSERT OR IGNORE INTO checkpoints VALUES (?, ?, ?)",
                (index_uid, source.source_id,
                 json.dumps(SourceCheckpoint().to_dict())))

    def delete_source(self, index_uid: str, source_id: str) -> None:
        with self._tx(), self._txn():
            metadata = self._index_row_by_uid(index_uid)
            if metadata.sources.pop(source_id, None) is None:
                raise MetastoreError(f"source {source_id!r} not found",
                                     kind="not_found")
            self._save_metadata(metadata)
            self._conn.execute(
                "DELETE FROM checkpoints WHERE index_uid = ? AND "
                "source_id = ?", (index_uid, source_id))

    def update_retention_policy(self, index_uid: str, retention) -> None:
        with self._tx(), self._txn():
            metadata = self._index_row_by_uid(index_uid)
            metadata.index_config.retention = retention
            self._save_metadata(metadata)

    def update_index_config(self, index_uid: str, index_config) -> None:
        with self._tx(), self._txn():
            metadata = self._index_row_by_uid(index_uid)
            metadata.index_config = index_config
            self._save_metadata(metadata)

    def toggle_source(self, index_uid: str, source_id: str,
                      enable: bool) -> None:
        with self._tx(), self._txn():
            metadata = self._index_row_by_uid(index_uid)
            source = metadata.sources.get(source_id)
            if source is None:
                raise MetastoreError(f"source {source_id!r} not found",
                                     kind="not_found")
            source.enabled = enable
            self._save_metadata(metadata)

    def reset_source_checkpoint(self, index_uid: str, source_id: str) -> None:
        with self._tx(), self._txn():
            metadata = self._index_row_by_uid(index_uid)
            if source_id not in metadata.sources:
                raise MetastoreError(f"source {source_id!r} not found",
                                     kind="not_found")
            self._conn.execute(
                "INSERT OR REPLACE INTO checkpoints VALUES (?, ?, ?)",
                (index_uid, source_id,
                 json.dumps(SourceCheckpoint().to_dict())))

    def source_checkpoint(self, index_uid: str,
                          source_id: str) -> SourceCheckpoint:
        with self._tx():
            self._index_row_by_uid(index_uid)
            row = self._conn.execute(
                "SELECT checkpoint FROM checkpoints WHERE index_uid = ? "
                "AND source_id = ?", (index_uid, source_id)).fetchone()
            if row is None:
                return SourceCheckpoint()
            return SourceCheckpoint.from_dict(json.loads(row[0]))

    # --- replication chain registry -----------------------------------
    def record_shard_chain(self, index_uid: str, source_id: str,
                           shard_id: str, leader: str,
                           follower: Optional[str]) -> None:
        with self._tx(), self._txn():
            self._index_row_by_uid(index_uid)
            self._conn.execute(
                "INSERT OR REPLACE INTO shard_chains VALUES (?, ?, ?, ?, ?)",
                (index_uid, source_id, shard_id, leader, follower))

    def shard_chain(self, index_uid: str, source_id: str,
                    shard_id: str) -> Optional[dict]:
        with self._tx():
            self._index_row_by_uid(index_uid)
            row = self._conn.execute(
                "SELECT leader, follower FROM shard_chains WHERE "
                "index_uid = ? AND source_id = ? AND shard_id = ?",
                (index_uid, source_id, shard_id)).fetchone()
            if row is None:
                return None
            return {"leader": row[0], "follower": row[1]}

    # --- splits -------------------------------------------------------
    def stage_splits(self, index_uid: str, split_metadatas) -> None:
        now = int(time.time())
        with self._tx(), self._txn():
            # the existence/incarnation check runs INSIDE the transaction:
            # BEGIN IMMEDIATE holds the write lock across the whole
            # check-then-act even between processes
            self._index_row_by_uid(index_uid)
            for md in split_metadatas:
                row = self._conn.execute(
                    "SELECT state FROM splits WHERE index_uid = ? AND "
                    "split_id = ?", (index_uid, md.split_id)).fetchone()
                if row is not None and row[0] != SplitState.STAGED.value:
                    raise MetastoreError(
                        f"split {md.split_id!r} exists in state {row[0]}",
                        kind="failed_precondition")
                split = Split(metadata=md, state=SplitState.STAGED,
                              update_timestamp=now)
                self._conn.execute(
                    "INSERT OR REPLACE INTO splits VALUES (?, ?, ?, ?)",
                    (index_uid, md.split_id, SplitState.STAGED.value,
                     json.dumps(split.to_dict())))

    def publish_splits(self, index_uid: str, staged_split_ids: list[str],
                       replaced_split_ids: Iterable[str] = (),
                       source_id: Optional[str] = None,
                       checkpoint_delta: Optional[CheckpointDelta] = None
                       ) -> None:
        now = int(time.time())
        with self._tx(), self._txn():
            # the existence/incarnation check runs INSIDE the transaction:
            # BEGIN IMMEDIATE holds the write lock across the whole
            # check-then-act even between processes
            self._index_row_by_uid(index_uid)
            # one transaction: all-or-nothing cut-over
            splits = {}
            for split_id in staged_split_ids:
                row = self._conn.execute(
                    "SELECT state, split FROM splits WHERE index_uid = ?"
                    " AND split_id = ?",
                    (index_uid, split_id)).fetchone()
                if row is None:
                    raise MetastoreError(
                        f"split {split_id!r} not found", kind="not_found")
                if row[0] != SplitState.STAGED.value:
                    raise MetastoreError(
                        f"split {split_id!r} is {row[0]}, not staged",
                        kind="failed_precondition")
                splits[split_id] = Split.from_dict(json.loads(row[1]))
            replaced = list(replaced_split_ids)
            for split_id in replaced:
                row = self._conn.execute(
                    "SELECT state, split FROM splits WHERE index_uid = ?"
                    " AND split_id = ?",
                    (index_uid, split_id)).fetchone()
                if row is None or row[0] != SplitState.PUBLISHED.value:
                    raise MetastoreError(
                        f"replaced split {split_id!r} is not published",
                        kind="failed_precondition")
                splits[split_id] = Split.from_dict(json.loads(row[1]))
            if checkpoint_delta is not None and not checkpoint_delta.is_empty:
                if source_id is None:
                    raise MetastoreError(
                        "checkpoint delta requires source_id")
                row = self._conn.execute(
                    "SELECT checkpoint FROM checkpoints WHERE "
                    "index_uid = ? AND source_id = ?",
                    (index_uid, source_id)).fetchone()
                checkpoint = (SourceCheckpoint.from_dict(
                    json.loads(row[0])) if row else SourceCheckpoint())
                try:
                    checkpoint.try_apply_delta(checkpoint_delta)
                except IncompatibleCheckpointDelta as exc:
                    raise MetastoreError(
                        str(exc), kind="failed_precondition") from exc
                self._conn.execute(
                    "INSERT OR REPLACE INTO checkpoints VALUES (?, ?, ?)",
                    (index_uid, source_id,
                     json.dumps(checkpoint.to_dict())))
            for split_id in staged_split_ids:
                split = splits[split_id]
                split.state = SplitState.PUBLISHED
                split.update_timestamp = now
                split.publish_timestamp = now
                self._conn.execute(
                    "UPDATE splits SET state = ?, split = ? WHERE "
                    "index_uid = ? AND split_id = ?",
                    (split.state.value, json.dumps(split.to_dict()),
                     index_uid, split_id))
            for split_id in replaced:
                split = splits[split_id]
                split.state = SplitState.MARKED_FOR_DELETION
                split.update_timestamp = now
                self._conn.execute(
                    "UPDATE splits SET state = ?, split = ? WHERE "
                    "index_uid = ? AND split_id = ?",
                    (split.state.value, json.dumps(split.to_dict()),
                     index_uid, split_id))

    def list_splits(self, query: ListSplitsQuery) -> list[Split]:
        with self._tx():
            if query.index_uids is not None:
                if not query.index_uids:
                    return []
                for uid in query.index_uids:
                    self._index_row_by_uid(uid)
                placeholders = ",".join("?" * len(query.index_uids))
                rows = self._conn.execute(
                    f"SELECT split FROM splits WHERE index_uid IN "  # noqa: S608
                    f"({placeholders})", tuple(query.index_uids)).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT split FROM splits").fetchall()
            splits = [Split.from_dict(json.loads(r[0])) for r in rows]
            return sorted((s for s in splits if query.matches(s)),
                          key=lambda s: s.metadata.split_id)

    def mark_splits_for_deletion(self, index_uid: str,
                                 split_ids: Iterable[str]) -> None:
        now = int(time.time())
        with self._tx(), self._txn():
            # the existence/incarnation check runs INSIDE the transaction:
            # BEGIN IMMEDIATE holds the write lock across the whole
            # check-then-act even between processes
            self._index_row_by_uid(index_uid)
            for split_id in split_ids:
                row = self._conn.execute(
                    "SELECT split FROM splits WHERE index_uid = ? AND "
                    "split_id = ?", (index_uid, split_id)).fetchone()
                if row is None:
                    continue
                split = Split.from_dict(json.loads(row[0]))
                if split.state is not SplitState.MARKED_FOR_DELETION:
                    split.state = SplitState.MARKED_FOR_DELETION
                    split.update_timestamp = now
                    self._conn.execute(
                        "UPDATE splits SET state = ?, split = ? WHERE "
                        "index_uid = ? AND split_id = ?",
                        (split.state.value, json.dumps(split.to_dict()),
                         index_uid, split_id))

    def delete_splits(self, index_uid: str,
                      split_ids: Iterable[str]) -> None:
        with self._tx(), self._txn():
            # the existence/incarnation check runs INSIDE the transaction:
            # BEGIN IMMEDIATE holds the write lock across the whole
            # check-then-act even between processes
            self._index_row_by_uid(index_uid)
            for split_id in split_ids:
                row = self._conn.execute(
                    "SELECT state FROM splits WHERE index_uid = ? AND "
                    "split_id = ?", (index_uid, split_id)).fetchone()
                if row is None:
                    continue
                if row[0] == SplitState.PUBLISHED.value:
                    raise MetastoreError(
                        f"cannot delete published split {split_id!r}",
                        kind="failed_precondition")
                self._conn.execute(
                    "DELETE FROM splits WHERE index_uid = ? AND "
                    "split_id = ?", (index_uid, split_id))

    # --- delete tasks -------------------------------------------------
    def create_delete_task(self, index_uid: str, query_ast_json: dict) -> int:
        with self._tx(), self._txn():
            # the existence/incarnation check runs INSIDE the transaction:
            # BEGIN IMMEDIATE holds the write lock across the whole
            # check-then-act even between processes
            self._index_row_by_uid(index_uid)
            row = self._conn.execute(
                "SELECT COALESCE(MAX(opstamp), 0) FROM delete_tasks "
                "WHERE index_uid = ?", (index_uid,)).fetchone()
            opstamp = int(row[0]) + 1
            task = {"opstamp": opstamp,
                    "create_timestamp": int(time.time()),
                    "query_ast": query_ast_json}
            self._conn.execute(
                "INSERT INTO delete_tasks VALUES (?, ?, ?)",
                (index_uid, opstamp, json.dumps(task)))
            return opstamp

    def list_delete_tasks(self, index_uid: str,
                          opstamp_start: int = 0) -> list[dict]:
        with self._tx():
            self._index_row_by_uid(index_uid)
            rows = self._conn.execute(
                "SELECT task FROM delete_tasks WHERE index_uid = ? AND "
                "opstamp > ? ORDER BY opstamp",
                (index_uid, opstamp_start)).fetchall()
            return [json.loads(r[0]) for r in rows]

    def last_delete_opstamp(self, index_uid: str) -> int:
        with self._tx():
            self._index_row_by_uid(index_uid)
            row = self._conn.execute(
                "SELECT COALESCE(MAX(opstamp), 0) FROM delete_tasks WHERE "
                "index_uid = ?", (index_uid,)).fetchone()
            return int(row[0])

    def update_splits_delete_opstamp(self, index_uid: str,
                                     split_ids: Iterable[str],
                                     opstamp: int) -> None:
        with self._tx(), self._txn():
            # the existence/incarnation check runs INSIDE the transaction:
            # BEGIN IMMEDIATE holds the write lock across the whole
            # check-then-act even between processes
            self._index_row_by_uid(index_uid)
            for split_id in split_ids:
                row = self._conn.execute(
                    "SELECT split FROM splits WHERE index_uid = ? AND "
                    "split_id = ?", (index_uid, split_id)).fetchone()
                if row is None:
                    continue
                split = Split.from_dict(json.loads(row[0]))
                split.metadata.delete_opstamp = opstamp
                self._conn.execute(
                    "UPDATE splits SET split = ? WHERE index_uid = ? "
                    "AND split_id = ?",
                    (json.dumps(split.to_dict()), index_uid, split_id))

    # --- index templates ----------------------------------------------
    def create_index_template(self, template: dict) -> None:
        self.validate_template(template)
        with self._tx(), self._txn():
            self._conn.execute(
                "INSERT OR REPLACE INTO templates VALUES (?, ?)",
                (template["template_id"], json.dumps(template)))

    def list_index_templates(self) -> list[dict]:
        with self._tx():
            rows = self._conn.execute(
                "SELECT template FROM templates ORDER BY template_id"
            ).fetchall()
            return [json.loads(r[0]) for r in rows]

    def delete_index_template(self, template_id: str) -> None:
        with self._tx(), self._txn():
            cursor = self._conn.execute(
                "DELETE FROM templates WHERE template_id = ?",
                (template_id,))
            if cursor.rowcount == 0:
                raise MetastoreError(f"template {template_id!r} not found",
                                     kind="not_found")

