"""Metastore service interface.

Role of the reference's `MetastoreService` gRPC API
(`quickwit-proto/protos/quickwit/metastore.proto:93-232`): index/source/split
metadata with the atomic publish protocol. Implementations:
`FileBackedMetastore` (object-storage JSON, reference
`file_backed/mod.rs:154`); a SQL backend is the reference's production
option and a future backend here.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

from ..models.index_metadata import IndexMetadata, SourceConfig
from ..models.split_metadata import Split, SplitMetadata, SplitState
from .checkpoint import CheckpointDelta


class MetastoreError(Exception):
    def __init__(self, message: str, kind: str = "internal"):
        super().__init__(message)
        self.kind = kind  # not_found | already_exists | failed_precondition | internal


@dataclass
class ListSplitsQuery:
    """Split-listing filter (reference: `ListSplitsQuery`); time-range and
    tag filters implement split pruning at plan time (`root.rs:1599`)."""
    index_uids: Optional[list[str]] = None
    states: Optional[list[SplitState]] = None
    time_range_start: Optional[int] = None   # micros, inclusive
    time_range_end: Optional[int] = None     # micros, exclusive
    required_tags: Optional[set[str]] = None
    mature_only: bool = False
    max_staleness_ts: Optional[int] = None

    def matches(self, split: Split) -> bool:
        if self.states is not None and split.state not in self.states:
            return False
        md = split.metadata
        if self.index_uids is not None and md.index_uid not in self.index_uids:
            return False
        end_incl = self.time_range_end - 1 if self.time_range_end is not None else None
        if not md.overlaps_time_range(self.time_range_start, end_incl):
            return False
        if not md.matches_tags(self.required_tags):
            return False
        if self.mature_only and not md.is_mature():
            return False
        return True


class Metastore:
    """Abstract metastore. All methods raise MetastoreError on failure."""

    # --- index lifecycle -------------------------------------------------
    def create_index(self, index_metadata: IndexMetadata) -> None:
        raise NotImplementedError

    def delete_index(self, index_uid: str) -> None:
        raise NotImplementedError

    def index_metadata(self, index_id: str) -> IndexMetadata:
        raise NotImplementedError

    def index_metadata_by_uid(self, index_uid: str) -> IndexMetadata:
        raise NotImplementedError

    def list_indexes(self) -> list[IndexMetadata]:
        raise NotImplementedError

    def refresh(self) -> None:
        """Drop any cached state so the next read reflects what other
        nodes have durably written. Backends with live reads (SQL) need
        nothing; the file-backed store invalidates its polling cache.
        Safety-critical readers (GC orphan scan) call this before acting
        on absence."""

    def update_retention_policy(self, index_uid: str, retention) -> None:
        """Persist a retention-policy change (reference `update_index`
        subset: retention only; other settings are immutable here)."""
        raise NotImplementedError

    def update_index_config(self, index_uid: str, index_config) -> None:
        """Persist a validated replacement IndexConfig (reference
        `update_index`, `metastore.proto` UpdateIndexRequest). The
        CALLER (IndexService.update_index) owns compatibility checks —
        append-only mapping changes, immutable index_id/uri."""
        raise NotImplementedError

    # --- sources -----------------------------------------------------------
    def add_source(self, index_uid: str, source: SourceConfig) -> None:
        raise NotImplementedError

    def delete_source(self, index_uid: str, source_id: str) -> None:
        raise NotImplementedError

    def toggle_source(self, index_uid: str, source_id: str, enable: bool) -> None:
        raise NotImplementedError

    def reset_source_checkpoint(self, index_uid: str, source_id: str) -> None:
        raise NotImplementedError

    # --- replication chain registry ----------------------------------------
    # Durable record of each shard's replication chain: which node leads the
    # shard and which node is the registered follower. The leader writes the
    # record BEFORE replicating the first batch to a new follower, and a
    # promotion rewrites it; failover may then promote ONLY the registered
    # follower — a replica copy that merely looks healthy (e.g. a crashed
    # follower that rejoined with a stale WAL) is not eligible. The qwmc
    # replication model (tools/qwmc/models.py) checks exhaustively that this
    # registry discipline is what makes promotion lose no acked record.
    def record_shard_chain(self, index_uid: str, source_id: str,
                           shard_id: str, leader: str,
                           follower: Optional[str]) -> None:
        raise NotImplementedError

    def shard_chain(self, index_uid: str, source_id: str,
                    shard_id: str) -> Optional[dict]:
        """Returns ``{"leader": node_id, "follower": node_id | None}`` or
        None when the shard never formed a replication chain."""
        raise NotImplementedError

    # --- splits ------------------------------------------------------------
    def stage_splits(self, index_uid: str, split_metadatas: list[SplitMetadata]) -> None:
        raise NotImplementedError

    def publish_splits(
        self,
        index_uid: str,
        staged_split_ids: list[str],
        replaced_split_ids: Iterable[str] = (),
        source_id: Optional[str] = None,
        checkpoint_delta: Optional[CheckpointDelta] = None,
    ) -> None:
        """Atomic cut-over: staged → published, replaced → marked-for-deletion,
        checkpoint advanced — all or nothing (reference `PublishSplits`)."""
        raise NotImplementedError

    def list_splits(self, query: ListSplitsQuery) -> list[Split]:
        raise NotImplementedError

    def mark_splits_for_deletion(self, index_uid: str, split_ids: Iterable[str]) -> None:
        raise NotImplementedError

    def delete_splits(self, index_uid: str, split_ids: Iterable[str]) -> None:
        """Only Staged or MarkedForDeletion splits may be deleted."""
        raise NotImplementedError

    # --- delete tasks (GDPR deletes, reference delete_task API) -----------
    def create_delete_task(self, index_uid: str, query_ast_json: dict) -> int:
        raise NotImplementedError

    def list_delete_tasks(self, index_uid: str, opstamp_start: int = 0) -> list[dict]:
        raise NotImplementedError

    def last_delete_opstamp(self, index_uid: str) -> int:
        raise NotImplementedError

    # --- index templates (shared logic; backends store/list/delete) -----
    @staticmethod
    def validate_template(template: dict) -> None:
        patterns = template.get("index_id_patterns")
        if (not isinstance(template.get("template_id"), str)
                or not isinstance(patterns, list) or not patterns
                or not all(isinstance(p, str) for p in patterns)):
            raise MetastoreError(
                "template requires a string template_id and a non-empty "
                "list of string index_id_patterns", kind="invalid_argument")

    def find_index_template(self, index_id: str):
        """Highest-priority template whose pattern matches (reference:
        index_template/mod.rs:35)."""
        import fnmatch
        candidates = [
            t for t in self.list_index_templates()
            if any(fnmatch.fnmatch(index_id, p)
                   for p in t["index_id_patterns"])
        ]
        if not candidates:
            return None
        return max(candidates, key=lambda t: t.get("priority", 0))

    def list_index_templates(self) -> list[dict]:
        raise NotImplementedError

    def update_splits_delete_opstamp(self, index_uid: str,
                                     split_ids: Iterable[str], opstamp: int) -> None:
        raise NotImplementedError
