"""Source checkpoints — the exactly-once boundary between queue and index.

Role of the reference's `quickwit-metastore/src/checkpoint.rs:30-120`:
a `SourceCheckpoint` maps partition ids to positions; every publish carries a
`CheckpointDelta` whose `from` positions must exactly equal the current
checkpoint, otherwise the publish is rejected — replays after a crash are
deduplicated by this check, which is what makes indexing exactly-once.

Positions are strings ordered by (length, lexicographic) so zero-padded
numeric offsets order correctly (the reference's `Position` encoding).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

BEGINNING = ""  # the position before any record


def position_gt(a: str, b: str) -> bool:
    """a > b under (length, lex) ordering; BEGINNING is smallest."""
    return (len(a), a) > (len(b), b)


def offset_position(offset: int) -> str:
    """Canonical position encoding for integer offsets (zero-padded,
    length-prefixed ordering-safe)."""
    return f"{offset:020d}"


class IncompatibleCheckpointDelta(ValueError):
    pass


@dataclass
class SourceCheckpoint:
    positions: dict[str, str] = field(default_factory=dict)

    def position_for(self, partition_id: str) -> str:
        return self.positions.get(partition_id, BEGINNING)

    def try_apply_delta(self, delta: "CheckpointDelta") -> None:
        """Validate-then-apply, atomically (validates all partitions first)."""
        for partition_id, (from_pos, to_pos) in delta.per_partition.items():
            current = self.position_for(partition_id)
            if from_pos != current:
                raise IncompatibleCheckpointDelta(
                    f"partition {partition_id!r}: delta starts at {from_pos!r} "
                    f"but checkpoint is at {current!r}")
            if position_gt(from_pos, to_pos):
                raise IncompatibleCheckpointDelta(
                    f"partition {partition_id!r}: delta goes backwards "
                    f"({from_pos!r} -> {to_pos!r})")
        for partition_id, (_, to_pos) in delta.per_partition.items():
            self.positions[partition_id] = to_pos

    def to_dict(self) -> dict[str, str]:
        return dict(self.positions)

    @staticmethod
    def from_dict(d: dict[str, str]) -> "SourceCheckpoint":
        return SourceCheckpoint(dict(d))


@dataclass
class CheckpointDelta:
    # partition_id -> (from_position_exclusive, to_position_inclusive)
    per_partition: dict[str, tuple[str, str]] = field(default_factory=dict)

    @staticmethod
    def from_range(partition_id: str, from_pos: str, to_pos: str) -> "CheckpointDelta":
        return CheckpointDelta({partition_id: (from_pos, to_pos)})

    def record(self, partition_id: str, from_pos: str, to_pos: str) -> None:
        if partition_id in self.per_partition:
            cur_from, cur_to = self.per_partition[partition_id]
            if cur_to != from_pos:
                raise IncompatibleCheckpointDelta(
                    f"partition {partition_id!r}: non-contiguous delta extension")
            self.per_partition[partition_id] = (cur_from, to_pos)
        else:
            self.per_partition[partition_id] = (from_pos, to_pos)

    def extend(self, other: "CheckpointDelta") -> None:
        for partition_id, (from_pos, to_pos) in other.per_partition.items():
            self.record(partition_id, from_pos, to_pos)

    @property
    def is_empty(self) -> bool:
        return not self.per_partition

    def to_dict(self) -> dict[str, list[str]]:
        return {p: [f, t] for p, (f, t) in self.per_partition.items()}

    @staticmethod
    def from_dict(d: dict[str, list[str]]) -> "CheckpointDelta":
        return CheckpointDelta({p: (v[0], v[1]) for p, v in d.items()})
