"""SQL analytics surface — the TPU-first analogue of the fork's
Parquet/DataFusion engine.

Role of the reference's `quickwit-datafusion` / `quickwit-df-core`
(`src/sources/metrics/table_provider.rs:1`, `service.rs:1`, mounted at
`quickwit-serve/src/datafusion_api/setup.rs:201`): a SQL aggregation
surface over the columnar data. The fork bolts a SECOND engine
(DataFusion over Parquet) beside tantivy; here the design is unified —
SQL **compiles onto the same device kernels** the search path runs
(QueryAst predicate → dense masks, GROUP BY → terms/date_histogram
bucket spaces, aggregates → the mergeable metric states), so analytics
inherits the whole distributed substrate: split pruning, fan-out, the
scatter-gather merge tree, caches, and admission. There is no second
storage format to compact and no second executor to schedule.

Dialect (vertical slice):

    SELECT <agg|col|DATE_TRUNC('unit', col)|<agg> OVER (...)> [AS a], ...
    FROM <index> [alias]
    [ [LEFT|INNER] JOIN <index> <alias> ON a.k = b.k [AND ...] ]*
    [WHERE <col op literal | col op (SELECT ...) |
            col [NOT] IN (list | SELECT ...)> [AND|OR ...] ]
    [GROUP BY <col | DATE_TRUNC('unit', col)> [, ...]]     -- any depth
    [HAVING <agg|alias> <op> <number> [AND ...]]
    [ORDER BY <alias|expr> [ASC|DESC]]
    [LIMIT n] [OFFSET n]

Aggregates: COUNT(*), COUNT(col), COUNT(DISTINCT col) /
APPROX_COUNT_DISTINCT (device HLL cardinality), SUM, AVG, MIN, MAX,
STDDEV, VARIANCE, APPROX_PERCENTILE(col, p) — the last rides the DDSketch percentile
kernels (the fork's sketch UDFs, `quickwit-datafusion/src/sources/
metrics/sketch_udf.rs`). GROUP BY chains compile onto the arbitrary-
depth nested bucket spaces, so N keys = one device pass.
Operators: = != <> < <= > >= ; string/number literals; AND/OR + parens.

Relational tail (the role of the fork's DataFusion operators the device
path has no analogue for):
- Subqueries in WHERE: scalar comparisons, [NOT] IN membership, and
  [NOT] EXISTS with a single equality correlation (decorrelated onto
  the IN machinery); resolved against live results first, so the OUTER
  query still compiles onto the device scan (membership becomes a
  term-set mask).
- Window functions: ROW_NUMBER / RANK / COUNT / SUM / AVG / MIN / MAX
  OVER (PARTITION BY ... [ORDER BY ...]); with ORDER BY the frame is the
  SQL default running frame (peers included).
- JOINs: equality INNER/LEFT joins between indexes. Single-table WHERE
  conjuncts push down through each side's device scan; the join itself
  and its grouped tail run host-side over the materialized sides.
JOIN sides and window inputs are capped at MATERIALIZE_CAP rows — the
host tail is for the (already reduced) relational step, not for full
scans; pure aggregation stays uncapped on the device path.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional

from ..query import ast as Q

_TRUNC_MICROS = {
    "second": 1_000_000, "minute": 60_000_000, "hour": 3_600_000_000,
    "day": 86_400_000_000, "week": 7 * 86_400_000_000,
}


class SqlError(ValueError):
    pass


# --------------------------------------------------------------------------
# lexer

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<number>-?\d+(?:\.\d+)?)
    | (?P<string>'(?:[^'\\]|\\.)*')
    | (?P<qident>"[^"]*")
    | (?P<op><=|>=|!=|<>|=|<|>|\(|\)|,|\*)
    | (?P<word>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""", re.VERBOSE)

_KEYWORDS = {"select", "from", "where", "group", "by", "order", "limit",
             "offset", "having", "and", "or", "as", "asc", "desc",
             "count", "sum", "avg", "min", "max", "stddev", "variance",
             "approx_percentile", "approx_count_distinct", "date_trunc",
             "distinct", "join", "left", "inner", "on", "over",
             "partition", "row_number", "rank", "in", "not", "exists"}

# Keywords new to the relational tail are CONTEXTUAL: where the grammar
# expects an identifier they still parse as column names, so existing
# indexes with fields named e.g. `rank` or `partition` keep working
# (`"quoted"` identifiers are the universal escape hatch).
_CONTEXTUAL = {"join", "left", "inner", "on", "over", "partition",
               "row_number", "rank", "in", "not", "exists"}

# Materialization cap for the host-side relational layer (JOIN sides and
# window-function inputs). Joins/windows run over rows fetched through
# the distributed search path; beyond this the query must be narrowed
# (the device agg path has no such cap — only the relational layer).
MATERIALIZE_CAP = 65536


def _tokenize(text: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise SqlError(f"cannot tokenize SQL at {text[pos:pos+20]!r}")
        pos = m.end()
        if m.group("number") is not None:
            out.append(("number", m.group("number")))
        elif m.group("string") is not None:
            out.append(("string",
                        m.group("string")[1:-1].replace("\\'", "'")))
        elif m.group("qident") is not None:
            out.append(("ident", m.group("qident")[1:-1]))
        elif m.group("op") is not None:
            out.append(("op", m.group("op")))
        else:
            word = m.group("word")
            kind = "kw" if word.lower() in _KEYWORDS else "ident"
            out.append((kind, word.lower() if kind == "kw" else word))
    return out


# --------------------------------------------------------------------------
# AST

@dataclass(frozen=True)
class SelectItem:
    kind: str          # "count_star" | "agg" | "col" | "trunc" | "window"
    func: Optional[str] = None
    column: Optional[str] = None
    unit: Optional[str] = None
    alias: Optional[str] = None
    percent: Optional[float] = None   # approx_percentile
    partition: tuple[str, ...] = ()               # window: PARTITION BY
    win_order: Optional[tuple[str, bool]] = None  # window: ORDER BY

    @property
    def name(self) -> str:
        if self.alias:
            return self.alias
        if self.kind == "count_star":
            return "count(*)"
        if self.kind == "agg":
            if self.func == "approx_percentile":
                return f"approx_percentile({self.column}, {self.percent:g})"
            return f"{self.func}({self.column})"
        if self.kind == "trunc":
            return f"date_trunc('{self.unit}', {self.column})"
        if self.kind == "window":
            base = (f"{self.func}({self.column})" if self.column
                    else f"{self.func}()")
            return f"{base} over"
        return self.column or ""


@dataclass(frozen=True)
class JoinClause:
    index: str
    alias: str
    on: tuple[tuple[str, str], ...]   # (left qualified, right qualified)
    left_outer: bool = False


@dataclass(frozen=True)
class SubqueryPred:
    """A WHERE leaf whose right-hand side is a subquery; resolved
    against live results (scalar comparison or IN/NOT IN membership,
    or [NOT] EXISTS decorrelation) before the predicate is compiled
    onto the device path. `column` is empty for EXISTS."""
    column: str
    op: str              # = != <> < <= > >= in not_in exists not_exists
    query: "SqlQuery"


@dataclass(frozen=True)
class ColumnEq:
    """`a.k = b.k` — a column-to-column equality leaf. Only meaningful
    as the correlation predicate inside an EXISTS subquery (the device
    scan has no cross-doc comparisons); anywhere else it resolves to a
    clear SqlError."""
    left: str
    right: str


@dataclass
class SqlQuery:
    index: str
    select: list[SelectItem]
    where: Optional[Q.QueryAst] = None
    group_by: list[SelectItem] = field(default_factory=list)
    order_by: Optional[tuple[str, bool]] = None  # (name, desc)
    having: list[tuple[str, str, float]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    alias: Optional[str] = None
    joins: list[JoinClause] = field(default_factory=list)


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.tokens = tokens
        self.pos = 0

    def peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self):
        token = self.peek()
        if token is None:
            raise SqlError("unexpected end of query")
        self.pos += 1
        return token

    def expect(self, kind: str, value: Optional[str] = None):
        token = self.next()
        if token[0] != kind or (value is not None and token[1] != value):
            raise SqlError(f"expected {value or kind}, got {token[1]!r}")
        return token

    def accept(self, kind: str, value: Optional[str] = None) -> bool:
        token = self.peek()
        if token and token[0] == kind and (value is None
                                           or token[1] == value):
            self.pos += 1
            return True
        return False

    def _ident(self) -> str:
        """An identifier; contextual keywords double as column names."""
        token = self.next()
        if token[0] == "ident" or (token[0] == "kw"
                                   and token[1] in _CONTEXTUAL):
            return token[1]
        raise SqlError(f"expected identifier, got {token[1]!r}")

    # --- grammar -------------------------------------------------------
    def parse(self) -> SqlQuery:
        q = self.parse_select()
        if self.peek() is not None:
            raise SqlError(f"unexpected trailing token {self.peek()[1]!r}")
        return q

    def _table_alias(self) -> Optional[str]:
        if self.accept("kw", "as"):
            return self.expect("ident")[1]
        token = self.peek()
        if token and token[0] == "ident":
            self.pos += 1
            return token[1]
        return None

    def parse_select(self) -> SqlQuery:
        self.expect("kw", "select")
        select = [self.select_item()]
        while self.accept("op", ","):
            select.append(self.select_item())
        self.expect("kw", "from")
        index = self.expect("ident")[1]
        alias = self._table_alias()
        joins: list[JoinClause] = []
        while True:
            left_outer = False
            if self.accept("kw", "left"):
                left_outer = True
                self.expect("kw", "join")
            elif self.accept("kw", "inner"):
                self.expect("kw", "join")
            elif not self.accept("kw", "join"):
                break
            j_index = self.expect("ident")[1]
            j_alias = self._table_alias()
            if alias is None or j_alias is None:
                raise SqlError("JOIN requires table aliases "
                               "(FROM a x JOIN b y ON x.k = y.k)")
            self.expect("kw", "on")
            on = [self._on_equality()]
            while self.accept("kw", "and"):
                on.append(self._on_equality())
            joins.append(JoinClause(j_index, j_alias, tuple(on),
                                    left_outer))
        where = None
        if self.accept("kw", "where"):
            where = self.predicate()
        group_by: list[SelectItem] = []
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_by.append(self.group_key())
            while self.accept("op", ","):
                group_by.append(self.group_key())
        having: list[tuple[str, str, float]] = []
        if self.accept("kw", "having"):
            having.append(self.having_clause())
            while self.accept("kw", "and"):
                having.append(self.having_clause())
        order_by = None
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            name = self.order_target()
            desc = False
            if self.accept("kw", "desc"):
                desc = True
            else:
                self.accept("kw", "asc")
            order_by = (name, desc)
        limit = None
        if self.accept("kw", "limit"):
            limit = int(self.expect("number")[1])
        offset = 0
        if self.accept("kw", "offset"):
            offset = int(self.expect("number")[1])
        return SqlQuery(index=index, select=select, where=where,
                        group_by=group_by, order_by=order_by,
                        having=having, limit=limit, offset=offset,
                        alias=alias, joins=joins)

    def _on_equality(self) -> tuple[str, str]:
        lhs = self._ident()
        self.expect("op", "=")
        rhs = self._ident()
        return (lhs, rhs)

    def having_clause(self) -> tuple[str, str, float]:
        item = self.select_item()
        if item.kind == "star":
            raise SqlError("HAVING takes an aggregate or alias")
        op = self.expect("op")[1]
        if op not in ("=", "!=", "<>", "<", "<=", ">", ">="):
            raise SqlError(f"unsupported HAVING operator {op!r}")
        value = float(self.expect("number")[1])
        return (item.name, op, value)

    def _maybe_over(self, item: SelectItem) -> SelectItem:
        """`<agg> OVER (PARTITION BY ... [ORDER BY ...])` turns an
        aggregate into a window item (computed host-side over
        materialized rows, cap `MATERIALIZE_CAP`)."""
        if not self.accept("kw", "over"):
            return item
        if item.kind not in ("count_star", "agg") and \
                item.func not in ("row_number", "rank"):
            raise SqlError("OVER applies to aggregate functions")
        if item.func in ("count_distinct", "approx_percentile",
                         "stddev", "variance"):
            raise SqlError(
                f"{item.func} is not supported as a window function")
        self.expect("op", "(")
        partition: list[str] = []
        if self.accept("kw", "partition"):
            self.expect("kw", "by")
            partition.append(self._ident())
            while self.accept("op", ","):
                partition.append(self._ident())
        win_order = None
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            col = self._ident()
            desc = False
            if self.accept("kw", "desc"):
                desc = True
            else:
                self.accept("kw", "asc")
            win_order = (col, desc)
        self.expect("op", ")")
        func = "count" if item.kind == "count_star" else item.func
        return SelectItem("window", func=func, column=item.column,
                          partition=tuple(partition), win_order=win_order,
                          alias=item.alias or self._alias())

    def select_item(self) -> SelectItem:
        token = self.next()
        if token == ("op", "*") or token[0] == "number":
            # `SELECT 1` / `SELECT *`: only meaningful inside EXISTS
            # subqueries (the row content is irrelevant there)
            return SelectItem("star", alias=self._alias())
        if token[0] == "kw" and token[1] in ("row_number", "rank") \
                and self.peek() == ("op", "("):
            self.next()  # (
            self.expect("op", ")")
            item = SelectItem("agg", func=token[1], alias=self._alias())
            if not (self.peek() and self.peek() == ("kw", "over")):
                raise SqlError(f"{token[1]}() requires an OVER clause")
            return self._maybe_over(item)
        if token[0] == "kw" and token[1] in _CONTEXTUAL:
            # contextual keyword in identifier position = a column name
            token = ("ident", token[1])
        if token[0] == "kw" and token[1] == "count":
            self.expect("op", "(")
            if self.accept("op", "*"):
                self.expect("op", ")")
                return self._maybe_over(
                    SelectItem("count_star", alias=self._alias()))
            if self.accept("kw", "distinct"):
                # COUNT(DISTINCT col) rides the device HLL cardinality
                # kernel (approximate, like every engine at scale)
                column = self._ident()
                self.expect("op", ")")
                return self._maybe_over(
                    SelectItem("agg", func="count_distinct",
                               column=column, alias=self._alias()))
            column = self._ident()
            self.expect("op", ")")
            return self._maybe_over(
                SelectItem("agg", func="count", column=column,
                           alias=self._alias()))
        if token[0] == "kw" and token[1] == "approx_count_distinct":
            self.expect("op", "(")
            column = self._ident()
            self.expect("op", ")")
            return self._maybe_over(
                SelectItem("agg", func="count_distinct", column=column,
                           alias=self._alias()))
        if token[0] == "kw" and token[1] in ("sum", "avg", "min", "max",
                                             "stddev", "variance"):
            self.expect("op", "(")
            column = self._ident()
            self.expect("op", ")")
            return self._maybe_over(
                SelectItem("agg", func=token[1], column=column,
                           alias=self._alias()))
        if token[0] == "kw" and token[1] == "approx_percentile":
            self.expect("op", "(")
            column = self._ident()
            self.expect("op", ",")
            percent = float(self.expect("number")[1])
            if not 0 < percent < 100:
                raise SqlError("approx_percentile takes a percent in (0,100)")
            self.expect("op", ")")
            return self._maybe_over(
                SelectItem("agg", func="approx_percentile", column=column,
                           percent=percent, alias=self._alias()))
        if token[0] == "kw" and token[1] == "date_trunc":
            self.expect("op", "(")
            unit = self.expect("string")[1].lower()
            if unit not in _TRUNC_MICROS:
                raise SqlError(f"unsupported date_trunc unit {unit!r}")
            self.expect("op", ",")
            column = self._ident()
            self.expect("op", ")")
            return SelectItem("trunc", column=column, unit=unit,
                              alias=self._alias())
        if token[0] == "ident":
            return SelectItem("col", column=token[1], alias=self._alias())
        raise SqlError(f"unexpected token {token[1]!r} in SELECT")

    def _alias(self) -> Optional[str]:
        if self.accept("kw", "as"):
            return self.next()[1]
        return None

    def group_key(self) -> SelectItem:
        item = self.select_item()
        if item.kind not in ("col", "trunc"):
            raise SqlError("GROUP BY takes columns or DATE_TRUNC(...)")
        return item

    def order_target(self) -> str:
        # an alias, a bare column, count(*) or fn(col)
        item = self.select_item()
        if item.kind == "star":
            raise SqlError("ORDER BY position numbers are not "
                           "supported; use the column name or alias")
        return item.name

    # --- WHERE ---------------------------------------------------------
    def predicate(self) -> Q.QueryAst:
        left = self.pred_term()
        while True:
            if self.accept("kw", "or"):
                right = self.pred_term()
                left = Q.Bool(should=(left, right), minimum_should_match=1)
            else:
                break
        return left

    def pred_term(self) -> Q.QueryAst:
        left = self.pred_factor()
        while self.accept("kw", "and"):
            right = self.pred_factor()
            left = Q.Bool(must=(left, right))
        return left

    def _exists_subquery(self, negate: bool) -> Q.QueryAst:
        self.expect("op", "(")
        sub = self.parse_select()
        self.expect("op", ")")
        return SubqueryPred("", "not_exists" if negate else "exists", sub)

    def pred_factor(self) -> Q.QueryAst:
        if self.accept("op", "("):
            inner = self.predicate()
            self.expect("op", ")")
            return inner
        # [NOT] EXISTS (SELECT ...) — contextual: `exists`/`not` still
        # parse as column names unless the subquery shape follows
        if self.peek() == ("kw", "exists") \
                and self.tokens[self.pos + 1: self.pos + 2] \
                == [("op", "(")]:
            self.next()
            return self._exists_subquery(negate=False)
        if self.peek() == ("kw", "not") \
                and self.tokens[self.pos + 1: self.pos + 2] \
                == [("kw", "exists")]:
            self.next()
            self.next()
            return self._exists_subquery(negate=True)
        column = self._ident()
        if self.accept("kw", "not"):
            self.expect("kw", "in")
            return self._in_subquery(column, negate=True)
        if self.accept("kw", "in"):
            return self._in_subquery(column, negate=False)
        op = self.expect("op")[1]
        if op in ("=", "!=", "<>", "<", "<=", ">", ">=") \
                and self.peek() == ("op", "(") \
                and self.pos + 1 < len(self.tokens) \
                and self.tokens[self.pos + 1] == ("kw", "select"):
            self.next()  # (
            sub = self.parse_select()
            self.expect("op", ")")
            return SubqueryPred(column, op, sub)
        if op == "=" and self.peek() is not None \
                and (self.peek()[0] == "ident"
                     or (self.peek()[0] == "kw"
                         and self.peek()[1] in _CONTEXTUAL)):
            # column = column: EXISTS correlation predicate
            return ColumnEq(column, self._ident())
        kind, literal = self.next()
        if kind not in ("number", "string"):
            raise SqlError(f"expected literal after {op}, got {literal!r}")
        if op == "=":
            return Q.Term(column, str(literal), verbatim=True)
        if op in ("!=", "<>"):
            return Q.Bool(must=(Q.MatchAll(),),
                          must_not=(Q.Term(column, str(literal),
                                           verbatim=True),))
        bound = Q.RangeBound(literal if kind == "string"
                             else float(literal), op in ("<=", ">="))
        if op in (">", ">="):
            return Q.Range(column, lower=bound)
        return Q.Range(column, upper=bound)

    def _in_subquery(self, column: str, negate: bool) -> Q.QueryAst:
        self.expect("op", "(")
        if self.peek() == ("kw", "select"):
            sub = self.parse_select()
            self.expect("op", ")")
            return SubqueryPred(column, "not_in" if negate else "in", sub)
        values = [str(self.next()[1])]
        while self.accept("op", ","):
            values.append(str(self.next()[1]))
        self.expect("op", ")")
        member: Q.QueryAst = Q.TermSet({column: tuple(values)})
        if negate:
            return Q.Bool(must=(Q.MatchAll(),), must_not=(member,))
        return member


def parse_sql(text: str) -> SqlQuery:
    return _Parser(_tokenize(text)).parse()


# --------------------------------------------------------------------------
# compilation onto the search/agg substrate

def _metric_body(item: SelectItem) -> dict:
    if item.kind == "count_star":
        return {}
    if item.func == "count":
        return {"value_count": {"field": item.column}}
    if item.func == "count_distinct":
        return {"cardinality": {"field": item.column}}
    if item.func == "approx_percentile":
        return {"percentiles": {"field": item.column,
                                "percents": [item.percent]}}
    if item.func in ("stddev", "variance"):
        return {"extended_stats": {"field": item.column}}
    return {item.func: {"field": item.column}}


def _metric_value(item: SelectItem, agg_result: dict):
    if item.func == "approx_percentile":
        return (agg_result.get("values") or {}).get(f"{item.percent:g}")
    if item.func == "stddev":
        return agg_result.get("std_deviation")
    if item.func == "variance":
        return agg_result.get("variance")
    return agg_result.get("value")


def execute_sql(text: str, search) -> dict[str, Any]:
    """Parse + compile + run one SQL statement. `search(index_id,
    query_ast, max_hits, aggs)` is the injected search entry (the node's
    root searcher) — analytics rides the full distributed query path.
    Returns {"columns": [...], "rows": [[...], ...]}."""
    return _execute(parse_sql(text), search)


def _execute(q: SqlQuery, search) -> dict[str, Any]:
    if any(s.kind == "star" for s in q.select):
        raise SqlError(
            "SELECT * / SELECT 1 is only supported inside EXISTS "
            "subqueries; name the columns")
    if q.joins:
        return _run_join(q, search)
    ast = _resolve_subqueries(q.where, search, q.alias) \
        if q.where is not None else Q.MatchAll()
    aggregates = [s for s in q.select
                  if s.kind in ("agg", "count_star")]
    windows = [s for s in q.select if s.kind == "window"]
    plain_cols = [s for s in q.select if s.kind in ("col", "trunc")]

    if windows:
        if q.group_by:
            raise SqlError(
                "window functions cannot be combined with GROUP BY")
        if aggregates:
            raise SqlError(
                "window functions cannot be mixed with plain aggregates")
        if any(s.kind == "trunc" for s in q.select):
            raise SqlError(
                "DATE_TRUNC is not supported alongside window functions")
        return _run_window(q, ast, search)
    if q.group_by:
        return _run_grouped(q, ast, aggregates, search)
    if aggregates:
        if plain_cols:
            raise SqlError(
                "non-aggregated columns require GROUP BY")
        return _run_global_aggs(q, ast, aggregates, search)
    if any(s.kind == "trunc" for s in q.select):
        raise SqlError(
            "DATE_TRUNC in a plain projection requires GROUP BY")
    return _run_projection(q, ast, search)


# --------------------------------------------------------------------------
# subqueries: resolved against live results, then compiled to plain
# predicates so the outer query still rides the device path untouched

def _resolve_subqueries(node, search, outer_alias=None):
    if isinstance(node, SubqueryPred):
        return _resolve_one_subquery(node, search, outer_alias)
    if isinstance(node, ColumnEq):
        raise SqlError(
            f"column-to-column comparison {node.left} = {node.right} is "
            "only supported in JOIN ON clauses and as the correlation "
            "predicate of an EXISTS subquery")
    if isinstance(node, Q.Bool):
        return Q.Bool(
            must=tuple(_resolve_subqueries(c, search, outer_alias)
                       for c in node.must),
            must_not=tuple(_resolve_subqueries(c, search, outer_alias)
                           for c in node.must_not),
            should=tuple(_resolve_subqueries(c, search, outer_alias)
                         for c in node.should),
            filter=tuple(_resolve_subqueries(c, search, outer_alias)
                         for c in node.filter),
            minimum_should_match=node.minimum_should_match)
    return node


def _resolve_one_subquery(pred: SubqueryPred, search,
                          outer_alias=None) -> Q.QueryAst:
    sub = pred.query
    if sub.joins:
        raise SqlError("subqueries cannot contain JOINs")
    if pred.op in ("exists", "not_exists"):
        return _decorrelate_exists(pred, search, outer_alias)
    if pred.op in ("in", "not_in"):
        if len(sub.select) != 1:
            raise SqlError("IN subquery must select exactly one column")
        # an un-limited plain projection drains up to cap+1, not the
        # projection default of 100 — membership wants ALL values, and
        # the extra row makes overflow DETECTABLE instead of a silent
        # truncation (NOT IN would otherwise return rows it must drop)
        if sub.limit is None and not sub.group_by and not any(
                s.kind in ("agg", "count_star") for s in sub.select):
            sub = SqlQuery(**{**sub.__dict__,
                              "limit": MATERIALIZE_CAP + 1})
        rows = _execute(sub, search)["rows"]
        if len(rows) > MATERIALIZE_CAP:
            raise SqlError(
                f"IN subquery produced more than {MATERIALIZE_CAP} values")
        values = tuple(dict.fromkeys(
            _sql_str(r[0]) for r in rows if r and r[0] is not None))
        if pred.op == "in":
            return Q.TermSet({pred.column: values}) if values \
                else Q.MatchNone()
        if not values:
            return Q.MatchAll()
        return Q.Bool(must=(Q.MatchAll(),),
                      must_not=(Q.TermSet({pred.column: values}),))
    rows = _execute(sub, search)["rows"]
    if not rows:
        # SQL: a 0-row scalar subquery is NULL; any comparison with
        # NULL is unknown -> matches nothing
        return Q.MatchNone()
    if len(rows) != 1 or len(rows[0]) != 1:
        raise SqlError("scalar subquery must return exactly one value "
                       f"(got {len(rows)} rows)")
    value = rows[0][0]
    if value is None:
        return Q.MatchNone()
    if pred.op == "=":
        return Q.Term(pred.column, _sql_str(value), verbatim=True)
    if pred.op in ("!=", "<>"):
        return Q.Bool(must=(Q.MatchAll(),),
                      must_not=(Q.Term(pred.column, _sql_str(value),
                                       verbatim=True),))
    try:
        numeric = float(value)
    except (TypeError, ValueError):
        raise SqlError(
            f"scalar subquery for {pred.op!r} must return a number "
            f"(got {value!r})")
    bound = Q.RangeBound(numeric, pred.op in ("<=", ">="))
    if pred.op in (">", ">="):
        return Q.Range(pred.column, lower=bound)
    return Q.Range(pred.column, upper=bound)


def _contains_column_eq(node) -> bool:
    if isinstance(node, ColumnEq):
        return True
    if isinstance(node, Q.Bool):
        return any(_contains_column_eq(c)
                   for group in (node.must, node.must_not,
                                 node.should, node.filter)
                   for c in group)
    return False


def _decorrelate_exists(pred: SubqueryPred, search,
                        outer_alias) -> Q.QueryAst:
    """[NOT] EXISTS with an equality correlation decorrelates onto the
    IN machinery: `EXISTS (SELECT 1 FROM b x WHERE x.k = k AND <preds>)`
    becomes `k [NOT] IN (SELECT x.k FROM b WHERE <preds>)`, so the
    outer query STILL compiles onto the device scan (the fork's
    DataFusion plans the same rewrite). NULL semantics follow EXISTS:
    a missing outer key never matches (and NOT EXISTS keeps it)."""
    sub = pred.query
    negate = pred.op == "not_exists"
    if sub.group_by or sub.having or sub.order_by \
            or sub.limit is not None or sub.offset:
        raise SqlError(
            "EXISTS subqueries support only FROM and WHERE "
            "(GROUP BY/HAVING/ORDER BY/LIMIT would be silently "
            "meaningless after decorrelation)")
    if any(s.kind in ("agg", "count_star") for s in sub.select):
        # SQL: an ungrouped aggregate subquery yields EXACTLY one row
        # (COUNT over zero rows is still the row [0]), so EXISTS over
        # it is constant-true — fold, matching Postgres/DataFusion
        return Q.MatchNone() if negate else Q.MatchAll()
    inner_prefix = (sub.alias + ".") if sub.alias else None
    outer_prefix = (outer_alias + ".") if outer_alias else None

    def strip_outer(name: str) -> str:
        if outer_prefix and name.startswith(outer_prefix):
            return name[len(outer_prefix):]
        return name

    correlations: list[tuple[str, str]] = []   # (outer col, inner col)
    inner_preds: list[Q.QueryAst] = []
    for conj in _conjuncts(sub.where) if sub.where is not None else []:
        if isinstance(conj, ColumnEq):
            if inner_prefix is None:
                raise SqlError(
                    "correlated EXISTS requires an alias on the inner "
                    "table (EXISTS (SELECT 1 FROM other x "
                    "WHERE x.k = k))")
            sides = (conj.left, conj.right)
            inner_side = [s for s in sides
                          if s.startswith(inner_prefix)]
            outer_side = [s for s in sides
                          if not s.startswith(inner_prefix)]
            if len(inner_side) != 1:
                raise SqlError(
                    f"EXISTS correlation {conj.left} = {conj.right} "
                    f"must compare one {sub.alias!r}-column with one "
                    "outer column")
            correlations.append((strip_outer(outer_side[0]),
                                 inner_side[0][len(inner_prefix):]))
            continue
        if _contains_column_eq(conj):
            raise SqlError(
                "the EXISTS correlation (col = col) must be a "
                "top-level AND conjunct of the subquery's WHERE — "
                "not nested under OR/NOT")
        fields = _pred_fields(conj)
        if inner_prefix is not None and any(
                not f.startswith(inner_prefix) for f in fields
                if "." in f
                and f.split(".", 1)[0] == (outer_alias or "")):
            raise SqlError(
                "outer-column predicates inside EXISTS must be the "
                "equality correlation (col = col)")
        inner_preds.append(_strip_alias(conj, sub.alias)
                           if sub.alias else conj)
    if len(correlations) > 1:
        raise SqlError(
            "EXISTS supports exactly one equality correlation")
    inner_where = Q.Bool(must=tuple(inner_preds)) if inner_preds \
        else None
    if not correlations:
        # uncorrelated EXISTS: constant-folds on whether ANY row matches
        probe = SqlQuery(index=sub.index,
                         select=[SelectItem("count_star")],
                         where=inner_where, alias=sub.alias)
        [[count]] = _execute(probe, search)["rows"]
        non_empty = bool(count)
        return Q.MatchAll() if non_empty != negate else Q.MatchNone()
    outer_col, inner_col = correlations[0]
    membership = SqlQuery(
        index=sub.index,
        select=[SelectItem("col", column=inner_col)],
        where=inner_where, alias=sub.alias)
    return _resolve_one_subquery(
        SubqueryPred(outer_col, "not_in" if negate else "in",
                     membership), search)


def _sql_str(value) -> str:
    """Literal normalization matching the parser's number formatting: a
    whole float renders as its integer spelling (Term lookups are
    string-keyed)."""
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return str(value)


def _agg_requests(aggregates: list[SelectItem]) -> dict:
    """One agg entry per DISTINCT metric body: SELECT STDDEV(x),
    VARIANCE(x) shares one extended_stats kernel; `_agg_key` maps each
    select item to its entry."""
    aggs = {}
    seen: dict[str, str] = {}
    for i, item in enumerate(aggregates):
        if item.kind == "count_star":
            continue  # doc_count / num_hits covers it
        body = _metric_body(item)
        canon = repr(sorted(body.items()))
        if canon not in seen:
            seen[canon] = f"a{i}"
            aggs[f"a{i}"] = body
    return aggs


def _agg_key(aggregates: list[SelectItem], item: SelectItem) -> str:
    canon = repr(sorted(_metric_body(item).items()))
    for i, other in enumerate(aggregates):
        if other.kind != "count_star" and \
                repr(sorted(_metric_body(other).items())) == canon:
            return f"a{i}"
    raise SqlError(f"internal: no agg entry for {item.name!r}")


def _run_global_aggs(q: SqlQuery, ast, aggregates, search):
    response = search(q.index, ast, 0, _agg_requests(aggregates) or None)
    row = []
    for i, item in enumerate(aggregates):
        if item.kind == "count_star":
            row.append(response.num_hits)
        else:
            row.append(_metric_value(
                item, (response.aggregations or {}).get(
                    _agg_key(aggregates, item), {})))
    rows = _apply_having(q, [row])
    return {"columns": [s.name for s in q.select], "rows": rows}


def _group_agg_body(key: SelectItem) -> dict:
    if key.kind == "trunc":
        interval_micros = _TRUNC_MICROS[key.unit]
        body = {"field": key.column,
                "fixed_interval": f"{interval_micros // 1_000_000}s",
                "min_doc_count": 1}
        if key.unit == "week":
            # SQL DATE_TRUNC weeks are Monday-aligned; the Unix epoch is a
            # Thursday, so shift bucket boundaries back 3 days
            body["offset"] = "-3d"
        return {"date_histogram": body}
    return {"terms": {"field": key.column, "size": 65536}}


def _run_grouped(q: SqlQuery, ast, aggregates, search):
    # every selected plain column must be a group key
    group_names = {g.name for g in q.group_by} | \
                  {g.column for g in q.group_by}
    for s in q.select:
        if s.kind in ("col", "trunc") and s.name not in group_names \
                and s.column not in group_names:
            raise SqlError(f"column {s.name!r} must appear in GROUP BY")

    # GROUP BY chain of any length compiles onto one nested bucket tree
    # (arbitrary-depth flattened device bucket spaces); metrics ride the
    # innermost level
    bodies = [_group_agg_body(g) for g in q.group_by]
    bodies[-1]["aggs"] = dict(_agg_requests(aggregates))
    for i in range(len(bodies) - 2, -1, -1):
        bodies[i]["aggs"] = {f"g{i + 1}": bodies[i + 1]}
    response = search(q.index, ast, 0, {"g0": bodies[0]})

    rows: list[list] = []

    def walk(level: int, path: list[dict], container: dict) -> None:
        for bucket in container.get(f"g{level}", {}).get("buckets", []):
            if level + 1 < len(q.group_by):
                walk(level + 1, path + [bucket], bucket)
            else:
                rows.append(_bucket_row(q, path + [bucket], aggregates))

    walk(0, [], response.aggregations or {})
    rows = _apply_having(q, rows)
    rows = _order_and_limit(q, rows)
    return {"columns": [s.name for s in q.select], "rows": rows}


_HAVING_OPS = {
    "=": lambda a, b: a == b, "!=": lambda a, b: a != b,
    "<>": lambda a, b: a != b, "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b, ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def _apply_having(q: SqlQuery, rows: list[list]) -> list[list]:
    if not q.having:
        return rows
    names = [s.name for s in q.select]
    for target, _op, _value in q.having:
        if target not in names:
            raise SqlError(
                f"HAVING target {target!r} must be selected (add it to "
                "the SELECT list, aliased if needed)")
    out = []
    for row in rows:
        keep = True
        for target, op, value in q.having:
            cell = row[names.index(target)]
            try:
                numeric = float(cell) if cell is not None else None
            except (TypeError, ValueError):
                raise SqlError(
                    f"HAVING target {target!r} is not numeric "
                    f"(got {cell!r})")
            if numeric is None or not _HAVING_OPS[op](numeric, value):
                keep = False
                break
        if keep:
            out.append(row)
    return out


def _bucket_key(item: SelectItem, bucket: dict):
    if item.kind == "trunc":
        return bucket.get("key_as_string", bucket.get("key"))
    return bucket.get("key")


def _bucket_row(q: SqlQuery, buckets: list[dict], aggregates):
    inner = buckets[-1]
    row = []
    for s in q.select:
        if s.kind in ("col", "trunc"):
            level = next(i for i, g in enumerate(q.group_by)
                         if g.column == s.column and g.kind == s.kind)
            row.append(_bucket_key(s, buckets[level]))
        elif s.kind == "count_star":
            row.append(inner.get("doc_count"))
        else:
            row.append(_metric_value(
                s, inner.get(_agg_key(aggregates, s), {})))
    return row


def _order_and_limit(q: SqlQuery, rows: list[list]):
    if q.order_by is not None:
        name, desc = q.order_by
        try:
            idx = [s.name for s in q.select].index(name)
        except ValueError:
            raise SqlError(f"ORDER BY target {name!r} is not selected")
        rows.sort(key=lambda r: (r[idx] is None,
                                 r[idx] if r[idx] is not None else 0),
                  reverse=desc)
    if q.offset:
        rows = rows[q.offset:]
    if q.limit is not None:
        rows = rows[: q.limit]
    return rows


def _run_projection(q: SqlQuery, ast, search):
    if q.having:
        raise SqlError("HAVING requires GROUP BY or aggregates")
    limit = q.limit if q.limit is not None else 100
    # fetch offset+limit hits so pagination slices real rows
    response = search(q.index, ast, limit + q.offset, None)
    columns = [s.name for s in q.select]
    rows = []
    for hit in response.hits:
        doc = hit.doc
        rows.append([_doc_get(doc, s.column or "") for s in q.select])
    if q.order_by:
        rows = _order_and_limit(q, rows)
    else:
        rows = rows[q.offset: q.offset + limit]
    return {"columns": columns, "rows": rows}


# --------------------------------------------------------------------------
# host-side relational layer: window functions + JOINs over rows
# materialized through the distributed search path (cap MATERIALIZE_CAP).
# The reference's DataFusion service gets these from its SQL engine over
# Parquet scans; here the device path stays the scan+filter substrate
# and the relational tail runs on the (already small) materialized set.

def _doc_get(doc, path: str):
    value: Any = doc
    for part in path.split("."):
        value = value.get(part) if isinstance(value, dict) else None
    return value


def _materialize(index: str, ast, search) -> list[dict]:
    response = search(index, ast, MATERIALIZE_CAP, None)
    if response.num_hits > MATERIALIZE_CAP:
        raise SqlError(
            f"query side matches {response.num_hits} rows; JOIN/window "
            f"materialization is capped at {MATERIALIZE_CAP} — narrow "
            "the predicate")
    return [hit.doc for hit in response.hits]


def _numeric(value) -> Optional[float]:
    if isinstance(value, bool) or value is None:
        return None
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


def _host_agg(func: str, column: Optional[str], values: list) -> Any:
    """One aggregate over host rows (the join/window tail). `values` are
    the raw column values (or row placeholders for COUNT(*))."""
    if func == "count" and column is None:
        return len(values)
    present = [v for v in values if v is not None]
    if func == "count":
        return len(present)
    if func == "count_distinct":
        return len({_sql_str(v) for v in present})
    nums = [n for n in (_numeric(v) for v in present) if n is not None]
    if not nums:
        return None
    if func == "sum":
        return sum(nums)
    if func == "avg":
        return sum(nums) / len(nums)
    if func == "min":
        return min(nums)
    if func == "max":
        return max(nums)
    raise SqlError(f"{func} is not supported over joined rows")


def _run_window(q: SqlQuery, ast, search):
    if q.having:
        raise SqlError("HAVING requires GROUP BY or aggregates")
    docs = _materialize(q.index, ast, search)
    win_values: dict[int, list] = {}
    for sel_idx, item in enumerate(q.select):
        if item.kind != "window":
            continue
        win_values[sel_idx] = _window_column(item, docs)
    rows = []
    for i, doc in enumerate(docs):
        row = []
        for sel_idx, item in enumerate(q.select):
            if item.kind == "window":
                row.append(win_values[sel_idx][i])
            else:
                row.append(_doc_get(doc, item.column or ""))
        rows.append(row)
    if q.order_by:
        rows = _order_and_limit(q, rows)
    else:
        limit = q.limit if q.limit is not None else 100
        rows = rows[q.offset: q.offset + limit]
    return {"columns": [s.name for s in q.select], "rows": rows}


def _window_column(item: SelectItem, docs: list[dict]) -> list:
    """Evaluate one window item over every row. With ORDER BY the frame
    is the SQL default RANGE UNBOUNDED PRECEDING..CURRENT ROW (running
    aggregate, order-value peers included); without it, the whole
    partition."""
    partitions: dict[tuple, list[int]] = {}
    for i, doc in enumerate(docs):
        key = tuple(_sql_str(_doc_get(doc, c)) for c in item.partition)
        partitions.setdefault(key, []).append(i)
    out: list = [None] * len(docs)
    for indices in partitions.values():
        if item.win_order is not None:
            col, desc = item.win_order
            order_vals = {i: _doc_get(docs[i], col) for i in indices}
            sort_key = lambda i: (  # noqa: E731
                order_vals[i] is None,
                _numeric(order_vals[i])
                if _numeric(order_vals[i]) is not None
                else 0.0,
                _sql_str(order_vals[i]) if order_vals[i] is not None
                and _numeric(order_vals[i]) is None else "")
            ordered = sorted(indices, key=sort_key, reverse=desc)
        else:
            ordered = list(indices)
        if item.func == "row_number":
            for pos, i in enumerate(ordered):
                out[i] = pos + 1
            continue
        if item.func == "rank":
            if item.win_order is None:
                for i in ordered:
                    out[i] = 1
                continue
            col, _ = item.win_order
            rank = 0
            prev = object()
            for pos, i in enumerate(ordered):
                cur = _doc_get(docs[i], col)
                if cur != prev:
                    rank = pos + 1
                    prev = cur
                out[i] = rank
            continue
        # running / whole-partition aggregate
        if item.win_order is None:
            values = [True if item.column is None
                      else _doc_get(docs[i], item.column)
                      for i in ordered]
            result = _host_agg(item.func, item.column, values)
            for i in ordered:
                out[i] = result
            continue
        col, _ = item.win_order
        # running accumulators carried across peer groups: O(n) per
        # partition (re-aggregating ordered[:end] per group is O(n^2),
        # minutes of host time at the materialization cap)
        run = _RunningAgg(item.func, item.column is None)
        pos = 0
        while pos < len(ordered):
            # peers (same order value) share one frame end
            end = pos + 1
            cur = _doc_get(docs[ordered[pos]], col)
            while end < len(ordered) \
                    and _doc_get(docs[ordered[end]], col) == cur:
                end += 1
            for i in ordered[pos:end]:
                run.add(True if item.column is None
                        else _doc_get(docs[i], item.column))
            result = run.result()
            for i in ordered[pos:end]:
                out[i] = result
            pos = end
    return out


class _RunningAgg:
    """Incremental count/sum/avg/min/max over a growing frame."""

    def __init__(self, func: str, count_star: bool):
        self.func = func
        self.count_star = count_star
        self.rows = 0        # COUNT(*): every row in the frame
        self.present = 0     # COUNT(col): non-null values
        self.total = 0.0
        self.nums = 0
        self.lo: Optional[float] = None
        self.hi: Optional[float] = None

    def add(self, value) -> None:
        self.rows += 1
        if value is None:
            return
        self.present += 1
        numeric = _numeric(value)
        if numeric is None:
            return
        self.nums += 1
        self.total += numeric
        self.lo = numeric if self.lo is None else min(self.lo, numeric)
        self.hi = numeric if self.hi is None else max(self.hi, numeric)

    def result(self):
        if self.func == "count":
            return self.rows if self.count_star else self.present
        if self.nums == 0:
            return None
        if self.func == "sum":
            return self.total
        if self.func == "avg":
            return self.total / self.nums
        if self.func == "min":
            return self.lo
        return self.hi


# --------------------------------------------------------------------------
# JOINs: each side's single-table predicates push down through the
# device scan path; the equality join itself hash-joins the (capped)
# materialized sides on the host, then the grouped/projected tail runs
# over joined rows.

def _conjuncts(node) -> list:
    """Flatten a pure-AND tree; anything else is one opaque conjunct."""
    if isinstance(node, Q.Bool) and not node.should \
            and not node.must_not and not node.filter:
        out: list = []
        for child in node.must:
            out.extend(_conjuncts(child))
        return out
    return [node]


def _pred_fields(node) -> set[str]:
    if isinstance(node, ColumnEq):
        return {node.left, node.right}
    if isinstance(node, SubqueryPred):
        if node.op in ("exists", "not_exists"):
            raise SqlError(
                "[NOT] EXISTS is not supported in this position "
                "(JOIN WHERE clauses or nested inside another EXISTS)")
        return {node.column}
    if isinstance(node, Q.Term):
        return {node.field}
    if isinstance(node, Q.Range):
        return {node.field}
    if isinstance(node, Q.TermSet):
        return set(node.terms_per_field)
    if isinstance(node, Q.Bool):
        fields: set[str] = set()
        for group in (node.must, node.must_not, node.should, node.filter):
            for child in group:
                fields |= _pred_fields(child)
        return fields
    if isinstance(node, (Q.MatchAll, Q.MatchNone)):
        return set()
    raise SqlError(
        f"unsupported predicate {type(node).__name__} in a JOIN query")


def _strip_alias(node, alias: str):
    """Rewrite every field `alias.col` -> `col` for the pushed-down
    single-table predicate."""
    from dataclasses import replace
    prefix = alias + "."

    def strip(name: str) -> str:
        return name[len(prefix):] if name.startswith(prefix) else name

    if isinstance(node, SubqueryPred):
        return SubqueryPred(strip(node.column), node.op, node.query)
    if isinstance(node, ColumnEq):
        return ColumnEq(strip(node.left), strip(node.right))
    if isinstance(node, (Q.Term, Q.Range)):
        return replace(node, field=strip(node.field))
    if isinstance(node, Q.TermSet):
        return Q.TermSet({strip(f): ts
                          for f, ts in node.terms_per_field.items()})
    if isinstance(node, Q.Bool):
        return Q.Bool(
            must=tuple(_strip_alias(c, alias) for c in node.must),
            must_not=tuple(_strip_alias(c, alias) for c in node.must_not),
            should=tuple(_strip_alias(c, alias) for c in node.should),
            filter=tuple(_strip_alias(c, alias) for c in node.filter),
            minimum_should_match=node.minimum_should_match)
    return node


def _qualified(name: str, aliases: dict[str, str]) -> tuple[str, str]:
    head, _, rest = name.partition(".")
    if head in aliases and rest:
        return head, rest
    raise SqlError(
        f"column {name!r} in a JOIN query must be alias-qualified "
        f"(one of {sorted(aliases)})")


def _row_get(row: dict[str, Optional[dict]], name: str,
             aliases: dict[str, str]):
    alias, path = _qualified(name, aliases)
    doc = row.get(alias)
    return _doc_get(doc, path) if doc is not None else None


def _run_join(q: SqlQuery, search) -> dict[str, Any]:
    aliases: dict[str, str] = {}
    if q.alias is None:
        raise SqlError("JOIN requires table aliases")
    aliases[q.alias] = q.index
    for j in q.joins:
        if j.alias in aliases:
            raise SqlError(f"duplicate table alias {j.alias!r}")
        aliases[j.alias] = j.index
    for s in q.select + q.group_by:
        if s.kind == "window":
            raise SqlError(
                "window functions are not supported in JOIN queries")
        if s.kind == "trunc":
            raise SqlError("DATE_TRUNC is not supported in JOIN queries")

    # decompose WHERE into single-table pushdowns
    pushdown: dict[str, list] = {a: [] for a in aliases}
    if q.where is not None:
        for conj in _conjuncts(q.where):
            fields = _pred_fields(conj)
            owners = {_qualified(f, aliases)[0] for f in fields}
            if len(owners) != 1:
                raise SqlError(
                    "each WHERE conjunct in a JOIN query must reference "
                    f"exactly one table (got {sorted(owners) or 'none'})")
            owner = owners.pop()
            pushdown[owner].append(_strip_alias(conj, owner))
    # a WHERE predicate on the nullable side of a LEFT JOIN is
    # null-rejecting (our predicates never match a missing field), so
    # SQL's post-join WHERE degenerates the join to INNER; pushing the
    # predicate into the side's scan while staying left-outer would
    # instead RESURRECT filtered-out rows as NULL-extended ones
    joins = [JoinClause(j.index, j.alias, j.on, left_outer=False)
             if j.left_outer and pushdown[j.alias] else j
             for j in q.joins]

    sides: dict[str, list[dict]] = {}
    for alias, index in aliases.items():
        preds = [_resolve_subqueries(p, search) for p in pushdown[alias]]
        ast = Q.Bool(must=tuple(preds)) if preds else Q.MatchAll()
        sides[alias] = _materialize(index, ast, search)

    # left-fold hash joins
    rows: list[dict[str, Optional[dict]]] = [
        {q.alias: doc} for doc in sides[q.alias]]
    joined = {q.alias}
    for j in joins:
        left_keys: list[str] = []
        right_keys: list[str] = []
        for lhs, rhs in j.on:
            l_alias, _ = _qualified(lhs, aliases)
            r_alias, _ = _qualified(rhs, aliases)
            if r_alias == j.alias and l_alias in joined:
                left_keys.append(lhs)
                right_keys.append(rhs)
            elif l_alias == j.alias and r_alias in joined:
                left_keys.append(rhs)
                right_keys.append(lhs)
            else:
                raise SqlError(
                    f"ON clause for {j.alias!r} must join it to an "
                    "already-joined table")
        # SQL NULL semantics: a missing/null key component never
        # matches anything (NULL = NULL is not true) — null-keyed docs
        # are left out of the build side and probe as no-match
        def join_key(values: list) -> Optional[tuple]:
            if any(v is None for v in values):
                return None
            return tuple(_sql_str(v) for v in values)

        table: dict[tuple, list[dict]] = {}
        for doc in sides[j.alias]:
            key = join_key([_doc_get(doc, _qualified(k, aliases)[1])
                            for k in right_keys])
            if key is not None:
                table.setdefault(key, []).append(doc)
        next_rows: list[dict[str, Optional[dict]]] = []
        for row in rows:
            key = join_key([_row_get(row, k, aliases)
                            for k in left_keys])
            matches = table.get(key, []) if key is not None else []
            if matches:
                for doc in matches:
                    next_rows.append({**row, j.alias: doc})
            elif j.left_outer:
                next_rows.append({**row, j.alias: None})
            if len(next_rows) > MATERIALIZE_CAP:
                raise SqlError(
                    f"JOIN produced more than {MATERIALIZE_CAP} rows — "
                    "narrow the predicates")
        rows = next_rows
        joined.add(j.alias)

    aggregates = [s for s in q.select if s.kind in ("agg", "count_star")]
    if q.group_by:
        return _run_join_grouped(q, rows, aggregates, aliases)
    if aggregates:
        if any(s.kind == "col" for s in q.select):
            raise SqlError("non-aggregated columns require GROUP BY")
        row = [_join_agg(s, rows, aliases) for s in q.select]
        out_rows = _apply_having(q, [row])
        return {"columns": [s.name for s in q.select], "rows": out_rows}
    if q.having:
        raise SqlError("HAVING requires GROUP BY or aggregates")
    out_rows = [[_row_get(row, s.column or "", aliases)
                 for s in q.select] for row in rows]
    if q.order_by:
        out_rows = _order_and_limit(q, out_rows)
    else:
        limit = q.limit if q.limit is not None else 100
        out_rows = out_rows[q.offset: q.offset + limit]
    return {"columns": [s.name for s in q.select], "rows": out_rows}


def _join_agg(item: SelectItem, rows: list[dict],
              aliases: dict[str, str]):
    if item.kind == "count_star":
        return len(rows)
    if item.func in ("approx_percentile", "stddev", "variance"):
        raise SqlError(f"{item.func} is not supported over joined rows")
    values = [_row_get(row, item.column or "", aliases) for row in rows]
    return _host_agg(item.func, item.column, values)


def _run_join_grouped(q: SqlQuery, rows: list[dict], aggregates,
                      aliases: dict[str, str]) -> dict[str, Any]:
    keys = [g.column or "" for g in q.group_by]
    group_names = {g.name for g in q.group_by} | set(keys)
    for s in q.select:
        if s.kind == "col" and s.name not in group_names \
                and s.column not in group_names:
            raise SqlError(f"column {s.name!r} must appear in GROUP BY")
    groups: dict[tuple, list[dict]] = {}
    for row in rows:
        key = tuple(_row_get(row, k, aliases) for k in keys)
        groups.setdefault(key, []).append(row)
    out_rows = []
    for key, members in groups.items():
        out = []
        for s in q.select:
            if s.kind == "col":
                out.append(key[keys.index(s.column or "")])
            else:
                out.append(_join_agg(s, members, aliases))
        out_rows.append(out)
    out_rows = _apply_having(q, out_rows)
    out_rows = _order_and_limit(q, out_rows)
    return {"columns": [s.name for s in q.select], "rows": out_rows}
