"""SQL analytics surface — the TPU-first analogue of the fork's
Parquet/DataFusion engine.

Role of the reference's `quickwit-datafusion` / `quickwit-df-core`
(`src/sources/metrics/table_provider.rs:1`, `service.rs:1`, mounted at
`quickwit-serve/src/datafusion_api/setup.rs:201`): a SQL aggregation
surface over the columnar data. The fork bolts a SECOND engine
(DataFusion over Parquet) beside tantivy; here the design is unified —
SQL **compiles onto the same device kernels** the search path runs
(QueryAst predicate → dense masks, GROUP BY → terms/date_histogram
bucket spaces, aggregates → the mergeable metric states), so analytics
inherits the whole distributed substrate: split pruning, fan-out, the
scatter-gather merge tree, caches, and admission. There is no second
storage format to compact and no second executor to schedule.

Dialect (vertical slice):

    SELECT <agg|col|DATE_TRUNC('unit', col)> [AS alias], ...
    FROM <index>
    [WHERE <col op literal> [AND|OR ...] ]
    [GROUP BY <col | DATE_TRUNC('unit', col)> [, <col>]]
    [ORDER BY <alias|expr> [ASC|DESC]]
    [LIMIT n]

Aggregates: COUNT(*), COUNT(col), SUM, AVG, MIN, MAX.
Operators: = != <> < <= > >= ; string/number literals; AND/OR + parens.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional

from ..query import ast as Q

_TRUNC_MICROS = {
    "second": 1_000_000, "minute": 60_000_000, "hour": 3_600_000_000,
    "day": 86_400_000_000, "week": 7 * 86_400_000_000,
}


class SqlError(ValueError):
    pass


# --------------------------------------------------------------------------
# lexer

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<number>-?\d+(?:\.\d+)?)
    | (?P<string>'(?:[^'\\]|\\.)*')
    | (?P<op><=|>=|!=|<>|=|<|>|\(|\)|,|\*)
    | (?P<word>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""", re.VERBOSE)

_KEYWORDS = {"select", "from", "where", "group", "by", "order", "limit",
             "and", "or", "as", "asc", "desc", "count", "sum", "avg",
             "min", "max", "date_trunc"}


def _tokenize(text: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise SqlError(f"cannot tokenize SQL at {text[pos:pos+20]!r}")
        pos = m.end()
        if m.group("number") is not None:
            out.append(("number", m.group("number")))
        elif m.group("string") is not None:
            out.append(("string",
                        m.group("string")[1:-1].replace("\\'", "'")))
        elif m.group("op") is not None:
            out.append(("op", m.group("op")))
        else:
            word = m.group("word")
            kind = "kw" if word.lower() in _KEYWORDS else "ident"
            out.append((kind, word.lower() if kind == "kw" else word))
    return out


# --------------------------------------------------------------------------
# AST

@dataclass(frozen=True)
class SelectItem:
    kind: str                 # "count_star" | "agg" | "col" | "trunc"
    func: Optional[str] = None
    column: Optional[str] = None
    unit: Optional[str] = None
    alias: Optional[str] = None

    @property
    def name(self) -> str:
        if self.alias:
            return self.alias
        if self.kind == "count_star":
            return "count(*)"
        if self.kind == "agg":
            return f"{self.func}({self.column})"
        if self.kind == "trunc":
            return f"date_trunc('{self.unit}', {self.column})"
        return self.column or ""


@dataclass
class SqlQuery:
    index: str
    select: list[SelectItem]
    where: Optional[Q.QueryAst] = None
    group_by: list[SelectItem] = field(default_factory=list)
    order_by: Optional[tuple[str, bool]] = None  # (name, desc)
    limit: Optional[int] = None


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.tokens = tokens
        self.pos = 0

    def peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self):
        token = self.peek()
        if token is None:
            raise SqlError("unexpected end of query")
        self.pos += 1
        return token

    def expect(self, kind: str, value: Optional[str] = None):
        token = self.next()
        if token[0] != kind or (value is not None and token[1] != value):
            raise SqlError(f"expected {value or kind}, got {token[1]!r}")
        return token

    def accept(self, kind: str, value: Optional[str] = None) -> bool:
        token = self.peek()
        if token and token[0] == kind and (value is None
                                           or token[1] == value):
            self.pos += 1
            return True
        return False

    # --- grammar -------------------------------------------------------
    def parse(self) -> SqlQuery:
        self.expect("kw", "select")
        select = [self.select_item()]
        while self.accept("op", ","):
            select.append(self.select_item())
        self.expect("kw", "from")
        index = self.expect("ident")[1]
        where = None
        if self.accept("kw", "where"):
            where = self.predicate()
        group_by: list[SelectItem] = []
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_by.append(self.group_key())
            while self.accept("op", ","):
                group_by.append(self.group_key())
        order_by = None
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            name = self.order_target()
            desc = False
            if self.accept("kw", "desc"):
                desc = True
            else:
                self.accept("kw", "asc")
            order_by = (name, desc)
        limit = None
        if self.accept("kw", "limit"):
            limit = int(self.expect("number")[1])
        if self.peek() is not None:
            raise SqlError(f"unexpected trailing token {self.peek()[1]!r}")
        return SqlQuery(index=index, select=select, where=where,
                        group_by=group_by, order_by=order_by, limit=limit)

    def select_item(self) -> SelectItem:
        token = self.next()
        if token[0] == "kw" and token[1] == "count":
            self.expect("op", "(")
            if self.accept("op", "*"):
                self.expect("op", ")")
                return SelectItem("count_star", alias=self._alias())
            column = self.expect("ident")[1]
            self.expect("op", ")")
            return SelectItem("agg", func="count", column=column,
                              alias=self._alias())
        if token[0] == "kw" and token[1] in ("sum", "avg", "min", "max"):
            self.expect("op", "(")
            column = self.expect("ident")[1]
            self.expect("op", ")")
            return SelectItem("agg", func=token[1], column=column,
                              alias=self._alias())
        if token[0] == "kw" and token[1] == "date_trunc":
            self.expect("op", "(")
            unit = self.expect("string")[1].lower()
            if unit not in _TRUNC_MICROS:
                raise SqlError(f"unsupported date_trunc unit {unit!r}")
            self.expect("op", ",")
            column = self.expect("ident")[1]
            self.expect("op", ")")
            return SelectItem("trunc", column=column, unit=unit,
                              alias=self._alias())
        if token[0] == "ident":
            return SelectItem("col", column=token[1], alias=self._alias())
        raise SqlError(f"unexpected token {token[1]!r} in SELECT")

    def _alias(self) -> Optional[str]:
        if self.accept("kw", "as"):
            return self.next()[1]
        return None

    def group_key(self) -> SelectItem:
        item = self.select_item()
        if item.kind not in ("col", "trunc"):
            raise SqlError("GROUP BY takes columns or DATE_TRUNC(...)")
        return item

    def order_target(self) -> str:
        # an alias, a bare column, count(*) or fn(col)
        item = self.select_item()
        return item.name

    # --- WHERE ---------------------------------------------------------
    def predicate(self) -> Q.QueryAst:
        left = self.pred_term()
        while True:
            if self.accept("kw", "or"):
                right = self.pred_term()
                left = Q.Bool(should=(left, right), minimum_should_match=1)
            else:
                break
        return left

    def pred_term(self) -> Q.QueryAst:
        left = self.pred_factor()
        while self.accept("kw", "and"):
            right = self.pred_factor()
            left = Q.Bool(must=(left, right))
        return left

    def pred_factor(self) -> Q.QueryAst:
        if self.accept("op", "("):
            inner = self.predicate()
            self.expect("op", ")")
            return inner
        column = self.expect("ident")[1]
        op = self.expect("op")[1]
        kind, literal = self.next()
        if kind not in ("number", "string"):
            raise SqlError(f"expected literal after {op}, got {literal!r}")
        if op == "=":
            return Q.Term(column, str(literal), verbatim=True)
        if op in ("!=", "<>"):
            return Q.Bool(must=(Q.MatchAll(),),
                          must_not=(Q.Term(column, str(literal),
                                           verbatim=True),))
        bound = Q.RangeBound(literal if kind == "string"
                             else float(literal), op in ("<=", ">="))
        if op in (">", ">="):
            return Q.Range(column, lower=bound)
        return Q.Range(column, upper=bound)


def parse_sql(text: str) -> SqlQuery:
    return _Parser(_tokenize(text)).parse()


# --------------------------------------------------------------------------
# compilation onto the search/agg substrate

def _metric_body(item: SelectItem) -> dict:
    if item.kind == "count_star":
        return {}
    if item.func == "count":
        return {"value_count": {"field": item.column}}
    return {item.func: {"field": item.column}}


def execute_sql(text: str, search) -> dict[str, Any]:
    """Parse + compile + run one SQL statement. `search(index_id,
    query_ast, max_hits, aggs)` is the injected search entry (the node's
    root searcher) — analytics rides the full distributed query path.
    Returns {"columns": [...], "rows": [[...], ...]}."""
    from ..query.parser import parse_query_string

    q = parse_sql(text)
    ast = q.where or Q.MatchAll()
    aggregates = [s for s in q.select
                  if s.kind in ("agg", "count_star")]
    plain_cols = [s for s in q.select if s.kind in ("col", "trunc")]

    if q.group_by:
        return _run_grouped(q, ast, aggregates, search)
    if aggregates:
        if plain_cols:
            raise SqlError(
                "non-aggregated columns require GROUP BY")
        return _run_global_aggs(q, ast, aggregates, search)
    if any(s.kind == "trunc" for s in q.select):
        raise SqlError(
            "DATE_TRUNC in a plain projection requires GROUP BY")
    return _run_projection(q, ast, search)


def _agg_requests(aggregates: list[SelectItem]) -> dict:
    aggs = {}
    for i, item in enumerate(aggregates):
        if item.kind == "count_star":
            continue  # doc_count / num_hits covers it
        aggs[f"a{i}"] = _metric_body(item)
    return aggs


def _run_global_aggs(q: SqlQuery, ast, aggregates, search):
    response = search(q.index, ast, 0, _agg_requests(aggregates) or None)
    row = []
    for i, item in enumerate(aggregates):
        if item.kind == "count_star":
            row.append(response.num_hits)
        else:
            row.append((response.aggregations or {}).get(
                f"a{i}", {}).get("value"))
    return {"columns": [s.name for s in q.select], "rows": [row]}


def _group_agg_body(key: SelectItem) -> dict:
    if key.kind == "trunc":
        interval_micros = _TRUNC_MICROS[key.unit]
        body = {"field": key.column,
                "fixed_interval": f"{interval_micros // 1_000_000}s",
                "min_doc_count": 1}
        if key.unit == "week":
            # SQL DATE_TRUNC weeks are Monday-aligned; the Unix epoch is a
            # Thursday, so shift bucket boundaries back 3 days
            body["offset"] = "-3d"
        return {"date_histogram": body}
    return {"terms": {"field": key.column, "size": 65536}}


def _run_grouped(q: SqlQuery, ast, aggregates, search):
    if len(q.group_by) > 2:
        raise SqlError("GROUP BY supports at most two keys")
    # every selected plain column must be a group key
    group_names = {g.name for g in q.group_by} | \
                  {g.column for g in q.group_by}
    for s in q.select:
        if s.kind in ("col", "trunc") and s.name not in group_names \
                and s.column not in group_names:
            raise SqlError(f"column {s.name!r} must appear in GROUP BY")

    outer_body = _group_agg_body(q.group_by[0])
    sub: dict = dict(_agg_requests(aggregates))
    if len(q.group_by) == 2:
        inner = _group_agg_body(q.group_by[1])
        inner["aggs"] = dict(_agg_requests(aggregates))
        sub = {"g1": inner}
    outer_body["aggs"] = sub
    response = search(q.index, ast, 0, {"g0": outer_body})
    buckets = (response.aggregations or {}).get("g0", {}).get("buckets", [])

    rows = []
    for bucket in buckets:
        if len(q.group_by) == 2:
            for inner_bucket in bucket.get("g1", {}).get("buckets", []):
                rows.append(_bucket_row(q, [bucket, inner_bucket],
                                        aggregates))
        else:
            rows.append(_bucket_row(q, [bucket], aggregates))

    rows = _order_and_limit(q, rows)
    return {"columns": [s.name for s in q.select], "rows": rows}


def _bucket_key(item: SelectItem, bucket: dict):
    if item.kind == "trunc":
        return bucket.get("key_as_string", bucket.get("key"))
    return bucket.get("key")


def _bucket_row(q: SqlQuery, buckets: list[dict], aggregates):
    inner = buckets[-1]
    row = []
    for s in q.select:
        if s.kind in ("col", "trunc"):
            level = next(i for i, g in enumerate(q.group_by)
                         if g.column == s.column and g.kind == s.kind)
            row.append(_bucket_key(s, buckets[level]))
        elif s.kind == "count_star":
            row.append(inner.get("doc_count"))
        else:
            pos = next(i for i, a in enumerate(aggregates) if a is s)
            row.append(inner.get(f"a{pos}", {}).get("value"))
    return row


def _order_and_limit(q: SqlQuery, rows: list[list]):
    if q.order_by is not None:
        name, desc = q.order_by
        try:
            idx = [s.name for s in q.select].index(name)
        except ValueError:
            raise SqlError(f"ORDER BY target {name!r} is not selected")
        rows.sort(key=lambda r: (r[idx] is None,
                                 r[idx] if r[idx] is not None else 0),
                  reverse=desc)
    if q.limit is not None:
        rows = rows[: q.limit]
    return rows


def _run_projection(q: SqlQuery, ast, search):
    limit = q.limit if q.limit is not None else 100
    response = search(q.index, ast, limit, None)
    columns = [s.name for s in q.select]
    rows = []
    for hit in response.hits:
        doc = hit.doc
        row = []
        for s in q.select:
            value: Any = doc
            for part in (s.column or "").split("."):
                value = value.get(part) if isinstance(value, dict) else None
            row.append(value)
        rows.append(row)
    rows = _order_and_limit(q, rows) if q.order_by else rows[:limit]
    return {"columns": columns, "rows": rows}
