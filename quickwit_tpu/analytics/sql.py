"""SQL analytics surface — the TPU-first analogue of the fork's
Parquet/DataFusion engine.

Role of the reference's `quickwit-datafusion` / `quickwit-df-core`
(`src/sources/metrics/table_provider.rs:1`, `service.rs:1`, mounted at
`quickwit-serve/src/datafusion_api/setup.rs:201`): a SQL aggregation
surface over the columnar data. The fork bolts a SECOND engine
(DataFusion over Parquet) beside tantivy; here the design is unified —
SQL **compiles onto the same device kernels** the search path runs
(QueryAst predicate → dense masks, GROUP BY → terms/date_histogram
bucket spaces, aggregates → the mergeable metric states), so analytics
inherits the whole distributed substrate: split pruning, fan-out, the
scatter-gather merge tree, caches, and admission. There is no second
storage format to compact and no second executor to schedule.

Dialect (vertical slice):

    SELECT <agg|col|DATE_TRUNC('unit', col)> [AS alias], ...
    FROM <index>
    [WHERE <col op literal> [AND|OR ...] ]
    [GROUP BY <col | DATE_TRUNC('unit', col)> [, ...]]     -- any depth
    [HAVING <agg|alias> <op> <number> [AND ...]]
    [ORDER BY <alias|expr> [ASC|DESC]]
    [LIMIT n] [OFFSET n]

Aggregates: COUNT(*), COUNT(col), COUNT(DISTINCT col) /
APPROX_COUNT_DISTINCT (device HLL cardinality), SUM, AVG, MIN, MAX,
STDDEV, VARIANCE, APPROX_PERCENTILE(col, p) — the last rides the DDSketch percentile
kernels (the fork's sketch UDFs, `quickwit-datafusion/src/sources/
metrics/sketch_udf.rs`). GROUP BY chains compile onto the arbitrary-
depth nested bucket spaces, so N keys = one device pass.
Operators: = != <> < <= > >= ; string/number literals; AND/OR + parens.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional

from ..query import ast as Q

_TRUNC_MICROS = {
    "second": 1_000_000, "minute": 60_000_000, "hour": 3_600_000_000,
    "day": 86_400_000_000, "week": 7 * 86_400_000_000,
}


class SqlError(ValueError):
    pass


# --------------------------------------------------------------------------
# lexer

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<number>-?\d+(?:\.\d+)?)
    | (?P<string>'(?:[^'\\]|\\.)*')
    | (?P<op><=|>=|!=|<>|=|<|>|\(|\)|,|\*)
    | (?P<word>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""", re.VERBOSE)

_KEYWORDS = {"select", "from", "where", "group", "by", "order", "limit",
             "offset", "having", "and", "or", "as", "asc", "desc",
             "count", "sum", "avg", "min", "max", "stddev", "variance",
             "approx_percentile", "approx_count_distinct", "date_trunc",
             "distinct"}


def _tokenize(text: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise SqlError(f"cannot tokenize SQL at {text[pos:pos+20]!r}")
        pos = m.end()
        if m.group("number") is not None:
            out.append(("number", m.group("number")))
        elif m.group("string") is not None:
            out.append(("string",
                        m.group("string")[1:-1].replace("\\'", "'")))
        elif m.group("op") is not None:
            out.append(("op", m.group("op")))
        else:
            word = m.group("word")
            kind = "kw" if word.lower() in _KEYWORDS else "ident"
            out.append((kind, word.lower() if kind == "kw" else word))
    return out


# --------------------------------------------------------------------------
# AST

@dataclass(frozen=True)
class SelectItem:
    kind: str                 # "count_star" | "agg" | "col" | "trunc"
    func: Optional[str] = None
    column: Optional[str] = None
    unit: Optional[str] = None
    alias: Optional[str] = None
    percent: Optional[float] = None   # approx_percentile

    @property
    def name(self) -> str:
        if self.alias:
            return self.alias
        if self.kind == "count_star":
            return "count(*)"
        if self.kind == "agg":
            if self.func == "approx_percentile":
                return f"approx_percentile({self.column}, {self.percent:g})"
            return f"{self.func}({self.column})"
        if self.kind == "trunc":
            return f"date_trunc('{self.unit}', {self.column})"
        return self.column or ""


@dataclass
class SqlQuery:
    index: str
    select: list[SelectItem]
    where: Optional[Q.QueryAst] = None
    group_by: list[SelectItem] = field(default_factory=list)
    order_by: Optional[tuple[str, bool]] = None  # (name, desc)
    having: list[tuple[str, str, float]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.tokens = tokens
        self.pos = 0

    def peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self):
        token = self.peek()
        if token is None:
            raise SqlError("unexpected end of query")
        self.pos += 1
        return token

    def expect(self, kind: str, value: Optional[str] = None):
        token = self.next()
        if token[0] != kind or (value is not None and token[1] != value):
            raise SqlError(f"expected {value or kind}, got {token[1]!r}")
        return token

    def accept(self, kind: str, value: Optional[str] = None) -> bool:
        token = self.peek()
        if token and token[0] == kind and (value is None
                                           or token[1] == value):
            self.pos += 1
            return True
        return False

    # --- grammar -------------------------------------------------------
    def parse(self) -> SqlQuery:
        self.expect("kw", "select")
        select = [self.select_item()]
        while self.accept("op", ","):
            select.append(self.select_item())
        self.expect("kw", "from")
        index = self.expect("ident")[1]
        where = None
        if self.accept("kw", "where"):
            where = self.predicate()
        group_by: list[SelectItem] = []
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_by.append(self.group_key())
            while self.accept("op", ","):
                group_by.append(self.group_key())
        having: list[tuple[str, str, float]] = []
        if self.accept("kw", "having"):
            having.append(self.having_clause())
            while self.accept("kw", "and"):
                having.append(self.having_clause())
        order_by = None
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            name = self.order_target()
            desc = False
            if self.accept("kw", "desc"):
                desc = True
            else:
                self.accept("kw", "asc")
            order_by = (name, desc)
        limit = None
        if self.accept("kw", "limit"):
            limit = int(self.expect("number")[1])
        offset = 0
        if self.accept("kw", "offset"):
            offset = int(self.expect("number")[1])
        if self.peek() is not None:
            raise SqlError(f"unexpected trailing token {self.peek()[1]!r}")
        return SqlQuery(index=index, select=select, where=where,
                        group_by=group_by, order_by=order_by,
                        having=having, limit=limit, offset=offset)

    def having_clause(self) -> tuple[str, str, float]:
        item = self.select_item()
        op = self.expect("op")[1]
        if op not in ("=", "!=", "<>", "<", "<=", ">", ">="):
            raise SqlError(f"unsupported HAVING operator {op!r}")
        value = float(self.expect("number")[1])
        return (item.name, op, value)

    def select_item(self) -> SelectItem:
        token = self.next()
        if token[0] == "kw" and token[1] == "count":
            self.expect("op", "(")
            if self.accept("op", "*"):
                self.expect("op", ")")
                return SelectItem("count_star", alias=self._alias())
            if self.accept("kw", "distinct"):
                # COUNT(DISTINCT col) rides the device HLL cardinality
                # kernel (approximate, like every engine at scale)
                column = self.expect("ident")[1]
                self.expect("op", ")")
                return SelectItem("agg", func="count_distinct",
                                  column=column, alias=self._alias())
            column = self.expect("ident")[1]
            self.expect("op", ")")
            return SelectItem("agg", func="count", column=column,
                              alias=self._alias())
        if token[0] == "kw" and token[1] == "approx_count_distinct":
            self.expect("op", "(")
            column = self.expect("ident")[1]
            self.expect("op", ")")
            return SelectItem("agg", func="count_distinct", column=column,
                              alias=self._alias())
        if token[0] == "kw" and token[1] in ("sum", "avg", "min", "max",
                                             "stddev", "variance"):
            self.expect("op", "(")
            column = self.expect("ident")[1]
            self.expect("op", ")")
            return SelectItem("agg", func=token[1], column=column,
                              alias=self._alias())
        if token[0] == "kw" and token[1] == "approx_percentile":
            self.expect("op", "(")
            column = self.expect("ident")[1]
            self.expect("op", ",")
            percent = float(self.expect("number")[1])
            if not 0 < percent < 100:
                raise SqlError("approx_percentile takes a percent in (0,100)")
            self.expect("op", ")")
            return SelectItem("agg", func="approx_percentile", column=column,
                              percent=percent, alias=self._alias())
        if token[0] == "kw" and token[1] == "date_trunc":
            self.expect("op", "(")
            unit = self.expect("string")[1].lower()
            if unit not in _TRUNC_MICROS:
                raise SqlError(f"unsupported date_trunc unit {unit!r}")
            self.expect("op", ",")
            column = self.expect("ident")[1]
            self.expect("op", ")")
            return SelectItem("trunc", column=column, unit=unit,
                              alias=self._alias())
        if token[0] == "ident":
            return SelectItem("col", column=token[1], alias=self._alias())
        raise SqlError(f"unexpected token {token[1]!r} in SELECT")

    def _alias(self) -> Optional[str]:
        if self.accept("kw", "as"):
            return self.next()[1]
        return None

    def group_key(self) -> SelectItem:
        item = self.select_item()
        if item.kind not in ("col", "trunc"):
            raise SqlError("GROUP BY takes columns or DATE_TRUNC(...)")
        return item

    def order_target(self) -> str:
        # an alias, a bare column, count(*) or fn(col)
        item = self.select_item()
        return item.name

    # --- WHERE ---------------------------------------------------------
    def predicate(self) -> Q.QueryAst:
        left = self.pred_term()
        while True:
            if self.accept("kw", "or"):
                right = self.pred_term()
                left = Q.Bool(should=(left, right), minimum_should_match=1)
            else:
                break
        return left

    def pred_term(self) -> Q.QueryAst:
        left = self.pred_factor()
        while self.accept("kw", "and"):
            right = self.pred_factor()
            left = Q.Bool(must=(left, right))
        return left

    def pred_factor(self) -> Q.QueryAst:
        if self.accept("op", "("):
            inner = self.predicate()
            self.expect("op", ")")
            return inner
        column = self.expect("ident")[1]
        op = self.expect("op")[1]
        kind, literal = self.next()
        if kind not in ("number", "string"):
            raise SqlError(f"expected literal after {op}, got {literal!r}")
        if op == "=":
            return Q.Term(column, str(literal), verbatim=True)
        if op in ("!=", "<>"):
            return Q.Bool(must=(Q.MatchAll(),),
                          must_not=(Q.Term(column, str(literal),
                                           verbatim=True),))
        bound = Q.RangeBound(literal if kind == "string"
                             else float(literal), op in ("<=", ">="))
        if op in (">", ">="):
            return Q.Range(column, lower=bound)
        return Q.Range(column, upper=bound)


def parse_sql(text: str) -> SqlQuery:
    return _Parser(_tokenize(text)).parse()


# --------------------------------------------------------------------------
# compilation onto the search/agg substrate

def _metric_body(item: SelectItem) -> dict:
    if item.kind == "count_star":
        return {}
    if item.func == "count":
        return {"value_count": {"field": item.column}}
    if item.func == "count_distinct":
        return {"cardinality": {"field": item.column}}
    if item.func == "approx_percentile":
        return {"percentiles": {"field": item.column,
                                "percents": [item.percent]}}
    if item.func in ("stddev", "variance"):
        return {"extended_stats": {"field": item.column}}
    return {item.func: {"field": item.column}}


def _metric_value(item: SelectItem, agg_result: dict):
    if item.func == "approx_percentile":
        return (agg_result.get("values") or {}).get(f"{item.percent:g}")
    if item.func == "stddev":
        return agg_result.get("std_deviation")
    if item.func == "variance":
        return agg_result.get("variance")
    return agg_result.get("value")


def execute_sql(text: str, search) -> dict[str, Any]:
    """Parse + compile + run one SQL statement. `search(index_id,
    query_ast, max_hits, aggs)` is the injected search entry (the node's
    root searcher) — analytics rides the full distributed query path.
    Returns {"columns": [...], "rows": [[...], ...]}."""
    from ..query.parser import parse_query_string

    q = parse_sql(text)
    ast = q.where or Q.MatchAll()
    aggregates = [s for s in q.select
                  if s.kind in ("agg", "count_star")]
    plain_cols = [s for s in q.select if s.kind in ("col", "trunc")]

    if q.group_by:
        return _run_grouped(q, ast, aggregates, search)
    if aggregates:
        if plain_cols:
            raise SqlError(
                "non-aggregated columns require GROUP BY")
        return _run_global_aggs(q, ast, aggregates, search)
    if any(s.kind == "trunc" for s in q.select):
        raise SqlError(
            "DATE_TRUNC in a plain projection requires GROUP BY")
    return _run_projection(q, ast, search)


def _agg_requests(aggregates: list[SelectItem]) -> dict:
    """One agg entry per DISTINCT metric body: SELECT STDDEV(x),
    VARIANCE(x) shares one extended_stats kernel; `_agg_key` maps each
    select item to its entry."""
    aggs = {}
    seen: dict[str, str] = {}
    for i, item in enumerate(aggregates):
        if item.kind == "count_star":
            continue  # doc_count / num_hits covers it
        body = _metric_body(item)
        canon = repr(sorted(body.items()))
        if canon not in seen:
            seen[canon] = f"a{i}"
            aggs[f"a{i}"] = body
    return aggs


def _agg_key(aggregates: list[SelectItem], item: SelectItem) -> str:
    canon = repr(sorted(_metric_body(item).items()))
    for i, other in enumerate(aggregates):
        if other.kind != "count_star" and \
                repr(sorted(_metric_body(other).items())) == canon:
            return f"a{i}"
    raise SqlError(f"internal: no agg entry for {item.name!r}")


def _run_global_aggs(q: SqlQuery, ast, aggregates, search):
    response = search(q.index, ast, 0, _agg_requests(aggregates) or None)
    row = []
    for i, item in enumerate(aggregates):
        if item.kind == "count_star":
            row.append(response.num_hits)
        else:
            row.append(_metric_value(
                item, (response.aggregations or {}).get(
                    _agg_key(aggregates, item), {})))
    rows = _apply_having(q, [row])
    return {"columns": [s.name for s in q.select], "rows": rows}


def _group_agg_body(key: SelectItem) -> dict:
    if key.kind == "trunc":
        interval_micros = _TRUNC_MICROS[key.unit]
        body = {"field": key.column,
                "fixed_interval": f"{interval_micros // 1_000_000}s",
                "min_doc_count": 1}
        if key.unit == "week":
            # SQL DATE_TRUNC weeks are Monday-aligned; the Unix epoch is a
            # Thursday, so shift bucket boundaries back 3 days
            body["offset"] = "-3d"
        return {"date_histogram": body}
    return {"terms": {"field": key.column, "size": 65536}}


def _run_grouped(q: SqlQuery, ast, aggregates, search):
    # every selected plain column must be a group key
    group_names = {g.name for g in q.group_by} | \
                  {g.column for g in q.group_by}
    for s in q.select:
        if s.kind in ("col", "trunc") and s.name not in group_names \
                and s.column not in group_names:
            raise SqlError(f"column {s.name!r} must appear in GROUP BY")

    # GROUP BY chain of any length compiles onto one nested bucket tree
    # (arbitrary-depth flattened device bucket spaces); metrics ride the
    # innermost level
    bodies = [_group_agg_body(g) for g in q.group_by]
    bodies[-1]["aggs"] = dict(_agg_requests(aggregates))
    for i in range(len(bodies) - 2, -1, -1):
        bodies[i]["aggs"] = {f"g{i + 1}": bodies[i + 1]}
    response = search(q.index, ast, 0, {"g0": bodies[0]})

    rows: list[list] = []

    def walk(level: int, path: list[dict], container: dict) -> None:
        for bucket in container.get(f"g{level}", {}).get("buckets", []):
            if level + 1 < len(q.group_by):
                walk(level + 1, path + [bucket], bucket)
            else:
                rows.append(_bucket_row(q, path + [bucket], aggregates))

    walk(0, [], response.aggregations or {})
    rows = _apply_having(q, rows)
    rows = _order_and_limit(q, rows)
    return {"columns": [s.name for s in q.select], "rows": rows}


_HAVING_OPS = {
    "=": lambda a, b: a == b, "!=": lambda a, b: a != b,
    "<>": lambda a, b: a != b, "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b, ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def _apply_having(q: SqlQuery, rows: list[list]) -> list[list]:
    if not q.having:
        return rows
    names = [s.name for s in q.select]
    for target, _op, _value in q.having:
        if target not in names:
            raise SqlError(
                f"HAVING target {target!r} must be selected (add it to "
                "the SELECT list, aliased if needed)")
    out = []
    for row in rows:
        keep = True
        for target, op, value in q.having:
            cell = row[names.index(target)]
            try:
                numeric = float(cell) if cell is not None else None
            except (TypeError, ValueError):
                raise SqlError(
                    f"HAVING target {target!r} is not numeric "
                    f"(got {cell!r})")
            if numeric is None or not _HAVING_OPS[op](numeric, value):
                keep = False
                break
        if keep:
            out.append(row)
    return out


def _bucket_key(item: SelectItem, bucket: dict):
    if item.kind == "trunc":
        return bucket.get("key_as_string", bucket.get("key"))
    return bucket.get("key")


def _bucket_row(q: SqlQuery, buckets: list[dict], aggregates):
    inner = buckets[-1]
    row = []
    for s in q.select:
        if s.kind in ("col", "trunc"):
            level = next(i for i, g in enumerate(q.group_by)
                         if g.column == s.column and g.kind == s.kind)
            row.append(_bucket_key(s, buckets[level]))
        elif s.kind == "count_star":
            row.append(inner.get("doc_count"))
        else:
            row.append(_metric_value(
                s, inner.get(_agg_key(aggregates, s), {})))
    return row


def _order_and_limit(q: SqlQuery, rows: list[list]):
    if q.order_by is not None:
        name, desc = q.order_by
        try:
            idx = [s.name for s in q.select].index(name)
        except ValueError:
            raise SqlError(f"ORDER BY target {name!r} is not selected")
        rows.sort(key=lambda r: (r[idx] is None,
                                 r[idx] if r[idx] is not None else 0),
                  reverse=desc)
    if q.offset:
        rows = rows[q.offset:]
    if q.limit is not None:
        rows = rows[: q.limit]
    return rows


def _run_projection(q: SqlQuery, ast, search):
    if q.having:
        raise SqlError("HAVING requires GROUP BY or aggregates")
    limit = q.limit if q.limit is not None else 100
    # fetch offset+limit hits so pagination slices real rows
    response = search(q.index, ast, limit + q.offset, None)
    columns = [s.name for s in q.select]
    rows = []
    for hit in response.hits:
        doc = hit.doc
        row = []
        for s in q.select:
            value: Any = doc
            for part in (s.column or "").split("."):
                value = value.get(part) if isinstance(value, dict) else None
            row.append(value)
        rows.append(row)
    if q.order_by:
        rows = _order_and_limit(q, rows)
    else:
        rows = rows[q.offset: q.offset + limit]
    return {"columns": columns, "rows": rows}
