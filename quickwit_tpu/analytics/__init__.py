from .sql import SqlError, execute_sql, parse_sql

__all__ = ["SqlError", "execute_sql", "parse_sql"]
