"""Split writer: typed docs → one immutable split file.

Role of the reference's indexer hot loop (`quickwit-indexing/src/actors/
indexer.rs` driving tantivy's `IndexWriter` + `Packager`'s hotcache build),
re-targeted at the TPU array layout of `format.py`:

- postings per term are **dense padded int32 arrays** (ids + term freqs),
  padded to POSTING_PAD lanes with `id = num_docs_padded` (an out-of-bounds
  sentinel whose scatter contributions are dropped on device) and `tf = 0`
  (zero BM25 contribution),
- fast fields are dense padded columns with presence masks (numeric) or
  dictionary ordinals (raw text),
- the doc store is zlib block-compressed JSON rows with a block index,
- per-field stats (df, avg field length, min/max) land in the footer so BM25
  and range pruning need no extra reads.
"""

from __future__ import annotations

import json
import os
import zlib
from collections import defaultdict
from typing import Any, Optional

import numpy as np

from ..models.doc_mapper import (
    DocMapper, FieldMapping, FieldType, TypedDoc, canonical_term,
    dynamic_canonical)
from ..utils.datetime_utils import truncate_to_precision
from .format import (
    DOC_PAD, POSTING_PAD, ZONEMAP_BLOCK, SplitFileBuilder, SplitFooter,
    pad_to)
from .impact import IMPACT_BLOCK, IMPACT_BUCKETS, build_impact_arrays

_STORE_BLOCK_BYTES = 64 * 1024
_NUMERIC_TYPES = (FieldType.I64, FieldType.U64, FieldType.F64, FieldType.BOOL,
                  FieldType.DATETIME, FieldType.IP)

# current analyzer generation (v2 = Porter2 en_stem); stamped into split
# footers so stale-analysis splits are detectable at plan time
ANALYZER_VERSION = 2


class _InvertedFieldBuilder:
    """Python-path postings accumulator. TEXT fields with the `default`
    tokenizer go through the native builder (`native/fastindex.cpp`) when it
    is available — see `_NativeInvertedFieldBuilder`."""

    def __init__(self, fm: FieldMapping):
        self.fm = fm
        self.with_positions = fm.record == "position" and fm.type is FieldType.TEXT
        # term -> ([doc_ids], [tfs], [positions])
        self.terms: dict[str, list] = {}
        self.fieldnorms: dict[int, int] = {}   # token count (BM25 doc length)
        self._pos_base: dict[int, int] = {}    # next position base, with gaps
        self.total_tokens = 0

    def add(self, doc_id: int, tokens: list) -> None:
        pos_base = self._pos_base.get(doc_id, 0)
        by_term: dict[str, list[int]] = defaultdict(list)
        for tok in tokens:
            by_term[tok.text].append(pos_base + tok.position)
        for term, positions in by_term.items():
            entry = self.terms.get(term)
            if entry is None:
                entry = self.terms[term] = ([], [], [])
            ids, tfs, poss = entry
            if ids and ids[-1] == doc_id:
                tfs[-1] += len(positions)
                poss[-1].extend(positions)
            else:
                ids.append(doc_id)
                tfs.append(len(positions))
                poss.append(positions)
        ntokens = len(tokens)
        self.fieldnorms[doc_id] = self.fieldnorms.get(doc_id, 0) + ntokens
        # positions of the next value for this doc start after a +1 gap so
        # phrases cannot match across value boundaries (tantivy semantics)
        self._pos_base[doc_id] = pos_base + ntokens + 1
        self.total_tokens += ntokens


class _NativeInvertedFieldBuilder:
    """C++ tokenize+postings path (role of tantivy's native segment writer).
    Buffers raw values and feeds them to fastindex in batches."""

    FLUSH_VALUES = 8192

    def __init__(self, fm: FieldMapping, fastindex):
        self.fm = fm
        self.with_positions = fm.record == "position"
        self.fastindex = fastindex
        self.handle = fastindex.new_builder(self.with_positions)
        self._doc_ids: list[int] = []
        self._texts: list[bytes] = []

    def add_value(self, doc_id: int, value: str) -> None:
        self._doc_ids.append(doc_id)
        self._texts.append(value.encode())
        if len(self._doc_ids) >= self.FLUSH_VALUES:
            self._flush()

    def _flush(self) -> None:
        if not self._doc_ids:
            return
        doc_ids = np.array(self._doc_ids, dtype=np.int32)
        blob = b"".join(self._texts)
        offsets = np.zeros(len(self._texts) + 1, dtype=np.int64)
        np.cumsum([len(t) for t in self._texts], out=offsets[1:])
        self.fastindex.add_values(self.handle, doc_ids.tobytes(), blob,
                                  offsets.tobytes())
        self._doc_ids.clear()
        self._texts.clear()

    def finish(self, num_docs_padded: int) -> dict[str, np.ndarray]:
        self._flush()
        out = self.fastindex.finish(self.handle, num_docs_padded)
        arrays = {
            "terms.blob": np.frombuffer(out[0], dtype=np.uint8),
            "terms.offsets": np.frombuffer(out[1], dtype=np.int64),
            "terms.df": np.frombuffer(out[2], dtype=np.int32),
            "terms.post_off": np.frombuffer(out[3], dtype=np.int64),
            "terms.post_len": np.frombuffer(out[4], dtype=np.int32),
            "postings.ids": np.frombuffer(out[5], dtype=np.int32),
            "postings.tfs": np.frombuffer(out[6], dtype=np.int32),
            "fieldnorm": np.frombuffer(out[7], dtype=np.int32),
        }
        self.total_tokens = int(out[8])
        if self.with_positions:
            arrays["positions.offsets"] = np.frombuffer(out[9], dtype=np.int64)
            arrays["positions.data"] = np.frombuffer(out[10], dtype=np.int32)
        return arrays


def _native_capable(fm: FieldMapping):
    if fm.type is not FieldType.TEXT or fm.tokenizer != "default":
        return None
    from ..native import load_fastindex
    return load_fastindex()


class _DynamicColumnBuilder:
    """Accumulates RAW dynamic leaf values; the split decides the column
    type at finish time (reference: tantivy's dynamic column coercion —
    the columnar side coerces mixed numerics to f64, mixed anything-else
    to strings, which is what makes a `long` observed alongside a
    `double` searchable but not aggregatable)."""

    def __init__(self):
        self.values: dict[int, list[Any]] = {}
        self.classes: set[str] = set()
        self._max_int = 0
        self._min_int = 0

    def add(self, doc_id: int, value: Any) -> None:
        if isinstance(value, bool):
            self.classes.add("boolean")
        elif isinstance(value, int):
            self.classes.add("long")
            self._max_int = max(self._max_int, value)
            self._min_int = min(self._min_int, value)
        elif isinstance(value, float):
            self.classes.add("double")
        else:
            self.classes.add("str")
        self.values.setdefault(doc_id, []).append(value)

    def coerced_type(self) -> FieldType:
        if "str" in self.classes:
            return FieldType.TEXT
        if "double" in self.classes:
            return FieldType.F64
        if "long" in self.classes:
            if self._max_int > (1 << 63) - 1:
                # >i64::MAX alongside a negative value: no integer dtype
                # holds both — coerce to f64 (lossy at the extremes, like
                # the reference's columnar coercion)
                return (FieldType.F64 if self._min_int < 0
                        else FieldType.U64)
            return FieldType.I64
        return FieldType.BOOL

    def to_column(self, tokenizer: str) -> "_ColumnBuilder":
        coerced = self.coerced_type()
        fm = FieldMapping("dynamic", coerced, tokenizer=tokenizer,
                          fast=True, indexed=False)
        col = _ColumnBuilder(fm)
        for doc_id, values in self.values.items():
            for value in values:
                if coerced is FieldType.TEXT:
                    col.add(doc_id, dynamic_canonical(value))
                elif coerced is FieldType.BOOL:
                    col.add(doc_id, 1 if value else 0)
                elif coerced is FieldType.F64:
                    col.add(doc_id, float(value))
                else:
                    col.add(doc_id, int(value))
        return col


class _ColumnBuilder:
    def __init__(self, fm: FieldMapping):
        self.fm = fm
        self.is_numeric = fm.type in _NUMERIC_TYPES
        self.values: dict[int, Any] = {}
        # ordinal (text) columns keep EVERY value: the dense column stores
        # the first (sort substrate), extra values ride in (doc, ordinal)
        # pair arrays for terms aggregations (reference: multivalued fast
        # fields)
        self.multi: dict[int, list] = {}
        # zonemap bounds track EVERY value, not just the first one the
        # dense column keeps — Term/Range matching goes through the
        # inverted index, which indexes all of a doc's values, so
        # first-value-only bounds could prune a split that matches
        self.vmin: Any = None
        self.vmax: Any = None

    def add(self, doc_id: int, value: Any) -> None:
        if not self.is_numeric:
            self.multi.setdefault(doc_id, []).append(value)
        else:
            if self.vmin is None or value < self.vmin:
                self.vmin = value
            if self.vmax is None or value > self.vmax:
                self.vmax = value
        # numeric columns keep the first value (dense single-valued)
        self.values.setdefault(doc_id, value)


class SplitWriter:
    """Accumulates docs, emits the split file bytes + summary stats."""

    def __init__(self, doc_mapper: DocMapper):
        self.doc_mapper = doc_mapper
        self.num_docs = 0
        self._inv: dict[str, Any] = {}
        for fm in doc_mapper.indexed_fields:
            fastindex = _native_capable(fm)
            self._inv[fm.name] = (
                _NativeInvertedFieldBuilder(fm, fastindex) if fastindex
                else _InvertedFieldBuilder(fm))
        self._cols: dict[str, _ColumnBuilder] = {
            fm.name: _ColumnBuilder(fm) for fm in doc_mapper.fast_fields
        }
        self._dyn_cols: dict[str, _DynamicColumnBuilder] = {}
        if doc_mapper.store_document_size:
            # synthetic `_doc_length` fast column (reference
            # store_document_size): serialized byte size per doc
            self._cols["_doc_length"] = _ColumnBuilder(FieldMapping(
                "_doc_length", FieldType.I64, fast=True, indexed=False))
        self._sources: list[bytes] = []
        self._uncompressed_docs_size = 0
        self._time_min: Optional[int] = None
        self._time_max: Optional[int] = None
        self.tags: set[str] = set()
        # filled by finish(): per-field zonemap bounds of the mapped
        # numeric fast columns
        self.column_bounds: dict[str, tuple[Any, Any]] = {}

    def add_json_doc(self, doc: dict[str, Any]) -> int:
        return self.add_typed_doc(self.doc_mapper.doc_from_json(doc))

    def add_typed_doc(self, tdoc: TypedDoc) -> int:
        doc_id = self.num_docs
        self.num_docs += 1
        for field_name, values in tdoc.fields.items():
            fm = self.doc_mapper.field(field_name)
            dynamic = False
            if fm is None:
                if self.doc_mapper.mode != "dynamic":
                    continue
                # dynamic mode: unmapped paths materialize per split with
                # the dynamic_mapping options — raw terms over canonical
                # value strings on the inverted side, a typed column
                # (coerced from the observed value classes) on the fast
                # side (doc_mapper._collect_dynamic_leaves keeps values raw)
                dynamic = True
                fm = self.doc_mapper.dynamic_field(field_name)
                if fm.indexed and field_name not in self._inv:
                    fastindex = _native_capable(fm)
                    self._inv[field_name] = (
                        _NativeInvertedFieldBuilder(fm, fastindex)
                        if fastindex else _InvertedFieldBuilder(fm))
            index_values = ([dynamic_canonical(v) for v in values]
                            if dynamic else values)
            if fm.indexed:
                builder = self._inv[field_name]
                if isinstance(builder, _NativeInvertedFieldBuilder):
                    for value in index_values:
                        builder.add_value(doc_id, value)
                else:
                    for value in index_values:
                        builder.add(doc_id,
                                    self.doc_mapper.tokens_for_field(fm, value))
            if fm.fast:
                if dynamic:
                    dcol = self._dyn_cols.setdefault(
                        field_name, _DynamicColumnBuilder())
                    for value in values:
                        dcol.add(doc_id, value)
                else:
                    col = self._cols[field_name]
                    for value in values:
                        col.add(doc_id, _fast_value(fm, value))
            elif dynamic:
                # no column: still record the observed value classes for
                # the per-split field registry (list_fields / field caps)
                dcol = self._dyn_cols.setdefault(
                    field_name, _DynamicColumnBuilder())
                dcol.classes.update(
                    "boolean" if isinstance(v, bool) else
                    "long" if isinstance(v, int) else
                    "double" if isinstance(v, float) else "str"
                    for v in values)
        ts = tdoc.timestamp_micros(self.doc_mapper.timestamp_field)
        if ts is not None:
            self._time_min = ts if self._time_min is None else min(self._time_min, ts)
            self._time_max = ts if self._time_max is None else max(self._time_max, ts)
        self.tags |= self.doc_mapper.tags(tdoc)
        source = json.dumps(tdoc.source, separators=(",", ":")).encode()
        self._sources.append(source)
        self._uncompressed_docs_size += len(source)
        if "_doc_length" in self._cols:
            # measured over the standard (space-separated) JSON text — the
            # canonical "document as received" size for NDJSON ingestion
            self._cols["_doc_length"].add(
                doc_id, len(json.dumps(tdoc.source)))
        return doc_id

    # ------------------------------------------------------------------
    def finish(self) -> bytes:
        if self.num_docs == 0:
            raise ValueError("cannot finish an empty split")
        num_docs_padded = pad_to(self.num_docs, DOC_PAD)
        builder = SplitFileBuilder()
        fields_meta: dict[str, dict[str, Any]] = {}

        for name, inv in self._inv.items():
            fields_meta[name] = self._write_inverted(builder, name, inv, num_docs_padded)
        for name, col in self._cols.items():
            meta = fields_meta.setdefault(name, {"type": col.fm.type.value})
            meta.update(self._write_column(builder, name, col, num_docs_padded))
        dm_tokenizer = (self.doc_mapper.dynamic_mapping.tokenizer
                        if self.doc_mapper.dynamic_mapping else "raw")
        for name, dcol in self._dyn_cols.items():
            meta = fields_meta.setdefault(name, {})
            meta["dynamic"] = True
            meta["value_classes"] = sorted(dcol.classes)
            if dcol.values:
                col = dcol.to_column(dm_tokenizer)
                meta.setdefault("type", col.fm.type.value)
                meta["col_type"] = col.fm.type.value
                meta.update(self._write_column(builder, name, col, num_docs_padded))
        self._write_docstore(builder)

        # split-granular zonemap: bounds over EVERY value of each
        # explicitly-mapped numeric field (i64/u64/f64 — the only fields
        # the root's constraint extraction consults; dynamic columns and
        # synthetic fields would be metastore dead weight)
        from ..models.doc_mapper import FieldType as _FT
        self.column_bounds = {
            name: (col.vmin, col.vmax)
            for name, col in self._cols.items()
            if col.vmin is not None
            and col.fm.type in (_FT.I64, _FT.U64, _FT.F64)
            # synthetic columns (_doc_length) are not mapped fields: the
            # root never consults them, so publishing their bounds would
            # be per-split metastore dead weight
            and self.doc_mapper.field(name) is not None}

        footer = SplitFooter(
            num_docs=self.num_docs,
            num_docs_padded=num_docs_padded,
            arrays={},
            fields=fields_meta,
            time_range=(self._time_min, self._time_max) if self._time_min is not None else None,
            doc_mapping_uid=self.doc_mapper.doc_mapping_uid,
            extra={"uncompressed_docs_size_bytes": self._uncompressed_docs_size,
                   # bumped whenever a tokenizer's output changes (e.g.
                   # en_stem light-stemmer → Porter2): query-side analysis
                   # must match index-side terms, so a version mismatch at
                   # plan time warns that the split needs reindexing
                   "analyzer_version": ANALYZER_VERSION},
        )
        return builder.finish(footer)

    def _write_inverted(self, builder: SplitFileBuilder, name: str,
                        inv: Any, num_docs_padded: int) -> dict[str, Any]:
        if isinstance(inv, _NativeInvertedFieldBuilder):
            arrays = inv.finish(num_docs_padded)
            # per-term max tf: the BM25 score upper bound's input
            # (search/pruning.py). reduceat over the padded tf arena —
            # pads are 0 and every segment holds >= 1 real posting
            if len(arrays["terms.df"]):
                arrays["terms.max_tf"] = np.maximum.reduceat(
                    arrays["postings.tfs"],
                    arrays["terms.post_off"]).astype(np.int32)
            else:
                arrays["terms.max_tf"] = np.zeros(0, dtype=np.int32)
            avg_len = (inv.total_tokens / self.num_docs) if self.num_docs else 0.0
            impact_meta = apply_impact_ordering(arrays, avg_len,
                                                self.num_docs)
            for suffix, arr in arrays.items():
                builder.add_array(f"inv.{name}.{suffix}", arr)
            num_terms = len(arrays["terms.df"])
            meta = {
                "type": inv.fm.type.value,
                "tokenizer": inv.fm.tokenizer,
                "record": inv.fm.record,
                "indexed": True,
                "num_terms": num_terms,
                "total_tokens": inv.total_tokens,
                "avg_len": avg_len,
                "native": True,
            }
            if impact_meta is not None:
                meta["impact"] = impact_meta
            return meta
        terms_sorted = sorted(inv.terms)
        num_terms = len(terms_sorted)
        blob_parts: list[bytes] = []
        offsets = np.zeros(num_terms + 1, dtype=np.int64)
        dfs = np.zeros(num_terms, dtype=np.int32)
        post_offs = np.zeros(num_terms, dtype=np.int64)
        post_lens = np.zeros(num_terms, dtype=np.int32)
        max_tfs = np.zeros(num_terms, dtype=np.int32)

        total_padded = sum(pad_to(len(inv.terms[t][0]), POSTING_PAD) for t in terms_sorted)
        ids_arena = np.full(total_padded, num_docs_padded, dtype=np.int32)
        tfs_arena = np.zeros(total_padded, dtype=np.int32)
        pos_offsets = np.zeros(total_padded + 1, dtype=np.int64) if inv.with_positions else None
        pos_chunks: list[list[int]] = []

        cursor = 0
        blob_len = 0
        pos_cursor = 0
        for t_idx, term in enumerate(terms_sorted):
            encoded = term.encode()
            blob_parts.append(encoded)
            blob_len += len(encoded)
            offsets[t_idx + 1] = blob_len
            ids, tfs, poss = inv.terms[term]
            df = len(ids)
            padded = pad_to(df, POSTING_PAD)
            dfs[t_idx] = df
            post_offs[t_idx] = cursor
            post_lens[t_idx] = padded
            ids_arena[cursor:cursor + df] = ids
            tfs_arena[cursor:cursor + df] = tfs
            max_tfs[t_idx] = max(tfs) if df else 0
            if pos_offsets is not None:
                for i, doc_positions in enumerate(poss):
                    pos_offsets[cursor + i] = pos_cursor
                    pos_chunks.append(doc_positions)
                    pos_cursor += len(doc_positions)
                pos_offsets[cursor + df: cursor + padded + 1] = pos_cursor
            cursor += padded

        norms = np.zeros(num_docs_padded, dtype=np.int32)
        for doc_id, length in inv.fieldnorms.items():
            norms[doc_id] = length

        arrays = {
            "terms.blob": np.frombuffer(b"".join(blob_parts), dtype=np.uint8),
            "terms.offsets": offsets,
            "terms.df": dfs,
            "terms.post_off": post_offs,
            "terms.post_len": post_lens,
            "terms.max_tf": max_tfs,
            "postings.ids": ids_arena,
            "postings.tfs": tfs_arena,
        }
        if pos_offsets is not None:
            arrays["positions.offsets"] = pos_offsets
            arrays["positions.data"] = np.array(
                [p for chunk in pos_chunks for p in chunk], dtype=np.int32)
        arrays["fieldnorm"] = norms
        avg_len = (inv.total_tokens / self.num_docs) if self.num_docs else 0.0
        impact_meta = apply_impact_ordering(arrays, avg_len, self.num_docs)
        for suffix, arr in arrays.items():
            builder.add_array(f"inv.{name}.{suffix}", arr)

        meta = {
            "type": inv.fm.type.value,
            "tokenizer": inv.fm.tokenizer,
            "record": inv.fm.record,
            "indexed": True,
            "num_terms": num_terms,
            "total_tokens": inv.total_tokens,
            "avg_len": avg_len,
        }
        if impact_meta is not None:
            meta["impact"] = impact_meta
        return meta

    def _write_column(self, builder: SplitFileBuilder, name: str,
                      col: _ColumnBuilder, num_docs_padded: int) -> dict[str, Any]:
        present = np.zeros(num_docs_padded, dtype=np.uint8)
        doc_ids = np.fromiter(col.values.keys(), dtype=np.int64, count=len(col.values))
        present[doc_ids] = 1
        if col.is_numeric:
            # u64 columns hold values above i64::MAX (the reference
            # dynamically types >2^63 values as u64); everything else is i64
            dtype = (np.float64 if col.fm.type is FieldType.F64
                     else np.uint64 if col.fm.type is FieldType.U64
                     else np.int64)
            values = np.zeros(num_docs_padded, dtype=dtype)
            vals = np.fromiter(col.values.values(), dtype=dtype, count=len(col.values))
            values[doc_ids] = vals
            meta = {
                "fast": True, "column_kind": "numeric",
                "min_value": (vals.min().item() if len(vals) else None),
                "max_value": (vals.max().item() if len(vals) else None),
            }
            packed = _pack_numeric(col.fm.type, vals)
            if packed is not None:
                # frame-of-reference layout: the narrow delta lanes REPLACE
                # the full-width values array on disk and in HBM; the reader
                # reconstructs full-width views host-side on demand
                deltas, for_min, for_scale, bit_width = packed
                lanes = np.zeros(num_docs_padded, dtype=deltas.dtype)
                lanes[doc_ids] = deltas
                builder.add_array(f"col.{name}.packed", lanes)
                meta["packed"] = {"for_min": for_min, "for_scale": for_scale,
                                  "bit_width": bit_width}
                zdomain = lanes.astype(np.int32)
            else:
                builder.add_array(f"col.{name}.values", values)
                zdomain = values
            builder.add_array(f"col.{name}.present", present)
            zmin, zmax = _column_zonemaps(zdomain, present)
            builder.add_array(f"col.{name}.zmin", zmin)
            builder.add_array(f"col.{name}.zmax", zmax)
            meta["zonemap_block"] = ZONEMAP_BLOCK
            return meta
        # dictionary-encoded raw text column (terms-agg substrate)
        all_values = col.multi if col.multi else {
            d: [v] for d, v in col.values.items()}
        uniques = sorted({str(v) for vs in all_values.values() for v in vs})
        ordinal_of = {term: i for i, term in enumerate(uniques)}
        ordinals = np.full(num_docs_padded, -1, dtype=np.int32)
        for doc_id, value in col.values.items():
            ordinals[doc_id] = ordinal_of[str(value)]
        blob = "".join(uniques).encode()
        dict_offsets = np.zeros(len(uniques) + 1, dtype=np.int64)
        acc = 0
        for i, term in enumerate(uniques):
            acc += len(term.encode())
            dict_offsets[i + 1] = acc
        builder.add_array(f"col.{name}.ordinals", ordinals)
        builder.add_array(f"col.{name}.dict_blob", np.frombuffer(blob, dtype=np.uint8))
        builder.add_array(f"col.{name}.dict_offsets", dict_offsets)
        meta = {"fast": True, "column_kind": "ordinal",
                "cardinality": len(uniques)}
        if any(len(vs) > 1 for vs in all_values.values()):
            # multivalued: (doc, ordinal) pair arrays, one pair per DISTINCT
            # value per doc (ES terms aggs count a doc once per term).
            # Padding: doc 0 with ordinal -1 — excluded on device by the
            # ordinal>=0 test without out-of-bounds gathers.
            pair_docs: list[int] = []
            pair_ords: list[int] = []
            for doc_id in sorted(all_values):
                seen: set[str] = set()
                for value in all_values[doc_id]:
                    text = str(value)
                    if text in seen:
                        continue
                    seen.add(text)
                    pair_docs.append(doc_id)
                    pair_ords.append(ordinal_of[text])
            padded = pad_to(max(len(pair_docs), 1), POSTING_PAD)
            docs_arr = np.zeros(padded, dtype=np.int32)
            ords_arr = np.full(padded, -1, dtype=np.int32)
            docs_arr[:len(pair_docs)] = pair_docs
            ords_arr[:len(pair_ords)] = pair_ords
            builder.add_array(f"col.{name}.mv_docs", docs_arr)
            builder.add_array(f"col.{name}.mv_ords", ords_arr)
            meta["multivalued"] = True
        return meta

    def _write_docstore(self, builder: SplitFileBuilder) -> None:
        blocks: list[bytes] = []
        block_first_doc = [0]
        block_offsets = [0]
        current: list[bytes] = []
        current_size = 0
        for doc_id, source in enumerate(self._sources):
            current.append(source)
            current_size += len(source) + 1
            if current_size >= _STORE_BLOCK_BYTES:
                blocks.append(zlib.compress(b"\n".join(current), 1))
                block_offsets.append(block_offsets[-1] + len(blocks[-1]))
                block_first_doc.append(doc_id + 1)
                current, current_size = [], 0
        if current:
            blocks.append(zlib.compress(b"\n".join(current), 1))
            block_offsets.append(block_offsets[-1] + len(blocks[-1]))
            block_first_doc.append(self.num_docs)
        builder.add_array("store.data", np.frombuffer(b"".join(blocks), dtype=np.uint8))
        builder.add_array("store.block_offsets", np.array(block_offsets, dtype=np.int64))
        builder.add_array("store.block_first_doc", np.array(block_first_doc, dtype=np.int32))


def _packing_enabled() -> bool:
    """Kill switch for A/B comparisons and bug triage: QW_DISABLE_PACKED=1
    writes raw full-width numeric columns (the v1 layout, still under a v2
    footer). Read per call so tests can flip it between splits."""
    return os.environ.get("QW_DISABLE_PACKED", "0") != "1"


def _impact_enabled() -> bool:
    """Kill switch mirroring `_packing_enabled`: QW_DISABLE_IMPACT=1 keeps
    postings doc-ordered with no impact arrays (the v2 layout under a v3
    footer) — the comparator for the impact equivalence suite and bench."""
    return os.environ.get("QW_DISABLE_IMPACT", "0") != "1"


def apply_impact_ordering(arrays: dict[str, np.ndarray], avg_len: float,
                          num_docs: int) -> Optional[dict[str, Any]]:
    """Impact-order one inverted field's posting arenas in place of the
    doc-ordered ones and attach the v3 `impact.*` arrays. `arrays` uses the
    writer's suffix keys (`postings.ids`, `terms.df`, ...); mutated in
    place. Returns the field-meta impact descriptor, or None when the field
    keeps doc order (kill switch, positions recorded, or no terms).

    Shared by the initial write (`_write_inverted`) and the merge path
    (`merge_arrays._merge_inverted`), so merged splits re-quantize against
    their merged df/fieldnorm/avg_len instead of inheriting stale scales.
    """
    if (not _impact_enabled() or "positions.offsets" in arrays
            or not len(arrays["terms.df"])):
        return None
    ids, tfs, quant, bmax, scales = build_impact_arrays(
        arrays["postings.ids"], arrays["postings.tfs"],
        arrays["terms.post_off"], arrays["terms.df"],
        arrays["fieldnorm"], avg_len, num_docs)
    arrays["postings.ids"] = ids
    arrays["postings.tfs"] = tfs
    arrays["impact.quant"] = quant
    arrays["impact.bmax"] = bmax
    arrays["impact.scale"] = scales
    return {"buckets": IMPACT_BUCKETS, "block": IMPACT_BLOCK,
            "ordered": True}


def _pack_numeric(field_type: FieldType, vals: np.ndarray):
    """Frame-of-reference packing decision for one numeric column.

    value = for_min + delta * for_scale, deltas stored in the narrowest
    unsigned lane (u8/u16/u32). for_scale is the GCD of the deltas — it
    collapses quantized domains (whole-second datetime micros scale by
    1e6, all-equal columns collapse to u8 zeros). The scaled span is
    capped just below 2^31 so kernels compare deltas in i32 and the host
    can express a never-matching rebased bound (span+1) in the same
    domain. f64 columns and wider-span integer columns keep the raw
    full-width layout (the high-dynamic-range fallback).

    Returns (deltas, for_min, for_scale, bit_width) or None for raw.
    """
    if not _packing_enabled() or field_type is FieldType.F64 or not len(vals):
        return None
    for_min = int(vals.min())
    span = int(vals.max()) - for_min
    if span >= (1 << 62):  # delta subtraction below must not overflow i64
        return None
    deltas = (vals - vals.dtype.type(for_min)).astype(np.uint64)
    for_scale = int(np.gcd.reduce(deltas)) or 1
    if for_scale > 1:
        deltas //= np.uint64(for_scale)
    span_scaled = span // for_scale
    if span_scaled <= 0xFF:
        bit_width = 8
    elif span_scaled <= 0xFFFF:
        bit_width = 16
    elif span_scaled <= (1 << 31) - 2:
        bit_width = 32
    else:
        return None
    lane = {8: np.uint8, 16: np.uint16, 32: np.uint32}[bit_width]
    return deltas.astype(lane), for_min, for_scale, bit_width


def _column_zonemaps(values: np.ndarray, present: np.ndarray):
    """Per-ZONEMAP_BLOCK-doc min/max over PRESENT values, in the on-disk
    domain of the column (scaled i32 deltas for packed columns, raw values
    otherwise). Blocks with no present docs get inverted sentinels
    (zmin > zmax where the dtype allows) so range predicates skip them."""
    nb = values.shape[0] // ZONEMAP_BLOCK
    v = values.reshape(nb, ZONEMAP_BLOCK)
    p = present.reshape(nb, ZONEMAP_BLOCK).astype(bool)
    if values.dtype.kind == "f":
        lo_sent, hi_sent = -np.inf, np.inf
    else:
        info = np.iinfo(values.dtype)
        lo_sent, hi_sent = info.min, info.max
    zmin = np.where(p, v, hi_sent).min(axis=1).astype(values.dtype)
    zmax = np.where(p, v, lo_sent).max(axis=1).astype(values.dtype)
    return zmin, zmax


def _fast_value(fm: FieldMapping, value: Any):
    if fm.type is FieldType.BOOL:
        return 1 if value else 0
    if fm.type is FieldType.DATETIME:
        return truncate_to_precision(int(value), fm.fast_precision)
    if fm.type in (FieldType.I64, FieldType.U64, FieldType.IP):
        return int(value)
    if fm.type is FieldType.F64:
        return float(value)
    if fm.type is FieldType.TEXT:
        text = str(value)
        # reference: `fast: {normalizer: lowercase}` — the fast column
        # (terms aggs, fast-field reads) observes the normalized form
        return text.lower() if fm.normalizer == "lowercase" else text
    return canonical_term(fm, value)
