from .format import SplitFooter, ArrayMeta, MAGIC, read_footer, DOC_PAD, POSTING_PAD
from .writer import SplitWriter
from .reader import SplitReader

__all__ = [
    "SplitWriter", "SplitReader", "SplitFooter", "ArrayMeta", "MAGIC",
    "read_footer", "DOC_PAD", "POSTING_PAD",
]
